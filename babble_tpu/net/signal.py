"""Signal/relay transport: gossip for NAT-ed nodes via a rendezvous server.

This is the framework's analogue of the reference's WebRTC stack
(src/net/webrtc_stream_layer.go + src/net/signal/ + signal/wamp/): there,
nodes register with a WAMP signaling router under their public key, exchange
SDP offers through it, and then speak over pion data channels. Here the same
topology is collapsed into one component: every node keeps a single
OUTBOUND TCP connection to a relay server, registers under its public key,
and all four consensus RPCs (Sync/EagerSync/FastForward/Join,
src/net/transport.go:5-35) are routed server-side by target public key.
Like TURN-relayed WebRTC, no node ever accepts an inbound connection, so
nodes behind NAT/firewalls can participate symmetrically.

Wire format: 4-byte big-endian length + JSON (canonical codec, bytes as
base64). Client -> server first frame registers; after that frames carry
{"to", "ch", "kind": "req"|"resp", "t": <rpc type byte>, "body", "error"}
and the server stamps "from" before forwarding.

Security: registration is challenge-response (the server only routes a
public key to a client that signs the server's nonce with it), and the
relay link itself can run over TLS — pass ``cert_file``/``key_file`` to
SignalServer and ``ca_file`` (or ``tls=True`` for system roots) to
SignalTransport. This matches the reference's WAMP signaling posture
(WSS + TLS with self-signed certs distributed out of band,
src/net/signal/wamp/client.go:24-120, wamp/wamp.go:1-19).

**Direct-connection upgrade** (``direct_listen=...``): the relay is then
only the SIGNALING plane, like the reference's WAMP router — nodes
exchange direct endpoints through it (the SDP offer/answer analogue,
src/net/webrtc_stream_layer.go:181-236) and upgrade to an authenticated
peer-to-peer TCP link; all subsequent gossip RPCs ride that link and the
relay is reduced to a fallback path (it keeps carrying traffic for pairs
whose direct connect fails, e.g. symmetric NATs — the TURN posture). A
direct link is mutually authenticated by a two-nonce challenge-response
(each side signs the other's nonce), so neither endpoint trusts an
unproven claim to a public key. Once upgraded, gossip keeps committing
even if the relay dies (tests/test_signal_direct.py pins this).

Threading note (TLS): each socket has exactly ONE reader thread, and all
writers serialize on the per-socket lock — i.e. at most one SSL_read and
one SSL_write run concurrently on an SSL object, the classic
reader+writer split OpenSSL >= 1.1.0 supports with its per-SSL locking.
A rare mid-read KeyUpdate colliding with a write can still surface as an
SSLError; both sides already treat any socket error as a dropped relay
link (client reconnects with backoff, server unregisters the client), so
the failure mode is a clean reconnect, not corruption.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import socket
import ssl
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from ..crypto.canonical import canonical_dumps
from ..crypto.hashing import sha256
from .rpc import (
    JoinRequest,
    REQUEST_TYPES,
    RESPONSE_TYPES,
    RPC,
    TYPE_OF_REQUEST,
)
from .tcp import _recv_exact
from .transport import TransportError

logger = logging.getLogger(__name__)


def _recv_frame(sock: socket.socket) -> dict:
    (length,) = struct.unpack(">I", _recv_exact(sock, 4))
    frame = json.loads(_recv_exact(sock, length))
    if not isinstance(frame, dict):
        raise ValueError("frame is not an object")
    return frame


def _send_frame(sock: socket.socket, obj: dict, lock: threading.Lock,
                timeout: Optional[float] = None) -> None:
    """Framed send; with ``timeout`` the whole write is bounded (the
    timeout is set inside the per-socket lock so concurrent writers never
    race the setting — used by the relay to drop jammed destinations)."""
    payload = canonical_dumps(obj)
    with lock:
        if timeout is not None:
            sock.settimeout(timeout)
            try:
                sock.sendall(struct.pack(">I", len(payload)) + payload)
            finally:
                sock.settimeout(None)
        else:
            sock.sendall(struct.pack(">I", len(payload)) + payload)


class SignalServer:
    """Rendezvous/relay router keyed by public key
    (reference: src/net/signal/wamp/server.go:18-98)."""

    def __init__(self, bind_addr: str, cert_file: Optional[str] = None,
                 key_file: Optional[str] = None,
                 send_timeout: float = 10.0):
        """``cert_file``/``key_file``: optional PEM pair; when given, every
        client connection is wrapped in TLS (reference posture:
        wamp/server.go serves WSS with a provided cert).

        ``send_timeout``: forwarding to a destination that has stopped
        draining its socket times out and DROPS that destination instead
        of wedging the sender's relay thread — without it one dead reader
        head-of-line-blocks every peer that gossips to it."""
        self._bind_addr = bind_addr
        self._send_timeout = send_timeout
        self._listener: Optional[socket.socket] = None
        self._clients: Dict[str, Tuple[socket.socket, threading.Lock]] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if cert_file:
            self._ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            self._ssl_ctx.load_cert_chain(cert_file, key_file)

    def listen(self) -> str:
        host, port_s = self._bind_addr.rsplit(":", 1)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port_s)))
        srv.listen(64)
        self._listener = srv
        if int(port_s) == 0:
            self._bind_addr = f"{host}:{srv.getsockname()[1]}"
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self._bind_addr

    def addr(self) -> str:
        return self._bind_addr

    def close(self) -> None:
        self._shutdown.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            for sock, _ in self._clients.values():
                try:
                    sock.close()
                except OSError:
                    pass
            self._clients.clear()

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handshake_and_serve, args=(conn,), daemon=True
            ).start()

    def _handshake_and_serve(self, conn: socket.socket) -> None:
        if self._ssl_ctx is not None:
            try:
                conn.settimeout(10.0)
                conn = self._ssl_ctx.wrap_socket(conn, server_side=True)
                conn.settimeout(None)
            except (ssl.SSLError, OSError, ConnectionError):
                try:
                    conn.close()
                except OSError:
                    pass
                return
        self._serve_client(conn)

    def _serve_client(self, conn: socket.socket) -> None:
        pub: Optional[str] = None
        wlock = threading.Lock()
        try:
            # Challenge-response registration: a client only gets routed
            # under a public key it can sign for, so identities cannot be
            # hijacked by merely claiming a key (the reference's WAMP
            # signaling authenticates with TLS + tickets the same way).
            nonce = os.urandom(32)
            _send_frame(conn, {"challenge": nonce.hex()}, wlock)
            hello = _recv_frame(conn)
            pub = hello.get("register")
            if not pub or not self._check_registration(
                pub, nonce, hello.get("sig", "")
            ):
                conn.close()
                return
            with self._lock:
                old = self._clients.get(pub)
                self._clients[pub] = (conn, wlock)
            if old is not None:
                # tell the displaced client it was replaced (not a server
                # crash) so it backs off instead of kicking back instantly
                try:
                    _send_frame(old[0], {"kind": "displaced"}, old[1])
                except (OSError, ConnectionError):
                    pass
                try:
                    old[0].close()
                except OSError:
                    pass
            while not self._shutdown.is_set():
                frame = _recv_frame(conn)
                frame["from"] = pub
                target = frame.pop("to", None)
                with self._lock:
                    dest = self._clients.get(target)
                delivered = False
                if dest is not None:
                    try:
                        # bounded send: a full kernel buffer (dest stopped
                        # reading) must drop the dest, not wedge this
                        # sender's relay thread
                        _send_frame(dest[0], frame, dest[1],
                                    timeout=self._send_timeout)
                        delivered = True
                    except (OSError, ConnectionError):
                        # the DESTINATION is dead or jammed — drop it, not
                        # the sender
                        with self._lock:
                            if self._clients.get(target, (None,))[0] is dest[0]:
                                del self._clients[target]
                        try:
                            dest[0].close()
                        except OSError:
                            pass
                if not delivered and frame.get("kind") == "req":
                    _send_frame(
                        conn,
                        {
                            "from": target or "",
                            "ch": frame.get("ch"),
                            "kind": "resp",
                            "error": f"unreachable peer {target}",
                            "body": None,
                            "t": frame.get("t"),
                        },
                        wlock,
                    )
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with self._lock:
                if pub is not None and self._clients.get(pub, (None,))[0] is conn:
                    del self._clients[pub]
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _check_registration(pub: str, nonce: bytes, sig: str) -> bool:
        try:
            from ..crypto.keys import PublicKey

            return PublicKey.from_hex(pub).verify(sha256(nonce), sig)
        except Exception:
            return False


def _direct_transcript(role: bytes, nonce_l: bytes, nonce_c: bytes,
                       signer_pub: str, counterparty_pub: str) -> bytes:
    """Channel-binding transcript for the direct-link handshake: the
    signature covers both nonces, the signer's key, AND the counterparty
    the signer believes it is talking to. Without the counterparty
    binding, a registered attacker could relay challenge/response pairs
    between a victim listener and an honest connector and have the victim
    adopt a link under the honest peer's identity (signature-relay MITM);
    with it, a relayed signature names the wrong counterparty and fails
    verification."""
    return sha256(
        b"babble-direct|" + role + b"|" + nonce_l + b"|" + nonce_c
        + b"|" + signer_pub.encode() + b"|" + counterparty_pub.encode()
    )


class _DirectLink:
    """One mutually-authenticated framed TCP connection to a peer — the
    data plane after a relay-signaled upgrade (the pion data-channel
    analogue, webrtc_stream_layer.go:181-236)."""

    __slots__ = ("sock", "wlock", "peer")

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.wlock = threading.Lock()
        self.peer = peer

    def send(self, frame: dict) -> None:
        _send_frame(self.sock, frame, self.wlock)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class SignalTransport:
    """Transport over a relay server; the local address IS the public key
    (the reference keys WebRTC connections by pubkey the same way,
    webrtc_stream_layer.go:16-30)."""

    @staticmethod
    def _norm(pub: str) -> str:
        """Normalize a pubkey address ('0X...' or bare hex) to lowercase
        hex so registration and routing always agree."""
        return (pub[2:] if pub[:2].upper() == "0X" else pub).lower()

    def __init__(
        self,
        server_addr: str,
        key,
        timeout: float = 5.0,
        join_timeout: float = 30.0,
        tls: bool = False,
        ca_file: Optional[str] = None,
        direct_listen: Optional[str] = None,
    ):
        """``key`` is the node's PrivateKey: registration must answer the
        server's challenge with a signature over it. ``ca_file`` (or
        ``tls=True`` for system roots) wraps the relay link in TLS —
        self-signed relay certs are distributed out of band, like the
        reference's WAMP cert notes (wamp/wamp.go:1-19)."""
        self._server_addr = server_addr
        self._key = key
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if tls or ca_file:
            self._ssl_ctx = ssl.create_default_context(cafile=ca_file)
            if ca_file:
                # self-signed relay cert: trust the pinned cert, match by
                # key not hostname
                self._ssl_ctx.check_hostname = False
        self._pub = self._norm(key.public_key.hex())
        self._timeout = timeout
        self._join_timeout = max(join_timeout, timeout)
        self._consumer: "queue.Queue[RPC]" = queue.Queue()
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        # ch -> (expected responder pubkey, response queue)
        self._pending: Dict[int, Tuple[str, "queue.Queue[dict]"]] = {}
        self._plock = threading.Lock()
        self._next_ch = 0
        self._shutdown = threading.Event()
        # Direct-connection upgrade (``direct_listen`` e.g. "0.0.0.0:0"):
        # relay becomes signaling-only once a pair upgrades.
        self._direct_listen = direct_listen
        self._direct_listener: Optional[socket.socket] = None
        self._direct_addr: Optional[str] = None
        self._direct: Dict[str, _DirectLink] = {}  # peer pub -> link
        self._dlock = threading.Lock()
        self._offered: set = set()  # peers we already offered to
        self._dialing: set = set()  # peers with a dial in flight
        self._fallback_waiting: set = set()  # larger-side grace timers

    # -- Transport interface -------------------------------------------------

    def consumer(self) -> "queue.Queue[RPC]":
        return self._consumer

    def local_addr(self) -> str:
        return self._pub

    def advertise_addr(self) -> str:
        return self._pub

    def _connect(self) -> socket.socket:
        host, port_s = self._server_addr.rsplit(":", 1)
        sock = socket.create_connection((host, int(port_s)), timeout=5.0)
        if self._ssl_ctx is not None:
            sock = self._ssl_ctx.wrap_socket(sock, server_hostname=host)
        sock.settimeout(10.0)
        challenge = _recv_frame(sock)
        nonce = bytes.fromhex(challenge.get("challenge", ""))
        sig = self._key.sign(sha256(nonce))
        _send_frame(sock, {"register": self._pub, "sig": sig}, self._wlock)
        sock.settimeout(None)
        return sock

    def listen(self) -> None:
        if self._sock is not None:
            return
        try:
            self._sock = self._connect()
        except (OSError, ValueError, ConnectionError) as err:
            raise TransportError(
                f"cannot reach signal server {self._server_addr}: {err}"
            ) from err
        threading.Thread(target=self._read_loop, daemon=True).start()
        if self._direct_listen:
            host, port_s = self._direct_listen.rsplit(":", 1)
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((host or "0.0.0.0", int(port_s)))
            srv.listen(16)
            self._direct_listener = srv
            self._direct_addr = f"{host or '127.0.0.1'}:{srv.getsockname()[1]}"
            threading.Thread(
                target=self._direct_accept_loop, daemon=True
            ).start()

    def close(self) -> None:
        self._shutdown.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._direct_listener is not None:
            try:
                self._direct_listener.close()
            except OSError:
                pass
            self._direct_listener = None
        with self._dlock:
            links, self._direct = list(self._direct.values()), {}
        for link in links:
            link.close()

    # -- direct upgrade ------------------------------------------------------

    def _direct_accept_loop(self) -> None:
        assert self._direct_listener is not None
        while not self._shutdown.is_set():
            try:
                conn, _ = self._direct_listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._direct_handshake_in, args=(conn,), daemon=True
            ).start()

    def _direct_handshake_in(self, conn: socket.socket) -> None:
        """Accepting side of the two-nonce mutual auth: challenge the
        connector, verify its channel-bound signature (it must name US as
        the counterparty), then prove our own key over the full
        transcript."""
        from ..crypto.keys import PublicKey

        wlock = threading.Lock()
        try:
            conn.settimeout(10.0)
            nonce = os.urandom(32)
            _send_frame(conn, {"challenge": nonce.hex()}, wlock)
            hello = _recv_frame(conn)
            peer = self._norm(hello.get("register") or "")
            their_nonce = bytes.fromhex(hello.get("nonce", ""))
            ok = False
            if peer and len(their_nonce) == 32:
                try:
                    ok = PublicKey.from_hex(peer).verify(
                        _direct_transcript(
                            b"connect", nonce, their_nonce, peer, self._pub
                        ),
                        hello.get("sig", ""),
                    )
                except Exception:
                    ok = False
            if not ok:
                conn.close()
                return
            _send_frame(
                conn,
                {
                    "register": self._pub,
                    "sig": self._key.sign(
                        _direct_transcript(
                            b"accept", nonce, their_nonce, self._pub, peer
                        )
                    ),
                },
                wlock,
            )
            conn.settimeout(None)
        except (OSError, ConnectionError, ValueError):
            try:
                conn.close()
            except OSError:
                pass
            return
        self._adopt_link(_DirectLink(conn, peer))

    def _direct_connect(self, peer: str, addr: str) -> None:
        """Connecting side: authenticate ourselves against the listener's
        challenge — the signature names ``peer`` as the counterparty, so
        it is useless to anyone else — and verify the listener proves
        ``peer``'s key over the same transcript (an endpoint learned
        through the relay is a claim, not a proof)."""
        from ..crypto.keys import PublicKey

        conn = None
        try:
            host, port_s = addr.rsplit(":", 1)
            conn = socket.create_connection((host, int(port_s)), timeout=5.0)
            conn.settimeout(10.0)
            wlock = threading.Lock()
            challenge = _recv_frame(conn)
            nonce = bytes.fromhex(challenge.get("challenge", ""))
            my_nonce = os.urandom(32)
            _send_frame(
                conn,
                {
                    "register": self._pub,
                    "sig": self._key.sign(
                        _direct_transcript(
                            b"connect", nonce, my_nonce, self._pub, peer
                        )
                    ),
                    "nonce": my_nonce.hex(),
                },
                wlock,
            )
            proof = _recv_frame(conn)
            ok = self._norm(proof.get("register") or "") == peer
            if ok:
                try:
                    ok = PublicKey.from_hex(peer).verify(
                        _direct_transcript(
                            b"accept", nonce, my_nonce, peer, self._pub
                        ),
                        proof.get("sig", ""),
                    )
                except Exception:
                    ok = False
            if not ok:
                conn.close()
                self._rearm_offer(peer)
                return
            conn.settimeout(None)
        except (OSError, ConnectionError, ValueError):
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
            self._rearm_offer(peer)
            return
        self._adopt_link(_DirectLink(conn, peer))

    def _should_dial(self, peer: str) -> bool:
        """Deterministic cross-dial tie-break: of any pair, only the
        lexicographically SMALLER pubkey dials; the larger side answers
        with its endpoint (so the smaller learns where to dial) and waits
        for the inbound handshake. Without this both sides dial on a
        simultaneous offer/answer exchange, each end adopts a DIFFERENT
        crossing socket, and latest-wins replacement can close the link
        the other side is still using (~1/3 flake in
        test_rpc_upgrades_to_direct_link). Both strings are _norm()ed
        lowercase hex, so the comparison agrees on both ends."""
        return self._pub < peer

    def _dial_direct(self, peer: str, addr: str) -> None:
        """_direct_connect with the in-flight bookkeeping the offer
        handler uses to avoid concurrent duplicate dials to one peer."""
        try:
            self._direct_connect(peer, addr)
        finally:
            with self._dlock:
                self._dialing.discard(peer)

    #: how long the larger pubkey waits for the deterministic (smaller)
    #: dialer before trying the reverse direction itself. This must sit
    #: well above worst-case handshake latency — on a loaded single-core
    #: host a HEALTHY smaller-side dial can stall for seconds (GIL
    #: starvation), and a premature fallback resurrects exactly the
    #: crossing-socket churn the tie-break removed. One-sided
    #: reachability recovery is an escape hatch, not a hot path; paying
    #: ten seconds once per affected pair is fine.
    FALLBACK_DIAL_GRACE_S = 10.0

    #: Retry budget after the grace window: a SINGLE fallback dial was
    #: the test_signal_direct flake — under full-suite load one dial (or
    #: its handshake frames) can fail transiently, and with the one shot
    #: spent the pair could only re-upgrade on the NEXT offer, which a
    #: single-RPC test never sends. A few spaced attempts make the
    #: escape hatch robust to scheduler noise without resurrecting the
    #: crossing-socket churn (each attempt still checks for a live link
    #: first).
    FALLBACK_DIAL_ATTEMPTS = 3
    FALLBACK_DIAL_RETRY_S = 1.0

    def _fallback_dial(self, peer: str, addr: str) -> None:
        """One-sided-reachability escape hatch for the non-dialing
        (larger) side: if no link materializes within the grace window —
        i.e. the smaller peer's deterministic dial is failing, e.g.
        against our NAT'd endpoint — dial the peer's advertised address
        ourselves, retrying a bounded number of times. Crossing sockets
        are only possible when the smaller dial is genuinely
        slow/failing, and latest-wins adoption resolves that rare
        overlap."""
        deadline = time.monotonic() + self.FALLBACK_DIAL_GRACE_S
        try:
            while time.monotonic() < deadline:
                if self._shutdown.is_set():
                    return
                with self._dlock:
                    if peer in self._direct:
                        return
                time.sleep(0.1)
            for attempt in range(self.FALLBACK_DIAL_ATTEMPTS):
                if self._shutdown.is_set():
                    return
                with self._dlock:
                    if peer in self._direct:
                        return
                    if peer in self._dialing:
                        # the deterministic dialer finally reached us —
                        # let its handshake finish rather than racing it
                        return
                    self._dialing.add(peer)
                self._dial_direct(peer, addr)
                with self._dlock:
                    if peer in self._direct:
                        return
                time.sleep(self.FALLBACK_DIAL_RETRY_S)
        finally:
            with self._dlock:
                self._fallback_waiting.discard(peer)

    def _rearm_offer(self, peer: str) -> None:
        """A failed connect must not leave ``peer`` stuck in the offered
        set: with no link AND no pending offer the pair could never
        upgrade again until some other event cleared it."""
        with self._dlock:
            self._offered.discard(peer)

    def _adopt_link(self, link: _DirectLink) -> None:
        """Register an authenticated link for outbound routing and start
        its reader. Latest link wins: after an asymmetric failure (the
        peer saw the error and redialed, we did not) a first-wins policy
        would let the stale registered link shadow the fresh one forever;
        replacing closes the old link (any reply in flight on it fails
        and the requester retries via the relay)."""
        if self._shutdown.is_set():
            # a dial (e.g. the larger side's grace-period fallback) can
            # complete its handshake just as close() sweeps _direct;
            # adopting now would leak the socket + a blocked reader
            link.close()
            return
        with self._dlock:
            old = self._direct.get(link.peer)
            self._direct[link.peer] = link
        if old is not None and old is not link:
            old.close()
        threading.Thread(
            target=self._direct_read_loop, args=(link,), daemon=True
        ).start()
        logger.info("direct link established with %s", link.peer[:16])

    def _drop_link(self, link: _DirectLink) -> None:
        with self._dlock:
            if self._direct.get(link.peer) is link:
                del self._direct[link.peer]
            # allow a fresh offer round for this peer
            self._offered.discard(link.peer)
        link.close()

    def _direct_read_loop(self, link: _DirectLink) -> None:
        try:
            while not self._shutdown.is_set():
                frame = _recv_frame(link.sock)
                frame["from"] = link.peer  # identity proven at handshake
                kind = frame.get("kind")
                if kind == "resp":
                    with self._plock:
                        entry = self._pending.get(frame.get("ch"))
                    if entry is not None and entry[0] == link.peer:
                        entry[1].put(frame)
                elif kind == "req":
                    threading.Thread(
                        target=self._serve_request,
                        args=(frame, link),
                        daemon=True,
                    ).start()
        except (ConnectionError, OSError, ValueError):
            pass
        self._drop_link(link)

    def _maybe_offer_direct(self, target: str) -> None:
        """Send our direct endpoint to ``target`` through the relay (the
        SDP-offer analogue). One offer per peer per link generation."""
        if self._direct_addr is None or self._sock is None:
            return
        with self._dlock:
            if target in self._direct or target in self._offered:
                return
            self._offered.add(target)
        try:
            _send_frame(
                self._sock,
                {
                    "to": target,
                    "kind": "direct",
                    "addr": self._direct_addr,
                },
                self._wlock,
            )
        except (OSError, ConnectionError):
            with self._dlock:
                self._offered.discard(target)

    # -- inbound -------------------------------------------------------------

    def _read_loop(self) -> None:
        backoff = 0.2
        while not self._shutdown.is_set():
            sock = self._sock
            if sock is None:
                return
            displaced = False
            try:
                while not self._shutdown.is_set():
                    frame = _recv_frame(sock)
                    kind = frame.get("kind")
                    if kind == "displaced":
                        # another live client took over this key; back off
                        # hard so two same-key processes don't livelock
                        # kicking each other
                        displaced = True
                        continue
                    backoff = 0.2
                    if kind == "resp":
                        with self._plock:
                            entry = self._pending.get(frame.get("ch"))
                        # deliver only if the (server-stamped, authenticated)
                        # sender matches who we asked — a third party cannot
                        # forge a response by guessing channel ids
                        if entry is not None and frame.get("from") in (
                            entry[0],
                            "",  # server-originated error replies
                        ):
                            entry[1].put(frame)
                    elif kind == "req":
                        threading.Thread(
                            target=self._serve_request,
                            args=(frame,),
                            daemon=True,
                        ).start()
                    elif kind == "direct":
                        # relay-signaled endpoint exchange (SDP-offer
                        # analogue): the lexicographically smaller pubkey
                        # dials (deterministic tie-break — see
                        # _should_dial); the larger side answers with its
                        # own endpoint so the smaller learns where to
                        # dial, and arms a grace-period fallback dial for
                        # one-sided reachability (_fallback_dial).
                        # Answers are not re-answered — no offer loops.
                        # Nodes WITHOUT direct_listen ignore offers
                        # entirely: "empty = gossip stays relayed" is an
                        # operator promise (egress policy), and honoring
                        # a peer's offer would let any registered key
                        # make this node dial an arbitrary address.
                        peer = self._norm(frame.get("from") or "")
                        addr = frame.get("addr")
                        if self._direct_listen and peer and addr:
                            is_answer = bool(frame.get("answer"))
                            dial = fallback = False
                            with self._dlock:
                                have = peer in self._direct
                                dialing = peer in self._dialing
                                if self._should_dial(peer):
                                    # An OFFER means the peer has no
                                    # usable link to us (it only offers
                                    # when unlinked): a link registered
                                    # here is stale-or-dying knowledge,
                                    # so the dialer side redials and
                                    # latest-wins replaces it. Answers
                                    # only follow our own offer (no link
                                    # on our side at offer time).
                                    dial = not dialing and (
                                        not have or not is_answer
                                    )
                                    if dial:
                                        self._dialing.add(peer)
                                elif (
                                    not have
                                    and peer not in self._fallback_waiting
                                ):
                                    # Larger side: normally only answers,
                                    # but arms a grace-period reverse dial
                                    # for one-sided reachability (the
                                    # smaller peer's dial may target an
                                    # unreachable NAT'd endpoint while
                                    # ours would succeed).
                                    fallback = True
                                    self._fallback_waiting.add(peer)
                            if dial:
                                threading.Thread(
                                    target=self._dial_direct,
                                    args=(peer, addr),
                                    daemon=True,
                                ).start()
                            elif fallback:
                                threading.Thread(
                                    target=self._fallback_dial,
                                    args=(peer, addr),
                                    daemon=True,
                                ).start()
                            if not frame.get("answer") and (
                                self._direct_addr is not None
                            ):
                                try:
                                    _send_frame(
                                        sock,
                                        {
                                            "to": peer,
                                            "kind": "direct",
                                            "addr": self._direct_addr,
                                            "answer": True,
                                        },
                                        self._wlock,
                                    )
                                except (OSError, ConnectionError):
                                    pass
            except (ConnectionError, OSError, ValueError):
                pass
            # relay connection dropped: reconnect with backoff so a signal
            # server restart does not permanently silence the node
            if displaced:
                time.sleep(5.0)
            while not self._shutdown.is_set():
                try:
                    self._sock = self._connect()
                    logger.info("signal relay reconnected")
                    break
                except (OSError, ValueError, ConnectionError):
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 5.0)

    def _serve_request(self, frame: dict,
                       link: Optional[_DirectLink] = None) -> None:
        """Serve one inbound RPC; the reply rides the path the request
        arrived on — the direct ``link`` when given, else the relay."""
        origin = frame.get("from")
        ch = frame.get("ch")
        t = frame.get("t")

        def reply(body, error) -> None:
            resp = {"ch": ch, "kind": "resp", "t": t, "body": body,
                    "error": error}
            try:
                if link is not None:
                    link.send(resp)
                    return
                sock = self._sock
                if sock is not None:
                    _send_frame(sock, {**resp, "to": origin}, self._wlock)
            except (OSError, ConnectionError):
                pass

        req_cls = REQUEST_TYPES.get(t)
        if req_cls is None:
            reply(None, f"unknown rpc type {t}")
            return
        try:
            command = req_cls.from_dict(frame.get("body"))
        except Exception as err:
            reply(None, f"malformed request body: {err}")
            return
        rpc = RPC(command)
        rpc.recv_ts = time.time()  # arrival stamp (trace attribution)
        self._consumer.put(rpc)
        wait = (
            self._join_timeout + 2.0
            if isinstance(command, JoinRequest)
            else self._timeout
        )
        try:
            result, error = rpc.wait(timeout=wait)
        except queue.Empty:
            result, error = None, "rpc handler timeout"
        reply(result.to_dict() if result is not None else None, error)

    # -- outbound ------------------------------------------------------------

    def _request(self, target: str, req, timeout: Optional[float] = None):
        if self._sock is None:
            raise TransportError("signal transport not listening")
        type_byte = TYPE_OF_REQUEST[type(req)]
        norm_target = self._norm(target)
        with self._plock:
            self._next_ch += 1
            ch = self._next_ch
            q: "queue.Queue[dict]" = queue.Queue()
            self._pending[ch] = (norm_target, q)
        msg = {
            "ch": ch,
            "kind": "req",
            "t": type_byte,
            "body": req.to_dict(),
        }
        try:
            # Prefer the direct link once a pair has upgraded; a dead link
            # drops back to the relay (which also re-arms the offer).
            with self._dlock:
                link = self._direct.get(norm_target)
            sent_direct = False
            if link is not None:
                try:
                    link.send(msg)
                    sent_direct = True
                except (OSError, ConnectionError):
                    self._drop_link(link)
            if not sent_direct:
                if self._direct_listen:
                    self._maybe_offer_direct(norm_target)
                _send_frame(
                    self._sock, {**msg, "to": norm_target}, self._wlock
                )
            try:
                frame = q.get(timeout=timeout or self._timeout)
            except queue.Empty:
                raise TransportError(f"rpc to {target} timed out")
        except (OSError, ConnectionError) as err:
            raise TransportError(f"rpc to {target}: {err}") from err
        finally:
            with self._plock:
                self._pending.pop(ch, None)
        if frame.get("error"):
            raise TransportError(f"remote error from {target}: {frame['error']}")
        return RESPONSE_TYPES[type_byte].from_dict(frame["body"])

    def sync(self, target: str, req):
        return self._request(target, req)

    def eager_sync(self, target: str, req):
        return self._request(target, req)

    def fast_forward(self, target: str, req):
        return self._request(target, req)

    def join(self, target: str, req):
        return self._request(target, req, timeout=self._join_timeout + 4.0)
