"""RPC message types and the request/response envelope.

Reference semantics: src/net/commands.go:12-68 (the four RPC pairs) and
src/net/rpc.go:4-21 (the RPC envelope whose response rides a channel; here
a one-slot queue.Queue).

Each message has a to_dict/from_dict codec so any byte transport (TCP
framing, tests, future ICI sidecar) can carry it as JSON.
"""

from __future__ import annotations

import queue
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto.canonical import PreNormalized
from ..hashgraph.block import Block
from ..hashgraph.event import WireEvent
from ..hashgraph.frame import Frame
from ..hashgraph.internal_transaction import InternalTransaction
from ..peers.peer import Peer

# Wire type tags, one byte on the TCP framing
# (reference: net/net_transport.go:33-50).
SYNC = 0
EAGER_SYNC = 1
FAST_FORWARD = 2
JOIN = 3


@dataclass
class SyncRequest:
    """Pull leg: ask a peer for events we don't know
    (reference: net/commands.go:12-24).

    ``trace`` is the optional wire trace context
    (obs/provenance.py: {"id", "origin", "hop", "ts"-µs}). It rides the
    payload only when present, so peers that predate the field — in
    either direction — interoperate untouched: an old sender simply
    omits it (``from_dict`` yields None, no trace is recorded), and an
    old receiver ignores the unknown key."""

    from_id: int
    known: Dict[int, int]
    sync_limit: int
    trace: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {
            "from_id": self.from_id,
            "known": {str(k): v for k, v in self.known.items()},
            "sync_limit": self.sync_limit,
        }
        if self.trace is not None:
            d["trace"] = self.trace
        return d

    @staticmethod
    def from_dict(d: dict) -> "SyncRequest":
        return SyncRequest(
            from_id=d["from_id"],
            known={int(k): v for k, v in d["known"].items()},
            sync_limit=d["sync_limit"],
            trace=d.get("trace"),
        )


@dataclass
class SyncResponse:
    """reference: net/commands.go:26-32."""

    from_id: int
    events: List[WireEvent] = field(default_factory=list)
    known: Dict[int, int] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "from_id": self.from_id,
            # memoized normalized form: each event's bytes are b64'd once
            # per process, not once per peer pushed to (event.py normalized)
            "events": [PreNormalized(e.normalized()) for e in self.events],
            "known": {str(k): v for k, v in self.known.items()},
        }

    @staticmethod
    def from_dict(d: dict) -> "SyncResponse":
        return SyncResponse(
            from_id=d["from_id"],
            events=[WireEvent.from_dict(e) for e in d["events"]],
            known={int(k): v for k, v in d["known"].items()},
        )


@dataclass
class EagerSyncRequest:
    """Push leg: send a peer the events they don't know
    (reference: net/commands.go:34-40). ``trace`` as on SyncRequest —
    optional on the wire, absent means no causal context."""

    from_id: int
    events: List[WireEvent] = field(default_factory=list)
    trace: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {
            "from_id": self.from_id,
            "events": [PreNormalized(e.normalized()) for e in self.events],
        }
        if self.trace is not None:
            d["trace"] = self.trace
        return d

    @staticmethod
    def from_dict(d: dict) -> "EagerSyncRequest":
        return EagerSyncRequest(
            from_id=d["from_id"],
            events=[WireEvent.from_dict(e) for e in d["events"]],
            trace=d.get("trace"),
        )


@dataclass
class EagerSyncResponse:
    """reference: net/commands.go:42-46."""

    from_id: int
    success: bool

    def to_dict(self) -> dict:
        return {"from_id": self.from_id, "success": self.success}

    @staticmethod
    def from_dict(d: dict) -> "EagerSyncResponse":
        return EagerSyncResponse(from_id=d["from_id"], success=d["success"])


@dataclass
class FastForwardRequest:
    """Catch-up: request the anchor block + frame + app snapshot
    (reference: net/commands.go:48-51). ``trace`` as on SyncRequest."""

    from_id: int
    trace: Optional[dict] = None

    def to_dict(self) -> dict:
        d = {"from_id": self.from_id}
        if self.trace is not None:
            d["trace"] = self.trace
        return d

    @staticmethod
    def from_dict(d: dict) -> "FastForwardRequest":
        return FastForwardRequest(
            from_id=d["from_id"], trace=d.get("trace")
        )


@dataclass
class FastForwardResponse:
    """reference: net/commands.go:53-59."""

    from_id: int
    block: Optional[Block] = None
    frame: Optional[Frame] = None
    snapshot: bytes = b""

    def to_dict(self) -> dict:
        return {
            "from_id": self.from_id,
            "block": self.block.to_dict() if self.block else None,
            "frame": self.frame.to_dict() if self.frame else None,
            "snapshot": self.snapshot.hex(),
        }

    @staticmethod
    def from_dict(d: dict) -> "FastForwardResponse":
        return FastForwardResponse(
            from_id=d["from_id"],
            block=Block.from_dict(d["block"]) if d["block"] else None,
            frame=Frame.from_dict(d["frame"]) if d["frame"] else None,
            snapshot=bytes.fromhex(d["snapshot"]),
        )


@dataclass
class JoinRequest:
    """Membership: a signed PEER_ADD internal transaction
    (reference: net/commands.go:61-63)."""

    internal_transaction: InternalTransaction

    def to_dict(self) -> dict:
        return {"internal_transaction": self.internal_transaction.to_dict()}

    @staticmethod
    def from_dict(d: dict) -> "JoinRequest":
        return JoinRequest(
            internal_transaction=InternalTransaction.from_dict(
                d["internal_transaction"]
            )
        )


@dataclass
class JoinResponse:
    """reference: net/commands.go:65-68."""

    from_id: int
    accepted: bool
    accepted_round: int
    peers: List[Peer] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "from_id": self.from_id,
            "accepted": self.accepted,
            "accepted_round": self.accepted_round,
            "peers": [p.to_dict() for p in self.peers],
        }

    @staticmethod
    def from_dict(d: dict) -> "JoinResponse":
        return JoinResponse(
            from_id=d["from_id"],
            accepted=d["accepted"],
            accepted_round=d["accepted_round"],
            peers=[Peer.from_dict(p) for p in d["peers"]],
        )


REQUEST_TYPES = {
    SYNC: SyncRequest,
    EAGER_SYNC: EagerSyncRequest,
    FAST_FORWARD: FastForwardRequest,
    JOIN: JoinRequest,
}

RESPONSE_TYPES = {
    SYNC: SyncResponse,
    EAGER_SYNC: EagerSyncResponse,
    FAST_FORWARD: FastForwardResponse,
    JOIN: JoinResponse,
}

TYPE_OF_REQUEST = {v: k for k, v in REQUEST_TYPES.items()}


class RPC:
    """A command plus a one-slot response queue (reference: net/rpc.go:4-21).

    The transport server puts RPCs on the node's consumer queue; the node
    handles them and calls respond(); the server relays the result back to
    the caller.
    """

    def __init__(self, command):
        self.command = command
        # Transport-arrival stamp (epoch seconds, the transport's clock):
        # set by the server loops when they park the RPC on the consumer
        # queue, so the handler can split "wire" from "queue" time in
        # per-hop trace attribution. None when the transport predates it.
        self.recv_ts: Optional[float] = None
        # Event-driven transports (net/atcp.py) set this instead of
        # parking a thread on wait(): respond() invokes it in the
        # handler's thread, so response serialization happens off the
        # transport's event loop.
        self.on_respond: Optional[Callable[[object, Optional[str]], None]] = None
        self._resp: "queue.Queue[Tuple[object, Optional[str]]]" = queue.Queue(1)

    def respond(self, result, error: Optional[str] = None) -> None:
        self._resp.put((result, error))
        cb = self.on_respond
        if cb is not None:
            try:
                cb(result, error)
            except Exception:
                # a dead connection must not crash the node's handler
                pass

    def wait(self, timeout: Optional[float] = None):
        """Block for the handler's response. Returns (result, error_str)."""
        return self._resp.get(timeout=timeout)
