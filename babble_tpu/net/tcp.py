"""TCP transport: typed RPC framing over pooled sockets.

Wire format mirrors the reference's (adapted-from-raft) framing
(/root/reference/src/net/net_transport.go:39-50,274-441): one RPC type
byte, then the JSON request; the response is an error string + JSON
payload. Here both directions are length-prefixed (4-byte big-endian)
JSON — same shape, explicit frame boundaries — with bytes fields base64
encoded by the canonical codec.

Server side: an accept loop; each connection gets a handler thread that
decodes requests, parks them on the node's consumer queue as RPC
envelopes, and relays the node's response (net_transport.go:321-441).
Client side: a per-target connection pool capped at ``max_pool``
(net_transport.go:161-219).
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..crypto.canonical import canonical_dumps
from .codec import CODEC_STATS
from .rpc import (
    JoinRequest,
    REQUEST_TYPES,
    RESPONSE_TYPES,
    RPC,
    TYPE_OF_REQUEST,
)
from .transport import RemoteError, TransportError


# Upper bound on any frame (request or response). A hostile peer could
# otherwise send a 4 GB length prefix and make the receiver allocate it.
MAX_FRAME = 64 * 1024 * 1024


class _ConnError(TransportError):
    """Connection-level failure (socket died mid-RPC) — retryable on a
    fresh dial, unlike a remote handler error, which means the peer
    received, processed, and answered the request."""


class _RecvBuffer:
    """One reusable receive buffer per connection: ``recv_into`` a
    pre-allocated bytearray instead of building each frame through
    per-call ``bytes`` concatenation (which allocated and copied
    O(chunks) intermediates per frame on the ingest hot path). The
    buffer grows to the largest frame the connection has seen and is
    reused for every subsequent read."""

    __slots__ = ("_buf",)

    def __init__(self, initial: int = 1 << 16):
        self._buf = bytearray(initial)

    def recv_exact(self, sock: socket.socket, n: int) -> bytes:
        if n > MAX_FRAME:
            raise ConnectionError(f"frame of {n} bytes exceeds limit")
        if len(self._buf) < n:
            self._buf = bytearray(n)
        view = memoryview(self._buf)
        got = 0
        while got < n:
            r = sock.recv_into(view[got:n])
            if not r:
                raise ConnectionError("connection closed")
            got += r
        CODEC_STATS.bytes_received += n
        return bytes(view[:n])


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """One-shot exact read (no connection to hang a buffer on)."""
    return _RecvBuffer(min(n, 1 << 16)).recv_exact(sock, n)


def _send_frame(sock: socket.socket, type_byte: Optional[int], payload: bytes) -> None:
    head = bytes([type_byte]) if type_byte is not None else b""
    data = head + struct.pack(">I", len(payload)) + payload
    sock.sendall(data)
    CODEC_STATS.bytes_sent += len(data)


class TCPTransport:
    """reference: net/tcp_transport.go:18-77 + net_transport.go."""

    def __init__(
        self,
        bind_addr: str,
        advertise_addr: Optional[str] = None,
        max_pool: int = 3,
        timeout: float = 10.0,
        join_timeout: Optional[float] = None,
        dial_timeout: Optional[float] = None,
    ):
        self._bind_addr = bind_addr
        self._advertise = advertise_addr or bind_addr
        self._timeout = timeout
        # Dial (connect) deadline, separate from the RPC deadline: a dead
        # host should fail the dial in seconds, not hold a gossip round
        # for the full RPC timeout.
        self._dial_timeout = (
            dial_timeout if dial_timeout is not None else min(timeout, 3.0)
        )
        # Join/leave RPCs block on consensus server-side, so they get their
        # own, much longer deadline (reference keeps these separate:
        # node_rpc.go join waits JoinTimeout while syncs use TCPTimeout).
        self._join_timeout = join_timeout if join_timeout is not None else max(
            timeout, 10.0
        )
        self._max_pool = max_pool
        self._consumer: "queue.Queue[RPC]" = queue.Queue()
        self._listener: Optional[socket.socket] = None
        self._pool: Dict[str, List[socket.socket]] = {}
        self._pool_lock = threading.Lock()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        # Pool-hardening counters: stale pooled sockets evicted mid-RPC,
        # and RPCs salvaged by the one fresh-dial retry.
        self.pool_evictions = 0
        self.retries = 0

    # -- Transport interface -------------------------------------------------

    def consumer(self) -> "queue.Queue[RPC]":
        return self._consumer

    def local_addr(self) -> str:
        return self._bind_addr

    def advertise_addr(self) -> str:
        return self._advertise

    def listen(self) -> None:
        if self._listener is not None:  # idempotent (Node.init also calls it)
            return
        host, port_s = self._bind_addr.rsplit(":", 1)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port_s)))
        srv.listen(64)
        self._listener = srv
        # rewrite port 0 to the assigned one so tests can bind ephemeral
        if int(port_s) == 0:
            port = srv.getsockname()[1]
            self._bind_addr = f"{host}:{port}"
            if self._advertise.endswith(":0"):
                self._advertise = f"{self._advertise.rsplit(':', 1)[0]}:{port}"
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._shutdown.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._pool_lock:
            for conns in self._pool.values():
                for c in conns:
                    try:
                        c.close()
                    except OSError:
                        pass
            self._pool.clear()

    # -- server side ---------------------------------------------------------

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._shutdown.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _handle_conn(self, conn: socket.socket) -> None:
        """One request/response at a time per connection
        (reference: net_transport.go:355-441)."""
        rbuf = _RecvBuffer()  # reused across every frame on this conn
        try:
            while not self._shutdown.is_set():
                type_byte = rbuf.recv_exact(conn, 1)[0]
                (length,) = struct.unpack(">I", rbuf.recv_exact(conn, 4))
                payload = rbuf.recv_exact(conn, length)
                req_cls = REQUEST_TYPES.get(type_byte)
                if req_cls is None:
                    _send_frame(
                        conn,
                        None,
                        canonical_dumps(
                            {"error": f"unknown rpc type {type_byte}", "payload": None}
                        ),
                    )
                    continue
                command = req_cls.from_dict(json.loads(payload))
                rpc = RPC(command)
                rpc.recv_ts = time.time()  # lint: allow(clock: recv_ts is a real-wire arrival stamp; sim uses SimTransport)
                self._consumer.put(rpc)
                # Joins park on a consensus promise in the handler; give the
                # node's own join deadline room to fire first (+2 s margin).
                wait_timeout = (
                    self._join_timeout + 2.0
                    if isinstance(command, JoinRequest)
                    else self._timeout
                )
                try:
                    result, error = rpc.wait(timeout=wait_timeout)
                except queue.Empty:
                    result, error = None, "rpc handler timeout"
                body = {
                    "error": error,
                    "payload": result.to_dict() if result is not None else None,
                }
                _send_frame(conn, None, canonical_dumps(body))
        except (ConnectionError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- client side ---------------------------------------------------------

    def _checkout(self, target: str) -> Tuple[socket.socket, bool]:
        """A connection to ``target``: (socket, came_from_pool)."""
        with self._pool_lock:
            conns = self._pool.get(target)
            if conns:
                return conns.pop(), True
        return self._dial(target), False

    def _dial(self, target: str) -> socket.socket:
        host, port_s = target.rsplit(":", 1)
        try:
            sock = socket.create_connection(
                (host, int(port_s)), timeout=self._dial_timeout
            )
        except OSError as err:
            raise TransportError(f"dial {target}: {err}") from err
        sock.settimeout(self._timeout)
        return sock

    def _evict_pool(self, target: str) -> None:
        """A pooled socket to ``target`` just failed mid-RPC; its pool
        siblings were checked in around the same time and are almost
        certainly stale too (peer restarted) — close them all rather than
        paying one failed RPC per corpse."""
        with self._pool_lock:
            conns = self._pool.pop(target, [])
            self.pool_evictions += 1 + len(conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def _checkin(self, target: str, sock: socket.socket) -> None:
        sock.settimeout(self._timeout)  # undo any per-request deadline
        with self._pool_lock:
            conns = self._pool.setdefault(target, [])
            if len(conns) < self._max_pool:
                conns.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _request(self, target: str, req, timeout: Optional[float] = None):
        """One RPC. A failure on a POOLED socket is most often a stale
        connection (the peer restarted between RPCs), not a dead peer:
        evict the target's pool and retry ONCE on a fresh dial before
        surfacing TransportError. Handlers are idempotent (hashgraph
        inserts dedupe), so the at-most-one duplicate delivery a retry
        can cause is safe. Fresh-dial failures surface immediately."""
        type_byte = TYPE_OF_REQUEST[type(req)]
        sock, pooled = self._checkout(target)
        try:
            return self._roundtrip(target, sock, type_byte, req, timeout)
        except _ConnError:
            if not pooled:
                raise
            self._evict_pool(target)
            self.retries += 1
            sock = self._dial(target)
            return self._roundtrip(target, sock, type_byte, req, timeout)

    def _roundtrip(
        self,
        target: str,
        sock: socket.socket,
        type_byte: int,
        req,
        timeout: Optional[float],
    ):
        rbuf = _RecvBuffer()  # reused for both reads of this round trip
        try:
            if timeout is not None:
                sock.settimeout(timeout)
            _send_frame(sock, type_byte, canonical_dumps(req.to_dict()))
            (length,) = struct.unpack(">I", rbuf.recv_exact(sock, 4))
            body = json.loads(rbuf.recv_exact(sock, length))
        except socket.timeout as err:
            # A timeout means the peer is slow or gone, NOT that the pooled
            # socket was stale — retrying would double the worst-case RPC
            # latency and deliver the request twice to a slow-but-alive
            # peer. Surface it as non-retryable.
            try:
                sock.close()
            except OSError:
                pass
            raise TransportError(f"rpc to {target}: {err}") from err
        except (OSError, ConnectionError, struct.error, ValueError) as err:
            try:
                sock.close()
            except OSError:
                pass
            raise _ConnError(f"rpc to {target}: {err}") from err
        self._checkin(target, sock)
        if body.get("error"):
            raise RemoteError(f"remote error from {target}: {body['error']}")
        resp_cls = RESPONSE_TYPES[type_byte]
        return resp_cls.from_dict(body["payload"])

    def sync(self, target: str, req):
        return self._request(target, req)

    def eager_sync(self, target: str, req):
        return self._request(target, req)

    def fast_forward(self, target: str, req):
        return self._request(target, req)

    def join(self, target: str, req):
        return self._request(target, req, timeout=self._join_timeout + 4.0)
