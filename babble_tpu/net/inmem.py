"""In-memory transport for tests: RPCs routed between transports through a
shared registry (reference: src/net/inmem_transport.go:34-185).

The Go version routes through per-peer channels with Connect/Disconnect
wiring; here an InmemNetwork object holds the addr -> transport map and a
disconnect set, and request() delivers the RPC straight onto the target's
consumer queue.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Dict, Optional, Set, Tuple

from .rpc import (
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    JoinRequest,
    JoinResponse,
    RPC,
    SyncRequest,
    SyncResponse,
)
from .transport import RemoteError, TransportError

_counter = itertools.count()


class InmemNetwork:
    """Registry connecting InmemTransports (reference: inmem_transport.go
    Connect/Disconnect wiring, :150-185)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._transports: Dict[str, "InmemTransport"] = {}
        self._severed: Set[Tuple[str, str]] = set()

    def new_transport(self, addr: str = "") -> "InmemTransport":
        t = InmemTransport(self, addr or f"inmem://{next(_counter)}")
        with self._lock:
            self._transports[t.advertise_addr()] = t
        return t

    def disconnect(self, a: str, b: str) -> None:
        """Sever the link between two addresses (both directions)."""
        with self._lock:
            self._severed.add((a, b))
            self._severed.add((b, a))

    def reconnect(self, a: str, b: str) -> None:
        with self._lock:
            self._severed.discard((a, b))
            self._severed.discard((b, a))

    def remove(self, addr: str) -> None:
        with self._lock:
            self._transports.pop(addr, None)

    def route(self, src: str, target: str, timeout: float):
        with self._lock:
            if (src, target) in self._severed:
                raise TransportError(f"link severed: {src} -> {target}")
            t = self._transports.get(target)
        if t is None or t.closed:
            raise TransportError(f"no transport listening on {target}")
        return t

    def request(self, src: str, target: str, command, timeout: float = 5.0):
        t = self.route(src, target, timeout)
        rpc = RPC(command)
        rpc.recv_ts = time.time()  # lint: allow(clock: recv_ts is a real arrival stamp; SimTransport leaves it None)
        t.consumer().put(rpc)
        try:
            result, error = rpc.wait(timeout=timeout)
        except queue.Empty:
            raise TransportError(f"rpc timeout to {target}")
        if error:
            raise RemoteError(error)
        return result


class InmemTransport:
    """Channel-routed fake network endpoint
    (reference: inmem_transport.go:34-80)."""

    def __init__(
        self,
        network: InmemNetwork,
        addr: str,
        timeout: float = 5.0,
        join_timeout: float = 30.0,
    ):
        self.network = network
        self.addr = addr
        self.timeout = timeout
        # Joins block on consensus server-side; give them their own longer
        # deadline, mirroring the TCP transport's split.
        self.join_timeout = max(join_timeout, timeout)
        self.closed = False
        self._consumer: "queue.Queue[RPC]" = queue.Queue()

    def consumer(self) -> "queue.Queue[RPC]":
        return self._consumer

    def local_addr(self) -> str:
        return self.addr

    def advertise_addr(self) -> str:
        return self.addr

    def listen(self) -> None:
        """No-op: delivery is direct onto the consumer queue."""

    def sync(self, target: str, req: SyncRequest) -> SyncResponse:
        return self.network.request(self.addr, target, req, self.timeout)

    def eager_sync(self, target: str, req: EagerSyncRequest) -> EagerSyncResponse:
        return self.network.request(self.addr, target, req, self.timeout)

    def fast_forward(
        self, target: str, req: FastForwardRequest
    ) -> FastForwardResponse:
        return self.network.request(self.addr, target, req, self.timeout)

    def join(self, target: str, req: JoinRequest) -> JoinResponse:
        return self.network.request(self.addr, target, req, self.join_timeout)

    def close(self) -> None:
        self.closed = True
        self.network.remove(self.addr)
