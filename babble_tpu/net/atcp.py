"""Event-driven async TCP transport: one selector loop, multiplexed
binary connections, per-peer outbound write queues.

The seed transport (net/tcp.py) is thread-per-connection with one
blocking request/response in flight per socket — at 16 nodes that is
hundreds of parked threads convoying on the GIL, and the JSON codec on
top of it is the measured wall (BENCH_r05, ROADMAP item 1). This
transport replaces the hot path:

- **One loop thread** (``selectors``-based) owns every socket:
  non-blocking accept, read, and write; outbound frames go through
  per-connection write queues drained as the socket becomes writable.
- **Connection multiplexing**: binary frames carry a ``req_id``, so a
  node keeps ONE connection per peer with many RPCs in flight instead
  of a pool of one-at-a-time sockets.
- **Version negotiation per connection** (net/codec.py HELLO): a binary
  client probes with a 9-byte hello (a well-formed legacy frame:
  type 0xBB, length 4, "BLG"+version). A binary peer acks it; a legacy
  JSON peer answers the probe with its normal "unknown rpc type" error
  frame, which the client detects and falls back to the legacy JSON
  framing on that same socket — old and new nodes interoperate in both
  directions with zero configuration. The server side speaks both: the
  first byte of a connection selects binary (0xBB) or legacy JSON
  (type byte 0-3).
- **Zero-copy-ish event path**: Sync/EagerSync payloads carry events as
  length-prefixed opaque blobs (encoded once per process, decoded once
  at ingest) — no per-peer JSON/base64 round-trips.

The blocking client API (sync/eager_sync/fast_forward/join) is
unchanged, so chaos/trace/sim layers compose exactly as with
TCPTransport, which remains available as the fallback transport.
"""

from __future__ import annotations

import json
import queue
import selectors
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..crypto.canonical import canonical_dumps
from . import codec
from .codec import CODEC_STATS, FLAG_ERROR, HELLO, MAX_FRAME, RESP_BIT
from .rpc import JoinRequest, REQUEST_TYPES, RESPONSE_TYPES, RPC, TYPE_OF_REQUEST
from .transport import RemoteError, TransportError

_U32 = struct.Struct(">I")
_CHUNK = 1 << 16


class _ConnError(TransportError):
    """Connection-level failure — retryable on a fresh dial (the peer
    may simply have restarted), unlike a RemoteError."""


class _Waiter:
    """One in-flight multiplexed RPC: the caller thread parks on the
    event; the loop thread delivers (flags, payload) or a conn error."""

    __slots__ = ("event", "flags", "payload", "conn_error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.flags: Optional[int] = None
        self.payload: Optional[bytes] = None
        self.conn_error: Optional[str] = None


#: Cap on bytes queued toward one connection. A peer that stops reading
#: (partition with the socket held open, SIGSTOP — the chaos-suite
#: scenarios) would otherwise grow conn.wq without bound, one eager-sync
#: frame per gossip round, for the fault's whole duration; the blocking
#: sendall of the threaded transport gave natural backpressure here.
#: Overflow drops the connection: pending RPCs fail fast, queued frames
#: are freed, and the next RPC redials (by then the peer either reads
#: again or the dial fails promptly).
MAX_CONN_BACKLOG = 16 * 1024 * 1024


class _Conn:
    """One registered socket: parse state + outbound write queue."""

    __slots__ = (
        "sock", "mode", "rbuf", "wq", "wq_bytes", "wview", "pending",
        "next_id", "lock", "closed",
    )

    # modes
    SRV_NEW, SRV_BIN, SRV_JSON, CLI_BIN = range(4)

    def __init__(self, sock: socket.socket, mode: int):
        self.sock = sock
        self.mode = mode
        self.rbuf = bytearray()
        self.wq: List[bytes] = []        # queued outbound frames
        self.wq_bytes = 0                # bytes across wq + wview
        self.wview: Optional[memoryview] = None  # partial write in progress
        self.pending: Dict[int, _Waiter] = {}    # client conns only
        self.next_id = 0
        self.lock = threading.Lock()     # guards pending/next_id
        self.closed = False


class AsyncTCPTransport:
    """Drop-in Transport (net/transport.py protocol) over the selector
    loop. Constructor mirrors TCPTransport so call sites can switch on a
    config flag; ``max_pool`` only bounds the legacy-JSON fallback pool."""

    def __init__(
        self,
        bind_addr: str,
        advertise_addr: Optional[str] = None,
        max_pool: int = 3,
        timeout: float = 10.0,
        join_timeout: Optional[float] = None,
        dial_timeout: Optional[float] = None,
    ):
        self._bind_addr = bind_addr
        self._advertise = advertise_addr or bind_addr
        self._timeout = timeout
        self._dial_timeout = (
            dial_timeout if dial_timeout is not None else min(timeout, 3.0)
        )
        self._join_timeout = join_timeout if join_timeout is not None else max(
            timeout, 10.0
        )
        self._max_pool = max_pool
        self._consumer: "queue.Queue[RPC]" = queue.Queue()
        self._listener: Optional[socket.socket] = None
        self._shutdown = threading.Event()

        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._ops_lock = threading.Lock()
        self._ops: List = []           # thunks for the loop thread
        self._loop_thread: Optional[threading.Thread] = None
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)

        self._cli_lock = threading.Lock()
        self._bin_conns: Dict[str, _Conn] = {}   # one multiplexed conn/peer
        self._json_pool: Dict[str, List[socket.socket]] = {}  # legacy peers
        # One dial/negotiation at a time per target: without this a
        # thundering herd of first RPCs to a peer races N probe dials
        # and throws away N-1 negotiated connections.
        self._dial_locks: Dict[str, threading.Lock] = {}
        # Interop counters (surfaced via stats()): how this transport's
        # outbound connections negotiated.
        self.peers_binary = 0
        self.peers_json = 0

    # -- Transport interface -------------------------------------------------

    def consumer(self) -> "queue.Queue[RPC]":
        return self._consumer

    def local_addr(self) -> str:
        return self._bind_addr

    def advertise_addr(self) -> str:
        return self._advertise

    def listen(self) -> None:
        if self._listener is not None:
            return
        host, port_s = self._bind_addr.rsplit(":", 1)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port_s)))
        srv.listen(256)
        srv.setblocking(False)
        self._listener = srv
        if int(port_s) == 0:
            port = srv.getsockname()[1]
            self._bind_addr = f"{host}:{port}"
            if self._advertise.endswith(":0"):
                self._advertise = f"{self._advertise.rsplit(':', 1)[0]}:{port}"
        self._sel.register(srv, selectors.EVENT_READ, "accept")
        self._ensure_loop()

    def close(self) -> None:
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        self._wakeup()
        t = self._loop_thread
        if t is not None:
            t.join(timeout=2.0)
        # the loop thread owns the teardown; if it never ran, clean here
        if t is None:
            self._teardown()
        with self._cli_lock:
            pools = list(self._json_pool.values())
            self._json_pool.clear()
        for conns in pools:
            for s in conns:
                try:
                    s.close()
                except OSError:
                    pass

    def stats(self) -> dict:
        return {
            "peers_binary": self.peers_binary,
            "peers_json": self.peers_json,
        }

    # -- loop plumbing -------------------------------------------------------

    def _ensure_loop(self) -> None:
        if self._shutdown.is_set():
            return  # a late client call must not resurrect a closed loop
        if self._loop_thread is None or not self._loop_thread.is_alive():
            self._loop_thread = threading.Thread(
                target=self._loop, daemon=True, name="atcp-loop"
            )
            self._loop_thread.start()

    def _wakeup(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def _run_in_loop(self, fn) -> None:
        with self._ops_lock:
            self._ops.append(fn)
        self._wakeup()

    def _loop(self) -> None:
        try:
            while not self._shutdown.is_set():
                for key, events in self._sel.select(timeout=0.5):
                    data = key.data
                    if key.fileobj is self._wake_r:
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    elif data == "accept":
                        self._accept()
                    elif isinstance(data, _Conn):
                        if events & selectors.EVENT_READ:
                            self._readable(data)
                        if events & selectors.EVENT_WRITE and not data.closed:
                            self._writable(data)
                with self._ops_lock:
                    ops, self._ops = self._ops, []
                for fn in ops:
                    try:
                        fn()
                    except Exception:
                        pass
        finally:
            self._teardown()

    def _teardown(self) -> None:
        try:
            conns = [
                key.data
                for key in list(self._sel.get_map().values())
                if isinstance(key.data, _Conn)
            ]
        except (RuntimeError, AttributeError, KeyError):
            conns = []  # selector already closed by an earlier teardown
        for conn in conns:
            self._drop_conn(conn, "transport closed")
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except Exception:
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _interest(self, conn: _Conn) -> None:
        """(Re)register the conn for read, plus write when data is queued."""
        mask = selectors.EVENT_READ
        if conn.wq or conn.wview is not None:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, conn)
        except KeyError:
            try:
                self._sel.register(conn.sock, mask, conn)
            except (KeyError, ValueError, OSError):
                pass

    def _enqueue(self, conn: _Conn, frame: bytes) -> None:
        """Loop-thread only: queue an outbound frame and try to flush
        immediately (most frames fit the socket buffer — no extra
        select round-trip on the common path). A connection whose peer
        has stopped reading is dropped at MAX_CONN_BACKLOG queued bytes
        instead of buffering for the fault's whole duration."""
        if conn.closed:
            return
        if conn.wq_bytes + len(frame) > MAX_CONN_BACKLOG:
            self._drop_conn(conn, "outbound queue overflow (stalled peer)")
            return
        conn.wq.append(frame)
        conn.wq_bytes += len(frame)
        self._writable(conn)

    def _send(self, conn: _Conn, frame: bytes) -> None:
        """Any-thread entry: hand the frame to the loop."""
        self._run_in_loop(lambda: self._enqueue(conn, frame))

    # -- server side ---------------------------------------------------------

    def _accept(self) -> None:
        assert self._listener is not None
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            conn = _Conn(sock, _Conn.SRV_NEW)
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (ValueError, OSError):
                try:
                    sock.close()
                except OSError:
                    pass

    def _readable(self, conn: _Conn) -> None:
        try:
            while True:
                chunk = conn.sock.recv(_CHUNK)
                if not chunk:
                    self._drop_conn(conn, "connection closed by peer")
                    return
                CODEC_STATS.bytes_received += len(chunk)
                conn.rbuf += chunk
                if len(chunk) < _CHUNK:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as err:
            self._drop_conn(conn, f"read error: {err}")
            return
        try:
            self._parse(conn)
        except (ValueError, struct.error, json.JSONDecodeError) as err:
            self._drop_conn(conn, f"protocol error: {err}")

    def _writable(self, conn: _Conn) -> None:
        try:
            while conn.wview is not None or conn.wq:
                if conn.wview is None:
                    conn.wview = memoryview(conn.wq.pop(0))
                n = conn.sock.send(conn.wview)
                CODEC_STATS.bytes_sent += n
                conn.wq_bytes -= n
                if n < len(conn.wview):
                    conn.wview = conn.wview[n:]
                    break
                conn.wview = None
        except (BlockingIOError, InterruptedError):
            pass
        except OSError as err:
            self._drop_conn(conn, f"write error: {err}")
            return
        self._interest(conn)

    def _parse(self, conn: _Conn) -> None:
        """Consume every complete frame in the conn's read buffer."""
        buf = conn.rbuf
        while True:
            if conn.mode == _Conn.SRV_NEW:
                if not buf:
                    return
                first = buf[0]
                if first == HELLO[0]:
                    if len(buf) < len(HELLO):
                        return
                    if bytes(buf[: len(HELLO) - 1]) != HELLO[:-1]:
                        raise ValueError("bad hello magic")
                    del buf[: len(HELLO)]
                    conn.mode = _Conn.SRV_BIN
                    CODEC_STATS.conns_binary += 1
                    self._enqueue(conn, HELLO)  # ack (version echo)
                    continue
                if first in REQUEST_TYPES:
                    conn.mode = _Conn.SRV_JSON
                    CODEC_STATS.conns_json += 1
                    continue
                raise ValueError(f"unknown protocol byte {first}")

            if conn.mode == _Conn.SRV_JSON:
                if len(buf) < 5:
                    return
                (length,) = _U32.unpack_from(buf, 1)
                if length > MAX_FRAME:
                    raise ValueError("oversized frame")
                if len(buf) < 5 + length:
                    return
                type_byte = buf[0]
                payload = bytes(buf[5:5 + length])
                del buf[:5 + length]
                self._dispatch_json(conn, type_byte, payload)
                continue

            # binary framing (server or client side of a negotiated conn)
            if conn.mode == _Conn.CLI_BIN or conn.mode == _Conn.SRV_BIN:
                if len(buf) < codec.FRAME_HEADER.size:
                    return
                kind, flags, req_id, length = codec.unpack_header(buf)
                total = codec.FRAME_HEADER.size + length
                if len(buf) < total:
                    return
                payload = bytes(buf[codec.FRAME_HEADER.size:total])
                del buf[:total]
                if kind & RESP_BIT:
                    self._deliver_response(conn, kind, flags, req_id, payload)
                else:
                    self._dispatch_bin(conn, kind, req_id, payload)
                continue
            return

    def _dispatch_bin(
        self, conn: _Conn, type_byte: int, req_id: int, payload: bytes
    ) -> None:
        try:
            command = codec.decode_request(type_byte, payload)
        except Exception as err:
            self._enqueue(
                conn,
                codec.pack_frame(
                    RESP_BIT | (type_byte & 0x7F), FLAG_ERROR, req_id,
                    f"bad request: {err}".encode("utf-8"),
                ),
            )
            return
        rpc = RPC(command)
        rpc.recv_ts = time.time()  # lint: allow(clock: recv_ts is a real-wire arrival stamp; sim uses SimTransport)

        def on_respond(result, error) -> None:
            if error is None and result is None:
                error = "empty response"
            if error is not None:
                frame = codec.pack_frame(
                    RESP_BIT | type_byte, FLAG_ERROR, req_id,
                    str(error).encode("utf-8"),
                )
            else:
                # encoded in the responder's thread, off the loop
                frame = codec.pack_frame(
                    RESP_BIT | type_byte, 0, req_id,
                    codec.encode_response(type_byte, result),
                )
            self._send(conn, frame)

        rpc.on_respond = on_respond
        self._consumer.put(rpc)

    def _dispatch_json(
        self, conn: _Conn, type_byte: int, payload: bytes
    ) -> None:
        req_cls = REQUEST_TYPES.get(type_byte)
        if req_cls is None:
            body = canonical_dumps(
                {"error": f"unknown rpc type {type_byte}", "payload": None}
            )
            self._enqueue(conn, _U32.pack(len(body)) + body)
            return
        command = req_cls.from_dict(json.loads(payload))
        rpc = RPC(command)
        rpc.recv_ts = time.time()  # lint: allow(clock: recv_ts is a real-wire arrival stamp; sim uses SimTransport)

        def on_respond(result, error) -> None:
            body = canonical_dumps(
                {
                    "error": error,
                    "payload": result.to_dict() if result is not None else None,
                }
            )
            self._send(conn, _U32.pack(len(body)) + body)

        rpc.on_respond = on_respond
        self._consumer.put(rpc)

    # -- client side ---------------------------------------------------------

    def _deliver_response(
        self, conn: _Conn, kind: int, flags: int, req_id: int, payload: bytes
    ) -> None:
        with conn.lock:
            waiter = conn.pending.pop(req_id, None)
        if waiter is None:  # late reply after caller timeout — drop
            return
        waiter.flags = flags
        waiter.payload = payload
        waiter.event.set()

    def _drop_conn(self, conn: _Conn, reason: str) -> None:
        if conn.closed:
            return
        conn.closed = True
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        with conn.lock:
            waiters = list(conn.pending.values())
            conn.pending.clear()
        for w in waiters:
            w.conn_error = reason
            w.event.set()
        with self._cli_lock:
            for target, c in list(self._bin_conns.items()):
                if c is conn:
                    del self._bin_conns[target]

    def _dial(self, target: str) -> socket.socket:
        host, port_s = target.rsplit(":", 1)
        try:
            sock = socket.create_connection(
                (host, int(port_s)), timeout=self._dial_timeout
            )
        except OSError as err:
            raise TransportError(f"dial {target}: {err}") from err
        sock.settimeout(self._timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        return sock

    def _negotiate(self, target: str) -> Tuple[Optional[_Conn], Optional[socket.socket]]:
        """Dial + HELLO probe. Returns (binary conn, None) for a binary
        peer or (None, legacy socket) for a JSON peer — the same probe
        that lets mixed-version clusters interoperate."""
        sock = self._dial(target)
        try:
            sock.sendall(HELLO)
            CODEC_STATS.bytes_sent += len(HELLO)
            first = _recv_exact_blocking(sock, 1)
            if first[0] == HELLO[0]:
                rest = _recv_exact_blocking(sock, len(HELLO) - 1)
                if first + rest != HELLO:
                    raise _ConnError(f"bad hello ack from {target}")
                sock.setblocking(False)
                conn = _Conn(sock, _Conn.CLI_BIN)
                self.peers_binary += 1
                self._ensure_loop()
                self._run_in_loop(lambda: self._interest(conn))
                return conn, None
            # Legacy JSON peer: it read our probe byte (0xBB) as an RPC
            # type and answered with a length-prefixed error frame —
            # drain it and keep the socket for JSON framing.
            rest = _recv_exact_blocking(sock, 3)
            (length,) = _U32.unpack(first + rest)
            if length > MAX_FRAME:
                raise _ConnError(f"bad probe reply from {target}")
            _recv_exact_blocking(sock, length)
            self.peers_json += 1
            return None, sock
        except (OSError, ConnectionError, struct.error) as err:
            try:
                sock.close()
            except OSError:
                pass
            raise _ConnError(f"negotiate {target}: {err}") from err

    def _request(self, target: str, req, timeout: Optional[float] = None):
        """One RPC: multiplexed binary when the peer negotiated it, the
        legacy pooled-JSON framing otherwise. A failure on a REUSED
        binary conn or pooled JSON socket retries ONCE on a fresh dial
        (the peer may have restarted; handlers are idempotent)."""
        if timeout is None:
            timeout = (
                self._join_timeout + 4.0
                if isinstance(req, JoinRequest)
                else self._timeout
            )
        conn, sock, fresh = self._checkout(target)
        try:
            if conn is not None:
                return self._bin_roundtrip(target, conn, req, timeout)
            return self._json_roundtrip(target, sock, req, timeout)
        except _ConnError:
            if fresh:
                raise
            # A REUSED conn/pooled socket died mid-RPC — most often the
            # peer restarted between RPCs. Evict and retry ONCE on a
            # fresh dial (handlers are idempotent, tcp.py contract).
            with self._cli_lock:
                stale = self._json_pool.pop(target, [])
            for s in stale:
                try:
                    s.close()
                except OSError:
                    pass
            conn, sock, _ = self._checkout(target)
            if conn is not None:
                return self._bin_roundtrip(target, conn, req, timeout)
            return self._json_roundtrip(target, sock, req, timeout)

    def _checkout(self, target: str):
        """(binary conn, legacy socket, came_fresh): an existing
        multiplexed conn or pooled socket when available, else ONE
        negotiation dial per target at a time (herd waiters reuse the
        winner's connection)."""
        with self._cli_lock:
            conn = self._bin_conns.get(target)
            if conn is not None and not conn.closed:
                return conn, None, False
            pool = self._json_pool.get(target)
            if pool:
                return None, pool.pop(), False
            dial_lock = self._dial_locks.setdefault(target, threading.Lock())
        with dial_lock:
            with self._cli_lock:
                conn = self._bin_conns.get(target)
                if conn is not None and not conn.closed:
                    return conn, None, False
                pool = self._json_pool.get(target)
                if pool:
                    return None, pool.pop(), False
            conn, sock = self._negotiate(target)
            if conn is not None:
                with self._cli_lock:
                    self._bin_conns[target] = conn
                return conn, None, True
            return None, sock, True

    def _bin_roundtrip(self, target: str, conn: _Conn, req, timeout: float):
        type_byte = TYPE_OF_REQUEST[type(req)]
        waiter = _Waiter()
        with conn.lock:
            conn.next_id = (conn.next_id + 1) & 0xFFFFFFFF
            req_id = conn.next_id
            conn.pending[req_id] = waiter
        if conn.closed:
            # raced with _drop_conn: closed is set BEFORE the pending
            # drain, so a waiter registered after the drain sees it here
            # (one registered before the drain gets error-signaled) —
            # either way we fail fast on the retry-eligible path instead
            # of burning the full RPC timeout
            with conn.lock:
                conn.pending.pop(req_id, None)
            raise _ConnError(f"rpc to {target}: connection closed")
        frame = codec.pack_frame(
            type_byte, 0, req_id, codec.encode_request(req)[1]
        )
        self._send(conn, frame)
        if not waiter.event.wait(timeout=timeout):
            with conn.lock:
                conn.pending.pop(req_id, None)
            raise TransportError(f"rpc to {target}: timeout")
        if waiter.conn_error is not None:
            raise _ConnError(f"rpc to {target}: {waiter.conn_error}")
        if waiter.flags & FLAG_ERROR:
            raise RemoteError(
                f"remote error from {target}: "
                f"{waiter.payload.decode('utf-8', 'replace')}"
            )
        return codec.decode_response(type_byte, waiter.payload)

    def _json_roundtrip(self, target: str, sock: socket.socket, req, timeout: float):
        """Legacy framing to an old JSON peer, one RPC per socket at a
        time (tcp.py semantics, including the error-frame contract)."""
        type_byte = TYPE_OF_REQUEST[type(req)]
        try:
            sock.settimeout(timeout)
            payload = canonical_dumps(req.to_dict())
            data = bytes([type_byte]) + _U32.pack(len(payload)) + payload
            sock.sendall(data)
            CODEC_STATS.bytes_sent += len(data)
            (length,) = _U32.unpack(_recv_exact_blocking(sock, 4))
            if length > MAX_FRAME:
                raise ValueError("oversized frame")
            body = json.loads(_recv_exact_blocking(sock, length))
        except socket.timeout as err:
            try:
                sock.close()
            except OSError:
                pass
            raise TransportError(f"rpc to {target}: {err}") from err
        except (OSError, ConnectionError, struct.error, ValueError) as err:
            try:
                sock.close()
            except OSError:
                pass
            raise _ConnError(f"rpc to {target}: {err}") from err
        sock.settimeout(self._timeout)
        with self._cli_lock:
            pool = self._json_pool.setdefault(target, [])
            if len(pool) < self._max_pool:
                pool.append(sock)
                sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        if body.get("error"):
            raise RemoteError(f"remote error from {target}: {body['error']}")
        return RESPONSE_TYPES[type_byte].from_dict(body["payload"])

    def sync(self, target: str, req):
        return self._request(target, req)

    def eager_sync(self, target: str, req):
        return self._request(target, req)

    def fast_forward(self, target: str, req):
        return self._request(target, req)

    def join(self, target: str, req):
        return self._request(target, req, timeout=self._join_timeout + 4.0)


def _recv_exact_blocking(sock: socket.socket, n: int) -> bytes:
    """Blocking exact read for the client-side negotiation/JSON path —
    one implementation shared with the threaded transport (net/tcp.py
    ``_RecvBuffer``: recv_into, MAX_FRAME guard, byte accounting)."""
    from .tcp import _recv_exact

    return _recv_exact(sock, n)
