"""Binary framed wire codec for the gossip hot path.

The seed wire format (net/tcp.py) is canonical JSON with every bytes
field base64-encoded — so each event pushed to a peer pays a dict
build, a b64 walk, and a JSON parse on the far side, per peer. At 16
nodes that codec IS the wall (BENCH_r05). This module replaces it on
the Sync/EagerSync hot path with a length-prefixed binary encoding:

- Each :class:`~babble_tpu.hashgraph.event.WireEvent` is encoded ONCE
  per process into an opaque byte blob (memoized on the shared
  WireEvent exactly like its ``normalized()`` JSON memo) and travels as
  a length-prefixed slice inside the message payload — no intermediate
  Python-dict round-trip, no base64. At ingest the blob is decoded once
  into a WireEvent and handed straight to ``Core.prepare_sync``.
- Cold-path messages (FastForward/Join, which carry Blocks/Frames/peer
  sets) ride as a canonical-JSON blob inside the binary frame: they are
  rare, and reusing the JSON schema keeps them byte-identical with the
  legacy wire (the interop property the codec tests pin).
- A 9-byte HELLO (type 0xBB, u32 length 4, "BLG"+version — a
  well-formed legacy frame) negotiates the protocol per
  connection, so binary peers interoperate with old JSON peers in both
  directions (net/atcp.py; the PR-8 backward-compat pattern extended
  from one optional field to the whole framing).

Byte order is big-endian throughout; all ints are signed 64-bit (peer
ids are 32-bit FNV hashes, indexes may be -1). Frames are bounded by
``MAX_FRAME`` so a hostile length prefix cannot force a huge allocation.
"""

from __future__ import annotations

import json
import struct
from typing import Dict, List, Optional, Tuple

from ..crypto.canonical import canonical_dumps
from ..hashgraph.event import WireEvent
from .rpc import (
    EAGER_SYNC,
    EagerSyncRequest,
    EagerSyncResponse,
    FAST_FORWARD,
    FastForwardRequest,
    JOIN,
    REQUEST_TYPES,
    RESPONSE_TYPES,
    SYNC,
    SyncRequest,
    SyncResponse,
)

# Upper bound on any frame (request or response) — shared with the
# legacy TCP framing so both protocols refuse the same hostile sizes.
MAX_FRAME = 64 * 1024 * 1024

#: Protocol negotiation: a binary client opens with HELLO and waits for
#: the identical ack. The hello is deliberately shaped as a WELL-FORMED
#: legacy frame — type byte 0xBB, u32 length 4, payload b"BLG"+version —
#: so an old JSON server parses it cleanly and answers with its normal
#: "unknown rpc type 187" error frame (keeping the connection open)
#: instead of tearing the connection down on a hostile-looking length.
#: The client disambiguates on the FIRST REPLY BYTE: a binary server
#: acks with 0xBB; a legacy server's error frame starts with the length
#: prefix's MSB, 0x00 for any sane frame. 0xBB can never be a legacy
#: RPC type byte (0-3), so the server side disambiguates on the first
#: byte of the connection.
CODEC_VERSION = 1
HELLO = b"\xbb" + struct.pack(">I", 4) + b"BLG" + bytes([CODEC_VERSION])

#: Binary frame header: kind(u8) flags(u8) req_id(u32) length(u32).
#: Requests carry the RPC type byte in ``kind``; responses set RESP_BIT.
#: req_id multiplexes many in-flight RPCs over one connection.
FRAME_HEADER = struct.Struct(">BBII")
RESP_BIT = 0x80
FLAG_ERROR = 0x01

_I64 = struct.Struct(">q")
_U32 = struct.Struct(">I")
_EVENT_VERSION = 1


class CodecStats:
    """Process-wide codec tallies (co-located nodes share them; racy
    increments under the GIL may drop an update, never corrupt)."""

    __slots__ = (
        "events_encoded", "event_cache_hits", "events_decoded",
        "bytes_sent", "bytes_received", "conns_binary", "conns_json",
    )

    def __init__(self) -> None:
        self.events_encoded = 0      # event blobs built (memo misses)
        self.event_cache_hits = 0    # sends served from the blob memo
        self.events_decoded = 0      # blobs decoded at ingest
        self.bytes_sent = 0          # wire bytes out (all protocols)
        self.bytes_received = 0      # wire bytes in (all protocols)
        self.conns_binary = 0        # connections negotiated binary
        self.conns_json = 0          # connections fell back to JSON

    def snapshot(self) -> dict:
        return {name: getattr(self, name) for name in self.__slots__}


#: The one shared tally — net/tcp.py and net/atcp.py both feed it.
CODEC_STATS = CodecStats()


# -- primitive writers/readers -------------------------------------------


def _w_bytes(out: List[bytes], b: bytes) -> None:
    out.append(_U32.pack(len(b)))
    out.append(b)


def _w_str(out: List[bytes], s: str) -> None:
    _w_bytes(out, s.encode("utf-8"))


def _w_i64(out: List[bytes], v: int) -> None:
    out.append(_I64.pack(v))


class _Reader:
    """Cursor over one payload; every read is bounds-checked so a
    truncated or hostile frame raises ValueError, never over-reads."""

    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def i64(self) -> int:
        v = _I64.unpack_from(self.buf, self.pos)[0]
        self.pos += 8
        return v

    def u8(self) -> int:
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def nbytes(self) -> bytes:
        n = _U32.unpack_from(self.buf, self.pos)[0]
        self.pos += 4
        if n > MAX_FRAME or self.pos + n > len(self.buf):
            raise ValueError("truncated or oversized field")
        v = bytes(self.buf[self.pos:self.pos + n])
        self.pos += n
        return v

    def string(self) -> str:
        return self.nbytes().decode("utf-8")

    def count(self, limit: int = 1 << 22) -> int:
        n = _U32.unpack_from(self.buf, self.pos)[0]
        self.pos += 4
        if n > limit:
            raise ValueError(f"hostile element count {n}")
        return n


def _w_json(out: List[bytes], obj) -> None:
    """Canonical-JSON blob (cold-path sub-objects: internal transactions,
    trace contexts, FastForward/Join payloads)."""
    _w_bytes(out, canonical_dumps(obj))


def _r_json(r: _Reader):
    return json.loads(_r_bytes_or_empty(r))


def _r_bytes_or_empty(r: _Reader) -> bytes:
    b = r.nbytes()
    return b if b else b"null"


def _w_opt_json(out: List[bytes], obj) -> None:
    if obj is None:
        out.append(b"\x00")
    else:
        out.append(b"\x01")
        _w_json(out, obj)


def _r_opt_json(r: _Reader):
    if r.u8() == 0:
        return None
    return _r_json(r)


def _w_known(out: List[bytes], known: Dict[int, int]) -> None:
    out.append(_U32.pack(len(known)))
    for pid, h in known.items():
        out.append(_I64.pack(pid))
        out.append(_I64.pack(h))


def _r_known(r: _Reader) -> Dict[int, int]:
    return {r.i64(): r.i64() for _ in range(r.count())}


# -- event blobs ----------------------------------------------------------


def encode_wire_event(we: WireEvent) -> bytes:
    """One immutable event → one opaque blob, memoized on the WireEvent:
    ``Event.to_wire()`` shares a single WireEvent per event, so pushing
    an event to 15 peers costs one encode and 15 buffer joins."""
    blob = getattr(we, "_bin", None)
    if blob is not None:
        CODEC_STATS.event_cache_hits += 1
        return blob
    CODEC_STATS.events_encoded += 1
    b = we.body
    out: List[bytes] = [bytes([_EVENT_VERSION])]
    out.append(_I64.pack(b.creator_id))
    out.append(_I64.pack(b.other_parent_creator_id))
    out.append(_I64.pack(b.index))
    out.append(_I64.pack(b.self_parent_index))
    out.append(_I64.pack(b.other_parent_index))
    out.append(_I64.pack(b.timestamp))
    _w_str(out, we.signature)
    out.append(_U32.pack(len(b.transactions)))
    for tx in b.transactions:
        _w_bytes(out, tx)
    out.append(_U32.pack(len(b.block_signatures)))
    for bs in b.block_signatures:
        out.append(_I64.pack(bs.index))
        _w_str(out, bs.signature)
    out.append(_U32.pack(len(b.internal_transactions)))
    for itx in b.internal_transactions:
        _w_json(out, itx.to_dict())
    blob = b"".join(out)
    we._bin = blob
    return blob


def decode_wire_event(blob: bytes) -> WireEvent:
    """Blob → WireEvent, decoded exactly once at ingest (the returned
    object feeds ``Core.prepare_sync`` directly; no dict intermediate)."""
    from ..hashgraph.event import WireBlockSignature, WireBody
    from ..hashgraph.internal_transaction import InternalTransaction

    CODEC_STATS.events_decoded += 1
    r = _Reader(blob)
    if r.u8() != _EVENT_VERSION:
        raise ValueError("unknown event encoding version")
    creator_id = r.i64()
    other_parent_creator_id = r.i64()
    index = r.i64()
    self_parent_index = r.i64()
    other_parent_index = r.i64()
    timestamp = r.i64()
    signature = r.string()
    txs = [r.nbytes() for _ in range(r.count())]
    sigs = [
        WireBlockSignature(index=r.i64(), signature=r.string())
        for _ in range(r.count())
    ]
    itxs = [
        InternalTransaction.from_dict(_r_json(r)) for _ in range(r.count())
    ]
    return WireEvent(
        body=WireBody(
            transactions=txs,
            internal_transactions=itxs,
            block_signatures=sigs,
            creator_id=creator_id,
            other_parent_creator_id=other_parent_creator_id,
            index=index,
            self_parent_index=self_parent_index,
            other_parent_index=other_parent_index,
            timestamp=timestamp,
        ),
        signature=signature,
    )


def _w_events(out: List[bytes], events: List[WireEvent]) -> None:
    out.append(_U32.pack(len(events)))
    for we in events:
        _w_bytes(out, encode_wire_event(we))


def _r_events(r: _Reader) -> List[WireEvent]:
    return [decode_wire_event(r.nbytes()) for _ in range(r.count())]


# -- message payloads -----------------------------------------------------


def encode_request(req) -> Tuple[int, bytes]:
    """Request object → (rpc type byte, binary payload)."""
    out: List[bytes] = []
    if isinstance(req, SyncRequest):
        _w_i64(out, req.from_id)
        _w_known(out, req.known)
        _w_i64(out, req.sync_limit)
        _w_opt_json(out, req.trace)
        return SYNC, b"".join(out)
    if isinstance(req, EagerSyncRequest):
        _w_i64(out, req.from_id)
        _w_events(out, req.events)
        _w_opt_json(out, req.trace)
        return EAGER_SYNC, b"".join(out)
    if isinstance(req, FastForwardRequest):
        _w_i64(out, req.from_id)
        _w_opt_json(out, req.trace)
        return FAST_FORWARD, b"".join(out)
    # JoinRequest (cold path): canonical JSON blob
    _w_json(out, req.to_dict())
    return JOIN, b"".join(out)


def decode_request(type_byte: int, payload: bytes):
    r = _Reader(payload)
    if type_byte == SYNC:
        return SyncRequest(
            from_id=r.i64(), known=_r_known(r), sync_limit=r.i64(),
            trace=_r_opt_json(r),
        )
    if type_byte == EAGER_SYNC:
        return EagerSyncRequest(
            from_id=r.i64(), events=_r_events(r), trace=_r_opt_json(r)
        )
    if type_byte == FAST_FORWARD:
        return FastForwardRequest(from_id=r.i64(), trace=_r_opt_json(r))
    if type_byte == JOIN:
        return REQUEST_TYPES[JOIN].from_dict(_r_json(r))
    raise ValueError(f"unknown rpc type {type_byte}")


def encode_response(type_byte: int, resp) -> bytes:
    out: List[bytes] = []
    if type_byte == SYNC:
        _w_i64(out, resp.from_id)
        _w_events(out, resp.events)
        _w_known(out, resp.known)
    elif type_byte == EAGER_SYNC:
        _w_i64(out, resp.from_id)
        out.append(b"\x01" if resp.success else b"\x00")
    else:
        # FastForwardResponse / JoinResponse: canonical JSON blob
        _w_json(out, resp.to_dict())
    return b"".join(out)


def decode_response(type_byte: int, payload: bytes):
    r = _Reader(payload)
    if type_byte == SYNC:
        return SyncResponse(
            from_id=r.i64(), events=_r_events(r), known=_r_known(r)
        )
    if type_byte == EAGER_SYNC:
        return EagerSyncResponse(from_id=r.i64(), success=r.u8() != 0)
    return RESPONSE_TYPES[type_byte].from_dict(_r_json(r))


# -- frame layer ----------------------------------------------------------


def pack_frame(kind: int, flags: int, req_id: int, payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME:
        raise ValueError(f"frame of {len(payload)} bytes exceeds limit")
    return FRAME_HEADER.pack(kind, flags, req_id, len(payload)) + payload


def unpack_header(buf) -> Tuple[int, int, int, int]:
    """(kind, flags, req_id, length); caller slices the payload."""
    kind, flags, req_id, length = FRAME_HEADER.unpack_from(buf, 0)
    if length > MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds limit")
    return kind, flags, req_id, length
