"""Transport layer: point-to-point request/response RPC between nodes
(reference: src/net/)."""

from .rpc import (
    RPC,
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    JoinRequest,
    JoinResponse,
    SyncRequest,
    SyncResponse,
)
from .transport import RemoteError, Transport, TransportError
from .inmem import InmemNetwork, InmemTransport
from .tcp import TCPTransport
from .atcp import AsyncTCPTransport
from .chaos import (
    ChaosController,
    ChaosTransport,
    LinkFaults,
    Nemesis,
    NemesisStep,
)

__all__ = [
    "RPC",
    "SyncRequest",
    "SyncResponse",
    "EagerSyncRequest",
    "EagerSyncResponse",
    "FastForwardRequest",
    "FastForwardResponse",
    "JoinRequest",
    "JoinResponse",
    "Transport",
    "TransportError",
    "RemoteError",
    "InmemNetwork",
    "InmemTransport",
    "TCPTransport",
    "AsyncTCPTransport",
    "ChaosController",
    "ChaosTransport",
    "LinkFaults",
    "Nemesis",
    "NemesisStep",
]
