"""Transport layer: point-to-point request/response RPC between nodes
(reference: src/net/)."""

from .rpc import (
    RPC,
    EagerSyncRequest,
    EagerSyncResponse,
    FastForwardRequest,
    FastForwardResponse,
    JoinRequest,
    JoinResponse,
    SyncRequest,
    SyncResponse,
)
from .transport import Transport, TransportError
from .inmem import InmemNetwork, InmemTransport
from .tcp import TCPTransport

__all__ = [
    "RPC",
    "SyncRequest",
    "SyncResponse",
    "EagerSyncRequest",
    "EagerSyncResponse",
    "FastForwardRequest",
    "FastForwardResponse",
    "JoinRequest",
    "JoinResponse",
    "Transport",
    "TransportError",
    "InmemNetwork",
    "InmemTransport",
    "TCPTransport",
]
