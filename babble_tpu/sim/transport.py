"""Synchronous in-memory transport for the simulation engine.

A ``SimTransport`` satisfies the same surface as
:class:`~babble_tpu.net.inmem.InmemTransport`, but delivery is a direct
function call: ``sync(target, req)`` runs the target's registered RPC
handler *inside the caller's scheduler event* and returns the response.
No queues between nodes, no threads, no timeouts — a request either
reaches a live handler (and its full server-side processing happens
now, deterministically ordered inside the current event) or raises
``TransportError`` immediately (target down / unregistered), which is
exactly what the chaos layer's partitions compose with.

Latency still exists: wrap a ``SimTransport`` in a ``ChaosTransport``
whose controller sleeps on the ``SimClock`` — delay faults advance
virtual time, so commit-latency histograms see them.
"""

from __future__ import annotations

import queue
from typing import Callable, Dict, Set

from ..net.rpc import RPC
from ..net.transport import RemoteError, TransportError


class SimNetwork:
    """addr -> handler registry plus a down-set (crash churn)."""

    def __init__(self) -> None:
        self._handlers: Dict[str, Callable[[RPC], None]] = {}
        self._down: Set[str] = set()
        self.requests = 0

    def register(self, addr: str, handler: Callable[[RPC], None]) -> None:
        self._handlers[addr] = handler

    def unregister(self, addr: str) -> None:
        self._handlers.pop(addr, None)

    def set_down(self, addr: str) -> None:
        self._down.add(addr)

    def set_up(self, addr: str) -> None:
        self._down.discard(addr)

    def is_down(self, addr: str) -> bool:
        return addr in self._down

    def request(self, src: str, target: str, command):
        if src in self._down:
            # a crashed node's in-flight call fails too (the driver stops
            # ticking it, but a sleep-delayed RPC may still be unwinding)
            raise TransportError(f"sim: {src} is down")
        handler = self._handlers.get(target)
        if handler is None or target in self._down:
            raise TransportError(f"sim: no transport listening on {target}")
        self.requests += 1
        rpc = RPC(command)
        handler(rpc)  # synchronous: the peer's full handler runs HERE
        try:
            result, error = rpc.wait(timeout=0)
        except queue.Empty:
            raise TransportError(f"sim: {target} returned no response")
        if error:
            raise RemoteError(error)
        return result


class SimTransport:
    """Transport facade bound to one address on a :class:`SimNetwork`."""

    def __init__(self, network: SimNetwork, addr: str):
        self.network = network
        self.addr = addr
        self.closed = False
        # Node._do_background_work would drain this in threaded mode; the
        # sim never starts that thread, but the attribute keeps the
        # Transport surface complete.
        self._consumer: "queue.Queue[RPC]" = queue.Queue()

    def consumer(self) -> "queue.Queue[RPC]":
        return self._consumer

    def local_addr(self) -> str:
        return self.addr

    def advertise_addr(self) -> str:
        return self.addr

    def listen(self) -> None:
        """No-op: handlers are registered by the harness."""

    def sync(self, target: str, req):
        return self.network.request(self.addr, target, req)

    def eager_sync(self, target: str, req):
        return self.network.request(self.addr, target, req)

    def fast_forward(self, target: str, req):
        return self.network.request(self.addr, target, req)

    def join(self, target: str, req):
        return self.network.request(self.addr, target, req)

    def close(self) -> None:
        self.closed = True
        self.network.unregister(self.addr)
