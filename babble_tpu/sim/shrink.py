"""Failure shrinking + replayable reproducer artifacts.

When a swept scenario violates an invariant, rerunning the full spec is
a bad starting point for debugging: it may carry five fault dimensions
when one suffices. ``shrink`` performs greedy delta-debugging over the
spec, in a deterministic candidate order:

1. drop nemesis steps (first halves, then single steps);
2. drop churn windows, the flood burst, and adversaries;
3. zero ambient fault rates (drop / duplicate / corrupt / delay);
4. remove a node; halve the fault-window duration.

A candidate replaces the current best only if it STILL fails (any
violation); the loop restarts from the smallest reductions until no
candidate fails or the run budget is exhausted. The result is a
strictly smaller (``ScenarioSpec.size()``) spec with a failing run —
never a guess.

The artifact is a self-contained JSON file: the shrunk spec, the
violations, and the run's determinism digests (commit sequences, event
log). ``replay_artifact`` re-executes the spec and reports whether the
digests still match — byte-level reproduction, not vibes.
"""

from __future__ import annotations

import json
from typing import Callable, Iterator, Optional, Tuple

from .scenario import ScenarioResult, ScenarioSpec, run_scenario

ARTIFACT_FORMAT = "babble-sim-repro/1"


def _candidates(spec: ScenarioSpec) -> Iterator[ScenarioSpec]:
    """Reductions in the order tried; every yield is strictly smaller."""
    n = spec.nemesis
    if len(n) > 1:
        half = len(n) // 2
        yield spec.with_(nemesis=n[:half])
        yield spec.with_(nemesis=n[half:])
    for i in range(len(n)):
        yield spec.with_(nemesis=n[:i] + n[i + 1:])
    c = spec.churn
    for i in range(len(c)):
        yield spec.with_(churn=c[:i] + c[i + 1:])
    if spec.flood is not None:
        yield spec.with_(flood=None)
    if spec.byzantine > 0:
        # churn indexes address the combined honest+byzantine range, so
        # dropping an adversary slot must drop churn that referenced it
        top = spec.nodes + spec.byzantine - 1
        yield spec.with_(
            byzantine=spec.byzantine - 1,
            churn=[x for x in c if x["node"] < top],
        )
    for dim in ("drop", "duplicate", "corrupt"):
        if getattr(spec, dim) > 0.0:
            yield spec.with_(**{dim: 0.0})
    if spec.delay_max_s > 0.0:
        yield spec.with_(delay_min_s=0.0, delay_max_s=0.0)
    if spec.nodes > 3:
        # churn/flood node indexes must stay in range after the removal
        nn = spec.nodes - 1
        churn = [x for x in spec.churn if x["node"] < nn + spec.byzantine]
        flood = spec.flood
        if flood is not None and flood.get("node", 0) >= nn:
            flood = dict(flood, node=0)
        yield spec.with_(nodes=nn, churn=churn, flood=flood)
    if spec.duration_s > 1.0:
        d = round(spec.duration_s / 2.0, 3)
        yield spec.with_(
            duration_s=d,
            nemesis=[s for s in spec.nemesis if s["at"] < d],
            churn=[s for s in spec.churn if s["at"] < d],
            flood=(spec.flood if spec.flood and spec.flood["at"] < d
                   else None),
        )


def shrink(
    spec: ScenarioSpec,
    runner: Callable[[ScenarioSpec], ScenarioResult] = run_scenario,
    max_runs: int = 40,
) -> Tuple[ScenarioSpec, ScenarioResult, int]:
    """Greedy reduction of a FAILING spec. Returns (smallest failing
    spec, its result, number of shrink runs). Raises ValueError if the
    input spec does not fail."""
    best_res = runner(spec)
    if best_res.ok:
        raise ValueError("shrink() needs a failing scenario")
    best = spec
    runs = 0
    improved = True
    while improved and runs < max_runs:
        improved = False
        for cand in _candidates(best):
            if runs >= max_runs:
                break
            runs += 1
            try:
                res = runner(cand)
            except Exception:
                # a reduction can compose into a spec the runner rejects
                # (e.g. cross-field validation); skip it — aborting the
                # sweep would lose the reproducer for the real failure
                continue
            if not res.ok:
                assert cand.size() < best.size(), "candidate must shrink"
                best, best_res = cand, res
                improved = True
                break
    return best, best_res, runs


# -- replay artifacts -----------------------------------------------------


def artifact_dict(
    spec: ScenarioSpec, result: ScenarioResult, shrink_runs: int = 0,
    original: Optional[ScenarioSpec] = None,
) -> dict:
    return {
        "format": ARTIFACT_FORMAT,
        "spec": spec.to_dict(),
        "original_spec": original.to_dict() if original else None,
        "shrink_runs": shrink_runs,
        "violations": result.violations,
        "commit_digests": result.commit_digests,
        "event_log_digest": result.event_log_digest,
        "telemetry_digest": result.telemetry_digest,
        "commits": result.commits,
        "virtual_s": result.virtual_s,
    }


def write_artifact(path: str, spec: ScenarioSpec, result: ScenarioResult,
                   shrink_runs: int = 0,
                   original: Optional[ScenarioSpec] = None) -> str:
    with open(path, "w") as f:
        json.dump(artifact_dict(spec, result, shrink_runs, original), f,
                  indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_artifact(path: str) -> dict:
    with open(path) as f:
        art = json.load(f)
    if art.get("format") != ARTIFACT_FORMAT:
        raise ValueError(f"not a sim reproducer artifact: {path}")
    return art


def replay_artifact(path: str) -> Tuple[ScenarioResult, bool]:
    """Re-run a reproducer. Returns (fresh result, digests_match) —
    ``digests_match`` is the byte-identical-replay check (commit
    sequences AND event interleaving)."""
    art = load_artifact(path)
    spec = ScenarioSpec.from_dict(art["spec"])
    result = run_scenario(spec)
    match = (
        result.commit_digests == art["commit_digests"]
        and result.event_log_digest == art["event_log_digest"]
    )
    return result, match
