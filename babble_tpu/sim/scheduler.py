"""SimScheduler: the deterministic single-threaded event loop.

One heap of ``(time, seq, label, fn)`` entries; events run strictly in
(time, insertion) order on the calling thread, with the
:class:`~babble_tpu.sim.clock.SimClock` advanced to each event's
timestamp before it fires. Because a whole gossip round — pull RPC,
the peer's handler, the insert sweep, the push leg — executes
*synchronously inside one event*, the interleaving of the simulation
is exactly the order of this heap, which is a pure function of the
schedule and of the seeded RNG streams below.

RNG streams: ``rng(name)`` returns a ``random.Random`` seeded from
``f"{seed}|{name}"`` and cached, one per actor (per-node tick jitter,
per-node selector, the tx mix, the scenario generator). An actor's
draws can never be perturbed by another actor running more or fewer
times — the same trick the chaos layer uses per directed link.

The event log is bounded: every executed event (time, seq, label) is
absorbed into a ROLLING sha256 at execution time — the digest is the
canonical "same interleaving" witness over the FULL run that the
determinism property test and the sweep's ``--dump`` output compare —
while ``event_log`` itself keeps only the most recent
``EVENT_LOG_TAIL`` entries for inspection, so a long or high-tick-rate
scenario can't grow memory linearly with virtual time.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import json
import random
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from .clock import SimClock

# Inspection tail kept in memory; the digest covers every event regardless.
EVENT_LOG_TAIL = 65536


class SimScheduler:
    def __init__(self, seed: int, start: float = 0.0):
        self.seed = seed
        self.clock = SimClock(start)
        self._heap: List[Tuple[float, int, str, Callable[[], None]]] = []
        self._seq = itertools.count()
        self._rngs: Dict[str, random.Random] = {}
        self.events_run = 0
        # (time, seq, label) per executed event — bounded inspection tail;
        # the rolling hash below is the complete interleaving record
        self.event_log: Deque[Tuple[float, int, str]] = deque(
            maxlen=EVENT_LOG_TAIL
        )
        self._log_hash = hashlib.sha256()

    # -- rng streams ----------------------------------------------------

    def rng(self, stream: str) -> random.Random:
        r = self._rngs.get(stream)
        if r is None:
            r = random.Random(f"{self.seed}|{stream}")
            self._rngs[stream] = r
        return r

    # -- scheduling -----------------------------------------------------

    @property
    def now(self) -> float:
        return self.clock.now

    def at(self, t: float, fn: Callable[[], None], label: str) -> None:
        """Schedule ``fn`` at virtual time ``t`` (past times fire at the
        next opportunity, in timestamp order)."""
        heapq.heappush(self._heap, (float(t), next(self._seq), label, fn))

    def after(self, dt: float, fn: Callable[[], None], label: str) -> None:
        self.at(self.clock.now + dt, fn, label)

    # -- running --------------------------------------------------------

    def run_until(self, t_end: float) -> int:
        """Execute every event scheduled at or before ``t_end`` (including
        ones those events schedule), then advance the clock to ``t_end``.
        Returns the number of events executed."""
        ran = 0
        while self._heap and self._heap[0][0] <= t_end:
            t, seq, label, fn = heapq.heappop(self._heap)
            # never rewind: an event that overslept (a handler called
            # sleep) pushes later events to fire "late" but in order
            self.clock.advance_to(t)
            entry = (round(t, 9), seq, label)
            self.event_log.append(entry)
            self._log_hash.update(
                json.dumps(entry, separators=(",", ":")).encode() + b"\n"
            )
            self.events_run += 1
            ran += 1
            fn()
        self.clock.advance_to(t_end)
        return ran

    def run_for(self, dt: float) -> int:
        return self.run_until(self.clock.now + dt)

    def pending(self) -> int:
        return len(self._heap)

    # -- determinism witness --------------------------------------------

    def event_log_digest(self) -> str:
        """sha256 over EVERY executed event (rolling, so the full run is
        witnessed even past the bounded inspection tail) — two runs
        interleaved identically iff their digests match."""
        return self._log_hash.hexdigest()
