"""Deterministic simulation engine (docs/simulation.md).

FoundationDB-style testing for the consensus stack: a virtual-time
clock (:mod:`.clock`), a single-threaded event scheduler
(:mod:`.scheduler`), a synchronous in-memory transport
(:mod:`.transport`), a harness that drives REAL ``Node`` /
``ByzantineNode`` objects as scheduled events (:mod:`.harness`), a
declarative scenario layer composing chaos, Byzantine attacks, churn
and mempool floods (:mod:`.scenario`), failure shrinking with
replayable artifacts (:mod:`.shrink`), and the seeded sweep driver
(``python -m babble_tpu.sim.sweep``).
"""

from .clock import SimClock
from .scheduler import SimScheduler
from .scenario import ScenarioSpec, ScenarioResult, run_scenario
from .shrink import shrink, write_artifact, load_artifact, replay_artifact

__all__ = [
    "SimClock",
    "SimScheduler",
    "ScenarioSpec",
    "ScenarioResult",
    "run_scenario",
    "shrink",
    "write_artifact",
    "load_artifact",
    "replay_artifact",
]
