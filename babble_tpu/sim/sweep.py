"""Seeded scenario sweep: ``python -m babble_tpu.sim.sweep``.

Generates N scenario combinations — chaos profile x Byzantine attack x
crash churn x mempool flood, each dimension drawn from a seeded stream
— runs them all in virtual time, and on any invariant violation shrinks
the failing spec to a minimal reproducer written as a replayable JSON
artifact (babble_tpu.sim.shrink).

The last stdout line is a compact JSON summary (same tail-capture
contract as bench.py); everything else goes to stderr. Determinism
contract: the same ``--seed``/``--seeds`` invocation produces
byte-identical commit sequences and event logs — verify with
``--dump FILE`` twice and compare the files.

Typical invocations:

    python -m babble_tpu.sim.sweep --seeds 200            # make simsmoke
    python -m babble_tpu.sim.sweep --seeds 2000           # make simsweep
    python -m babble_tpu.sim.sweep --seeds 1 --seed 7 --dump a.json
    python -m babble_tpu.sim.sweep --replay artifact.json
    python -m babble_tpu.sim.sweep --seeds 5 --inject-failure --out d/
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from typing import List, Optional

from .harness import sim_addr
from .scenario import ScenarioSpec, run_scenario
from .shrink import replay_artifact, shrink, write_artifact

CHAOS_PROFILES = (
    "none", "drop", "drop", "partition", "partition_drop", "flap", "slow",
)
BYZ_ATTACKS = (
    "none", "none", "none",
    "equivocate", "equivocate", "wrong_key", "oversize", "replay",
    "lying_known", "garbage",
)


def generate_scenario(master_seed: int, i: int) -> ScenarioSpec:
    """Deterministic combination #i for a master seed. Budgets are sized
    for sweep throughput: ~1 virtual second of faults on 3-5 nodes; the
    settle phase extends itself when convergence needs longer."""
    rng = random.Random(f"{master_seed}|scenario|{i}")
    seed = int(rng.getrandbits(32))
    nodes = rng.choice((3, 3, 4, 4, 5))
    chaos = rng.choice(CHAOS_PROFILES)
    attack = rng.choice(BYZ_ATTACKS)
    byz = 0
    if attack != "none":
        # stay inside the BFT bound: f >= 1 needs >= 4 validators
        if nodes < 4:
            nodes = 4
        byz = 1
        nodes -= 1  # keep total validators modest: n_honest + 1 adversary
    duration = round(rng.uniform(0.7, 1.1), 3)
    spec = ScenarioSpec(
        seed=seed,
        name=f"s{i}:{chaos}+{attack}",
        nodes=nodes,
        byzantine=byz,
        attack=attack if attack != "none" else "equivocate",
        duration_s=duration,
        heartbeat_s=0.08,
        tx_rate=5.0,
        settle_s=0.8,
        settle_rounds=6,
        mempool_max_txs=256,
    )
    n_total = nodes + byz
    addrs = [sim_addr(k) for k in range(n_total)]
    if chaos == "drop":
        spec = spec.with_(drop=round(rng.uniform(0.05, 0.2), 3),
                          duplicate=0.05)
    elif chaos == "slow":
        spec = spec.with_(delay_min_s=0.001, delay_max_s=0.01)
    elif chaos in ("partition", "partition_drop"):
        cut = rng.randrange(1, n_total)
        t0 = round(rng.uniform(0.1, 0.3), 3)
        heal = round(t0 + rng.uniform(0.3, duration - t0), 3)
        spec = spec.with_(
            nemesis=[
                {"at": t0, "op": "partition",
                 "kwargs": {"groups": [addrs[:cut], addrs[cut:]]}},
                {"at": heal, "op": "heal", "kwargs": {}},
            ],
            drop=(0.1 if chaos == "partition_drop" else 0.0),
        )
    elif chaos == "flap":
        victim = rng.randrange(n_total)
        spec = spec.with_(nemesis=[
            {"at": 0.2, "op": "isolate",
             "kwargs": {"addr": addrs[victim], "others": addrs}},
            {"at": 0.6, "op": "heal_peer",
             "kwargs": {"addr": addrs[victim], "others": addrs}},
        ])
    if rng.random() < 0.25:
        victim = rng.randrange(nodes)  # churn an HONEST node
        down = round(rng.uniform(0.1, 0.4), 3)
        up = round(down + rng.uniform(0.2, 0.5), 3)
        spec = spec.with_(churn=[
            {"at": down, "node": victim, "action": "down"},
            {"at": up, "node": victim, "action": "up"},
        ])
    if rng.random() < 0.25:
        spec = spec.with_(flood={
            "at": round(rng.uniform(0.1, 0.5), 3),
            "count": 400,
            "node": rng.randrange(nodes),
        })
    return spec


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m babble_tpu.sim.sweep",
        description="seeded virtual-time scenario sweep with shrinking",
    )
    ap.add_argument("--seeds", type=int, default=100,
                    help="number of scenario combinations to run")
    ap.add_argument("--seed", type=int, default=42, help="master seed")
    ap.add_argument("--out", default="sim_artifacts",
                    help="directory for failure reproducer artifacts")
    ap.add_argument("--dump", default="",
                    help="write per-scenario determinism digests here")
    ap.add_argument("--no-shrink", action="store_true",
                    help="record failures without shrinking them")
    ap.add_argument("--max-shrink-runs", type=int, default=40)
    ap.add_argument("--inject-failure", action="store_true",
                    help="force scenario #0 to violate a pseudo-invariant "
                         "(CI proof that shrinking + artifacts work)")
    ap.add_argument("--replay", default="",
                    help="re-run a reproducer artifact and exit")
    args = ap.parse_args(argv)

    if args.replay:
        result, match = replay_artifact(args.replay)
        print(json.dumps({
            "replay": args.replay,
            "violations": result.violations,
            "digests_match": match,
            "commits": result.commits,
        }, sort_keys=True))
        return 0 if (result.violations and match) else 1

    wall0 = time.perf_counter()
    passed = failed = shrunk = 0
    commits_total = 0
    events_total = 0
    virtual_total = 0.0
    artifacts: List[str] = []
    violations_by_invariant: dict = {}
    dump_rows = []

    for i in range(args.seeds):
        spec = generate_scenario(args.seed, i)
        if args.inject_failure and i == 0:
            if not spec.nemesis:
                spec = spec.with_(nemesis=[
                    {"at": 0.2, "op": "partition", "kwargs": {"groups": [
                        [sim_addr(0)],
                        [sim_addr(k)
                         for k in range(1, spec.nodes + spec.byzantine)],
                    ]}},
                    {"at": 0.5, "op": "heal", "kwargs": {}},
                ])
            spec = spec.with_(inject_failure=True)
        result = run_scenario(spec)
        commits_total += max(result.commits) + 1 if result.commits else 0
        events_total += result.events_run
        virtual_total += result.virtual_s
        if result.ok:
            passed += 1
        else:
            failed += 1
            for v in result.violations:
                violations_by_invariant[v["invariant"]] = (
                    violations_by_invariant.get(v["invariant"], 0) + 1
                )
            print(
                f"FAIL {spec.name} seed={spec.seed}: {result.violations}",
                file=sys.stderr,
            )
            small, small_res, runs = spec, result, 0
            if not args.no_shrink:
                small, small_res, runs = shrink(
                    spec, max_runs=args.max_shrink_runs
                )
                shrunk += 1
                print(
                    f"  shrunk {spec.size()} -> {small.size()} "
                    f"in {runs} runs",
                    file=sys.stderr,
                )
            os.makedirs(args.out, exist_ok=True)
            path = os.path.join(
                args.out, f"repro_{i}_{small.digest()}.json"
            )
            write_artifact(path, small, small_res, runs, original=spec)
            artifacts.append(path)
        if args.dump:
            dump_rows.append({
                "i": i,
                "name": spec.name,
                "spec_digest": spec.digest(),
                "commit_digests": result.commit_digests,
                "event_log_digest": result.event_log_digest,
                "telemetry_digest": result.telemetry_digest,
                "violations": result.violations,
            })

    if args.dump:
        with open(args.dump, "w") as f:
            json.dump(dump_rows, f, indent=1, sort_keys=True)
            f.write("\n")

    wall = time.perf_counter() - wall0
    summary = {
        "sim_scenarios": args.seeds,
        "passed": passed,
        "failed": failed,
        "shrunk": shrunk,
        "violations": violations_by_invariant,
        "artifacts": artifacts[:5],
        "blocks_committed": commits_total,
        "sim_events": events_total,
        "virtual_s": round(virtual_total, 1),
        "wall_s": round(wall, 1),
        "scenarios_per_s": round(args.seeds / wall, 2) if wall else None,
        "speedup_virtual": round(virtual_total / wall, 1) if wall else None,
        "seed": args.seed,
    }
    # Runtime lock-order audit (docs/static_analysis.md §Lock model):
    # with BABBLE_LOCKCHECK=1 the whole sweep doubles as an empirical
    # check of the static lock graph — simsmoke asserts zero inversions.
    from ..common import lockcheck

    if lockcheck.ENABLED:
        summary["lock_order_edges"] = len(lockcheck.RECORDER.edge_list())
        summary["lock_inversions"] = len(lockcheck.RECORDER.inversions())
    line = json.dumps(summary, sort_keys=True)
    assert len(line) < 2000, "summary line contract: keep it compact"
    print(line)
    # exit nonzero on violations so a bare `make simsweep` (no assertion
    # pipe) still fails CI; artifacts are on disk either way
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
