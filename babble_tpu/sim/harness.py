"""Sim harness: REAL nodes driven as scheduler events.

This is the layer that makes the simulation honest: the objects under
test are the production :class:`~babble_tpu.node.node.Node` and
:class:`~babble_tpu.adversary.byzantine.ByzantineNode` — same gossip
legs, same RPC handlers, same mempool/sentry/selector/telemetry — with
exactly three substitutions:

1. the node ``Clock`` is the scheduler's :class:`SimClock`;
2. the transport is a :class:`SimTransport` (synchronous delivery)
   wrapped in the production ``ChaosTransport`` whose controller sleeps
   on virtual time;
3. the thread-shaped drivers (``run()``'s state loop, the control
   timer, the background worker, the adversary's attack/serve loops)
   are replaced by scheduler events that call the same internal methods
   those threads call: ``_gossip`` / ``_monologue`` on a jittered
   heartbeat for honest nodes, one pull+attack round per tick for the
   adversary, and ``_process_rpc`` / ``_serve_one`` as the inbound
   handler.

Determinism inputs: node keys are derived from the master seed, the
selector/tick RNGs are scheduler streams, event timestamps come off
the virtual clock, and signing is forced onto the RFC 6979 path (the
scenario layer flips that switch) because the consensus order breaks
ties on signature ``r``.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional

from ..adversary.byzantine import ByzantineNode
from ..config.config import Config
from ..crypto import secp256k1 as _curve
from ..crypto.keys import PrivateKey
from ..dummy.state import State as DummyState
from ..hashgraph.store import InmemStore
from ..net.chaos import ChaosController, ChaosTransport, LinkFaults
from ..node.node import Node
from ..node.state import State
from ..node.validator import Validator
from ..peers.peer import Peer
from ..peers.peer_set import PeerSet
from .scheduler import SimScheduler
from .transport import SimNetwork, SimTransport


def sim_key(seed: int, i: int) -> PrivateKey:
    """Deterministic validator key #i for master seed ``seed``."""
    h = hashlib.sha256(f"babble-sim|{seed}|key|{i}".encode()).digest()
    d = (int.from_bytes(h, "big") % (_curve.N - 1)) + 1
    return PrivateKey(d)


def sim_addr(i: int) -> str:
    return f"sim://node{i}"


class _HonestDriver:
    """One node's gossip heartbeat as a self-rescheduling event.

    With the adaptive scheduler on (``node.adaptive``), each tick asks
    the node for its plan — the SAME control law the threaded
    ControlTimer path runs, fed by the same virtual-time signals, so
    adaptation is simulated honestly and deterministically (the law is
    pure arithmetic; the only randomness is this driver's seeded
    jitter stream). With it off, the seed's fixed cadence."""

    def __init__(self, node: Node, sch: SimScheduler, idx: int,
                 heartbeat_s: float):
        self.node = node
        self.sch = sch
        self.idx = idx
        self.heartbeat_s = heartbeat_s
        self.rng = sch.rng(f"tick|{idx}")
        self.down = False

    def start(self) -> None:
        # staggered first tick, mirroring ControlTimer's [hb, 2hb) jitter
        self.sch.at(
            self.rng.uniform(0.0, self.heartbeat_s),
            self._tick,
            f"tick|n{self.idx}",
        )

    def _tick(self) -> None:
        node = self.node
        interval = self.heartbeat_s
        if not self.down and node.get_state() == State.BABBLING:
            fanout = 1
            if node.adaptive is not None:
                plan_interval, fanout = node.gossip_plan()
                # the plan's rails are the node Config's heartbeat
                # pair, which SimCluster derives from heartbeat_s — so
                # the adaptive interval replaces the fixed cadence
                interval = plan_interval
            peers = node.core.peer_selector.next_many(fanout)
            if peers:
                for peer in peers:
                    node._gossip(peer)
            else:
                node._monologue()
        # jittered cadence in [iv, 2*iv) — same law as the control timer
        self.sch.after(
            interval * (1.0 + self.rng.random()),
            self._tick,
            f"tick|n{self.idx}",
        )


class _ByzantineDriver:
    """One attack round per tick: the body of ByzantineNode._attack_loop
    as a scheduler event (pull to stay current, then the named attack)."""

    def __init__(self, byz: ByzantineNode, sch: SimScheduler, idx: int,
                 heartbeat_s: float):
        self.byz = byz
        self.sch = sch
        self.idx = idx
        self.heartbeat_s = heartbeat_s
        self.rng = sch.rng(f"tick|{idx}")
        self.attacking = True
        self._step = getattr(byz, f"_step_{byz.attack}")

    def start(self) -> None:
        self.sch.at(
            self.rng.uniform(0.0, self.heartbeat_s),
            self._tick,
            f"tick|byz{self.idx}",
        )

    def _tick(self) -> None:
        byz = self.byz
        if self.attacking:
            targets = byz._targets()
            if targets:
                peer = byz._rng.choice(targets)
                try:
                    byz._pull(peer)
                    byz.pulls += 1
                except Exception:  # noqa: BLE001 — faults are expected
                    byz.pull_errors += 1
                try:
                    self._step(targets)
                except Exception:  # noqa: BLE001 — attacks never crash us
                    byz.push_errors += 1
        self.sch.after(
            self.heartbeat_s * (1.0 + self.rng.random()),
            self._tick,
            f"tick|byz{self.idx}",
        )


class SimCluster:
    """n honest nodes (+ optional adversaries) on one SimNetwork under
    one seeded ChaosController, all clocked by the scheduler."""

    def __init__(
        self,
        sch: SimScheduler,
        n_honest: int,
        n_byzantine: int = 0,
        attack: str = "equivocate",
        heartbeat_s: float = 0.05,
        faults: Optional[LinkFaults] = None,
        sync_limit: int = 256,
        mempool_max_txs: int = 512,
        split: bool = False,
        trace_sample: Optional[float] = None,
        adaptive: bool = True,
        store_factory: Optional[Callable[[int], object]] = None,
        conf_extra: Optional[dict] = None,
    ):
        self.sch = sch
        self.network = SimNetwork()
        # virtual-time chaos: delay faults advance the SimClock, drop
        # holds cost virtual (not wall) time, duplicates deliver inline
        self.controller = ChaosController(
            seed=sch.seed,
            default_faults=faults or LinkFaults(),
            drop_hold_s=0.005,
            sleep=sch.clock.sleep,
            spawn=lambda fn: fn(),
        )
        n = n_honest + n_byzantine
        keys = [sim_key(sch.seed, i) for i in range(n)]
        self.peers = PeerSet(
            [
                Peer(sim_addr(i), k.public_key.hex(), f"node{i}")
                for i, k in enumerate(keys)
            ]
        )
        self.addrs = [sim_addr(i) for i in range(n)]
        self.n_honest = n_honest

        # Per-node store override (the lifecycle plateau sims swap in a
        # PersistentStore so prune/vacuum byte accounting is real).
        if store_factory is None:
            store_factory = lambda i: InmemStore(10000)  # noqa: E731

        def conf(i: int) -> Config:
            kw = dict(conf_extra or {})
            if trace_sample is not None:
                # provenance sampling override (the determinism tests
                # trace every tx; stamps ride the SimClock, so same-seed
                # runs export byte-identical provenance)
                kw["trace_sample"] = trace_sample
            c = Config(
                heartbeat_timeout=heartbeat_s,
                slow_heartbeat_timeout=4 * heartbeat_s,
                moniker=f"node{i}",
                log_level="error",
                no_service=True,
                sync_limit=sync_limit,
                mempool_max_txs=mempool_max_txs,
                clock=sch.clock,
                sim_seed=sch.seed,
                **kw,
            )
            # Pinned AFTER construction: the BABBLE_ADAPT env override
            # (an operator switch for live clusters) must not silently
            # flip a sim A/B arm — adaptive=False IS the control arm of
            # the adaptive-vs-fixed recovery tests.
            c.adaptive_gossip = adaptive
            return c

        self.nodes: List[Node] = []
        self.proxies = []
        self.states: List[DummyState] = []
        self.drivers: List[_HonestDriver] = []
        from ..proxy.proxy import InmemProxy

        for i in range(n_honest):
            trans = ChaosTransport(
                SimTransport(self.network, self.addrs[i]), self.controller
            )
            state = DummyState()
            proxy = InmemProxy(state)
            node = Node(
                conf(i), Validator(keys[i], f"node{i}"), self.peers,
                self.peers, store_factory(i), trans, proxy,
            )
            node.init()
            self.network.register(
                self.addrs[i], node._process_rpc
            )
            self.nodes.append(node)
            self.proxies.append(proxy)
            self.states.append(state)
            self.drivers.append(_HonestDriver(node, sch, i, heartbeat_s))

        self.byzantine: List[ByzantineNode] = []
        self.byz_drivers: List[_ByzantineDriver] = []
        for j in range(n_byzantine):
            i = n_honest + j
            trans = ChaosTransport(
                SimTransport(self.network, self.addrs[i]), self.controller
            )
            byz = ByzantineNode(
                conf(i), Validator(keys[i], f"node{i}"), self.peers,
                self.peers, InmemStore(10000), trans,
                attack=attack, split=split,
                seed=int(
                    hashlib.sha256(
                        f"babble-sim|{sch.seed}|byz|{j}".encode()
                    ).hexdigest()[:8],
                    16,
                ),
            )
            self.network.register(self.addrs[i], self._byz_handler(byz))
            self.byzantine.append(byz)
            self.byz_drivers.append(_ByzantineDriver(byz, sch, i, heartbeat_s))

        # tx accounting for the exactly-once invariant: payload -> node
        # index whose mempool ACCEPTED it
        self.accepted: Dict[bytes, int] = {}
        self._tx_seq = 0

    @staticmethod
    def _byz_handler(byz: ByzantineNode) -> Callable:
        def handler(rpc) -> None:
            byz.served += 1
            try:
                byz._serve_one(rpc)
            except Exception:  # noqa: BLE001
                try:
                    rpc.respond(None, "byzantine")
                except Exception:
                    pass

        return handler

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        for d in self.drivers:
            d.start()
        for d in self.byz_drivers:
            d.start()

    def shutdown(self) -> None:
        for node in self.nodes:
            node.shutdown()
        for byz in self.byzantine:
            byz.core.hg.store.close()

    # -- scenario hooks -------------------------------------------------

    def submit(self, node_idx: int, payload: bytes) -> str:
        verdict = self.nodes[node_idx]._admit_transaction(payload)
        if verdict == "accepted":
            self.accepted[payload] = node_idx
        return verdict

    def submit_auto(self, rng) -> str:
        """One unique background transaction to an rng-chosen honest node."""
        self._tx_seq += 1
        payload = f"sim tx {self._tx_seq}".encode()
        return self.submit(rng.randrange(self.n_honest), payload)

    def set_node_down(self, i: int) -> None:
        """Crash-style churn: the node vanishes from the network and
        stops gossiping; its state (store, mempool) survives for the
        restart — the model is a machine reboot, not a disk loss."""
        self.network.set_down(self.addrs[i])
        if i < self.n_honest:
            self.drivers[i].down = True

    def set_node_up(self, i: int) -> None:
        self.network.set_up(self.addrs[i])
        if i < self.n_honest:
            self.drivers[i].down = False

    def heal(self) -> None:
        """Lift every fault: partitions, link faults, slow peers, downed
        nodes, and adversary attack rounds (it keeps serving)."""
        self.controller.heal()
        self.controller.clear_slow()
        self.controller.set_default_faults(LinkFaults())
        for i in range(len(self.addrs)):
            self.set_node_up(i)
        for d in self.byz_drivers:
            d.attacking = False

    # -- observations ---------------------------------------------------

    def honest_last_blocks(self) -> List[int]:
        return [n.get_last_block_index() for n in self.nodes]

    def committed_txs(self, i: int) -> List[bytes]:
        return self.states[i].committed_txs

    def commit_digest(self, i: int) -> str:
        """sha256 over the node's committed block-BODY hashes in order.
        Body hashes (not signatures) so the digest witnesses the decided
        contents + order, which is what must be identical across nodes
        and across same-seed runs."""
        node = self.nodes[i]
        h = hashlib.sha256()
        for bi in range(node.get_last_block_index() + 1):
            h.update(node.get_block(bi).body.hash())
        return h.hexdigest()

    def commit_digests(self) -> Dict[str, str]:
        return {f"node{i}": self.commit_digest(i)
                for i in range(self.n_honest)}

    def provenance_exports(self) -> List[dict]:
        """Every honest node's /traces-shaped provenance export — the
        input obs.traceview.merge_all consumes, identical to what a live
        cluster serves over HTTP."""
        return [n.get_traces(limit=-1) for n in self.nodes]

    def provenance_digest(self) -> str:
        """sha256 over every honest node's provenance export (stamps are
        SimClock time, ids are per-node tracer counters — byte-identical
        across same-seed runs; docs/simulation.md)."""
        import json as _json

        payload = _json.dumps(
            self.provenance_exports(), sort_keys=True,
            separators=(",", ":"), default=str,
        )
        return hashlib.sha256(payload.encode()).hexdigest()
