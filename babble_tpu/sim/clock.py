"""Virtual time for the simulation engine.

A ``SimClock`` is a :class:`~babble_tpu.common.clock.Clock` whose time
only moves when the scheduler (or a sleeper) advances it. Everything
the node stack reads through its injected clock — deadlines, backoff,
event timestamps, telemetry durations — becomes a pure function of the
event schedule: a 10-second soak costs microseconds of wall time and
two runs with the same seed read identical clocks.

``sleep`` advances time in place. Inside a scheduler event this means
the sleeping code blocks *virtually* — events scheduled inside the
slept window run after the current event returns (at their scheduled
time, which is then in the past, so in timestamp order immediately
after). That is a coarser interleaving than real threads produce, but
it is deterministic, which is the property the engine exists for; the
boundary is documented in docs/simulation.md.
"""

from __future__ import annotations

from ..common.clock import Clock

# Fixed wall-clock epoch for ``time()``: event bodies carry absolute
# timestamps, and determinism requires the epoch to be part of the sim,
# not of the host. 2023-11-14T22:13:20Z, for no particular reason.
SIM_EPOCH = 1_700_000_000.0


class SimClock(Clock):
    def __init__(self, start: float = 0.0, epoch: float = SIM_EPOCH):
        self.now = float(start)
        self.epoch = float(epoch)
        self.sleeps = 0
        self.slept_total_s = 0.0

    def monotonic(self) -> float:
        return self.now

    def perf_counter(self) -> float:
        return self.now

    def time(self) -> float:
        return self.epoch + self.now

    def sleep(self, seconds: float) -> None:
        if seconds > 0.0:
            self.sleeps += 1
            self.slept_total_s += seconds
            self.now += seconds

    def advance_to(self, t: float) -> None:
        """Move to ``t`` if it is in the future (never rewinds)."""
        if t > self.now:
            self.now = t
