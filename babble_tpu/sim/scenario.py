"""Declarative seeded scenarios + the invariant checker.

A :class:`ScenarioSpec` is a JSON-serializable description of one
simulated run: cluster shape (honest + Byzantine validators), ambient
link faults, a nemesis schedule (the same ``NemesisStep`` ops the
wall-clock chaos layer runs), crash/restart churn windows, a mempool
flood burst, and a background transaction mix. ``run_scenario``
executes it entirely in virtual time and checks four invariants:

- **no_fork** — every block in the honest nodes' common prefix is
  byte-identical (block-body hash);
- **liveness** — after every fault heals, all honest nodes commit at
  least one NEW block (the settle phase extends a bounded number of
  times before declaring a violation, so slow convergence isn't
  misread as a stall);
- **bounded_queues** — mempool pending never exceeds its cap and the
  undetermined-event set is bounded at the end;
- **exactly_once** — no transaction commits twice on any honest node,
  and every transaction a node's mempool ACCEPTED is committed on that
  node by the end (no loss).

``inject_failure=True`` adds a deliberately-failing pseudo-invariant
(it trips whenever the nemesis schedule is non-empty); the sweep uses
it to prove, in CI, that a failure actually shrinks to a minimal
replayable artifact.

Determinism boundary (docs/simulation.md): everything inside the
scheduler is seeded; signing is forced onto RFC 6979 for the run
because the consensus order breaks ties on signature ``r``.
"""

from __future__ import annotations

import hashlib
import json
import time
from dataclasses import asdict, dataclass, field, replace
from typing import Dict, List, Optional

from ..crypto.keys import set_deterministic_signing
from ..net.chaos import LinkFaults
from .harness import SimCluster, sim_addr
from .scheduler import SimScheduler

SPEC_FORMAT = "babble-sim-scenario/1"


@dataclass
class ScenarioSpec:
    seed: int = 42
    name: str = ""
    nodes: int = 4  # honest validators
    byzantine: int = 0  # adversarial validators (keep <= (n-1)//3)
    attack: str = "equivocate"
    split: bool = False
    duration_s: float = 2.0  # fault window (virtual seconds)
    heartbeat_s: float = 0.05
    # ambient link faults (every directed link, whole run until heal)
    drop: float = 0.0
    duplicate: float = 0.0
    corrupt: float = 0.0
    delay_min_s: float = 0.0
    delay_max_s: float = 0.0
    # scheduled fault transitions: [{"at": s, "op": name, "kwargs": {}}]
    # — ops are ChaosController methods, exactly like NemesisStep
    nemesis: List[dict] = field(default_factory=list)
    # crash churn: [{"at": s, "node": i, "action": "down"|"up"}]
    churn: List[dict] = field(default_factory=list)
    # mempool overload burst: {"at": s, "count": n, "node": i}
    flood: Optional[dict] = None
    tx_rate: float = 15.0  # background submissions/s across the cluster
    sync_limit: int = 256
    mempool_max_txs: int = 512
    settle_s: float = 2.0  # post-heal liveness window (extended, bounded)
    settle_rounds: int = 4
    max_undetermined: int = 600
    inject_failure: bool = False  # deliberate violation (shrink/CI proof)

    # -- codec ----------------------------------------------------------

    def to_dict(self) -> dict:
        d = asdict(self)
        d["format"] = SPEC_FORMAT
        return d

    @staticmethod
    def from_dict(d: dict) -> "ScenarioSpec":
        d = dict(d)
        fmt = d.pop("format", SPEC_FORMAT)
        if fmt != SPEC_FORMAT:
            raise ValueError(f"unknown scenario format {fmt!r}")
        return ScenarioSpec(**d)

    def digest(self) -> str:
        payload = json.dumps(self.to_dict(), sort_keys=True,
                             separators=(",", ":"))
        return hashlib.sha256(payload.encode()).hexdigest()[:16]

    def with_(self, **kw) -> "ScenarioSpec":
        return replace(self, **kw)

    def size(self) -> tuple:
        """Shrink ordering: a spec is strictly smaller when this tuple
        is (nodes+adversaries, scheduled fault count, ambient fault mass,
        duration) — lexicographically — smaller."""
        return (
            self.nodes + self.byzantine,
            len(self.nemesis) + len(self.churn)
            + (1 if self.flood else 0) + self.byzantine,
            round(self.drop + self.duplicate + self.corrupt
                  + self.delay_max_s, 6),
            round(self.duration_s, 6),
        )

    def validate(self) -> None:
        if self.nodes < 1:
            raise ValueError("need at least one honest node")
        if self.byzantine and self.nodes + self.byzantine < 4:
            raise ValueError(
                "byzantine scenarios need >= 4 validators (f >= 1)"
            )
        for step in self.nemesis:
            if "at" not in step or "op" not in step:
                raise ValueError(f"malformed nemesis step: {step}")
        for c in self.churn:
            if c.get("action") not in ("down", "up"):
                raise ValueError(f"malformed churn entry: {c}")
            if not 0 <= c.get("node", -1) < self.nodes + self.byzantine:
                raise ValueError(f"churn node out of range: {c}")


@dataclass
class ScenarioResult:
    spec_digest: str
    violations: List[dict]
    commit_digests: Dict[str, str]
    event_log_digest: str
    telemetry_digest: str
    events_run: int
    commits: List[int]  # last block index per honest node
    committed_txs: int  # node 0's committed tx count
    accepted_txs: int
    virtual_s: float
    wall_s: float
    liveness_ok: bool
    heal_base: int
    stats: Dict[str, object] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> dict:
        return asdict(self)

    def determinism_view(self) -> dict:
        """The byte-comparable subset: everything except wall time."""
        d = self.to_dict()
        d.pop("wall_s", None)
        return d


def _partition_groups(spec: ScenarioSpec, cut: int) -> List[List[str]]:
    """Addresses split into [0..cut) | [cut..n) — helper for generators."""
    n = spec.nodes + spec.byzantine
    return [[sim_addr(i) for i in range(cut)],
            [sim_addr(i) for i in range(cut, n)]]


def run_scenario(spec: ScenarioSpec) -> ScenarioResult:
    """Execute one spec under virtual time and evaluate the invariants."""
    spec.validate()
    wall0 = time.perf_counter()
    # The signing flip is process-wide state: restore it even when cluster
    # construction raises (bad spec knobs) or shutdown() itself fails —
    # a leaked True would silently put every later signature in this
    # process on the RFC 6979 path.
    prev_sig = set_deterministic_signing(True)
    cluster = None
    try:
        sch = SimScheduler(spec.seed)
        cluster = SimCluster(
            sch,
            spec.nodes,
            spec.byzantine,
            attack=spec.attack,
            split=spec.split,
            heartbeat_s=spec.heartbeat_s,
            faults=LinkFaults(
                drop=spec.drop,
                duplicate=spec.duplicate,
                corrupt=spec.corrupt,
                delay_min_s=spec.delay_min_s,
                delay_max_s=spec.delay_max_s,
            ),
            sync_limit=spec.sync_limit,
            mempool_max_txs=spec.mempool_max_txs,
        )
        cluster.start()
        txrng = sch.rng("txmix")

        # background transaction mix over the fault window
        if spec.tx_rate > 0:
            interval = 1.0 / spec.tx_rate
            t = interval
            while t < spec.duration_s:
                sch.at(t, lambda: cluster.submit_auto(txrng), "tx")
                t += interval

        # nemesis schedule as virtual-time events
        nemesis_fired: List[str] = []
        for step in spec.nemesis:
            op = step["op"]
            kwargs = step.get("kwargs", {})
            if not callable(getattr(cluster.controller, op, None)):
                raise ValueError(f"unknown nemesis op: {op!r}")

            def fire(op=op, kwargs=kwargs) -> None:
                getattr(cluster.controller, op)(**kwargs)
                nemesis_fired.append(op)

            sch.at(step["at"], fire, f"nemesis|{op}")

        # crash churn
        for c in spec.churn:
            fn = (cluster.set_node_down if c["action"] == "down"
                  else cluster.set_node_up)
            sch.at(
                c["at"],
                lambda fn=fn, i=c["node"]: fn(i),
                f"churn|{c['action']}|n{c['node']}",
            )

        # mempool flood burst
        if spec.flood:
            fl = dict(spec.flood)

            def do_flood(fl=fl) -> None:
                node = fl.get("node", 0) % spec.nodes
                for k in range(int(fl["count"])):
                    cluster.submit(node, f"flood tx {k}".encode())

            sch.at(fl["at"], do_flood, "flood")

        # phase 1: the fault window
        sch.run_until(spec.duration_s)

        # phase 2: heal everything, then drive until liveness (bounded)
        cluster.heal()
        heal_base = max(cluster.honest_last_blocks())
        liveness_ok = False
        for _ in range(spec.settle_rounds):
            for k in range(3):
                sch.after(
                    0.01 * (k + 1),
                    lambda: cluster.submit_auto(txrng),
                    "tx|settle",
                )
            sch.run_for(spec.settle_s)
            if min(cluster.honest_last_blocks()) >= heal_base + 1:
                liveness_ok = True
                break

        # phase 3: convergence drain — no new txs; keep ticking until
        # every accepted tx committed on its accepting node, mempools
        # drained, and all honest chains level. Bounded: a cluster that
        # cannot drain in the budget is a bounded/exactly-once violation,
        # not an excuse to run forever.
        committed_sets: List[set] = []
        for attempt in range(9):
            lbs = cluster.honest_last_blocks()
            committed_sets = [set(cluster.committed_txs(i))
                              for i in range(spec.nodes)]
            undrained = any(
                payload not in committed_sets[acceptor]
                for payload, acceptor in cluster.accepted.items()
            ) or any(
                n.core.mempool.pending_count > 0 for n in cluster.nodes
            )
            # the final pass only refreshes committed_sets (handed to
            # _evaluate below so it never rebuilds them) — no extra tick
            if (min(lbs) == max(lbs) and not undrained) or attempt == 8:
                break
            sch.run_for(1.0)

        violations = _evaluate(spec, cluster, liveness_ok, heal_base,
                               nemesis_fired, committed_sets)
        tele = hashlib.sha256(
            json.dumps(
                [cluster.nodes[i].telemetry.registry.snapshot()
                 for i in range(spec.nodes)],
                sort_keys=True, separators=(",", ":"), default=str,
            ).encode()
        ).hexdigest()
        stats: Dict[str, object] = dict(cluster.controller.stats())
        stats["sim_requests"] = cluster.network.requests
        sentry_stats = [n.core.sentry.stats() for n in cluster.nodes]
        stats["sentry_quarantined"] = [
            s["sentry_quarantined_peers"] for s in sentry_stats
        ]
        stats["sentry_proofs"] = [
            s["sentry_proofs"] for s in sentry_stats
        ]
        if cluster.byzantine:
            stats["byz"] = [b.stats() for b in cluster.byzantine]
        return ScenarioResult(
            spec_digest=spec.digest(),
            violations=violations,
            commit_digests=cluster.commit_digests(),
            event_log_digest=sch.event_log_digest(),
            telemetry_digest=tele,
            events_run=sch.events_run,
            commits=cluster.honest_last_blocks(),
            committed_txs=len(cluster.committed_txs(0)),
            accepted_txs=len(cluster.accepted),
            virtual_s=round(sch.now, 6),
            wall_s=round(time.perf_counter() - wall0, 3),
            liveness_ok=liveness_ok,
            heal_base=heal_base,
            stats=stats,
        )
    finally:
        try:
            if cluster is not None:
                cluster.shutdown()
        finally:
            set_deterministic_signing(prev_sig)


def _evaluate(
    spec: ScenarioSpec,
    cluster: SimCluster,
    liveness_ok: bool,
    heal_base: int,
    nemesis_fired: List[str],
    committed_sets: List[set],
) -> List[dict]:
    violations: List[dict] = []
    lbs = cluster.honest_last_blocks()

    # no_fork: the honest common prefix must be byte-identical
    common = min(lbs)
    if common >= 0:
        ref_node = cluster.nodes[0]
        for bi in range(common + 1):
            ref = ref_node.get_block(bi).body.hash()
            for i in range(1, spec.nodes):
                if cluster.nodes[i].get_block(bi).body.hash() != ref:
                    violations.append({
                        "invariant": "no_fork",
                        "detail": f"block {bi} differs on node{i}",
                    })
                    break
            else:
                continue
            break

    # liveness: new commits on every honest node after heal
    if not liveness_ok:
        violations.append({
            "invariant": "liveness",
            "detail": f"post-heal blocks {lbs} (heal base {heal_base})",
        })

    # bounded queues
    for i in range(spec.nodes):
        pending = cluster.nodes[i].core.mempool.pending_count
        if pending > spec.mempool_max_txs:
            violations.append({
                "invariant": "bounded_queues",
                "detail": f"node{i} mempool pending {pending} "
                          f"> cap {spec.mempool_max_txs}",
            })
        undet = len(cluster.nodes[i].core.get_undetermined_events())
        if undet > spec.max_undetermined:
            violations.append({
                "invariant": "bounded_queues",
                "detail": f"node{i} undetermined events {undet} "
                          f"> {spec.max_undetermined}",
            })

    # exactly-once commit: no duplicates anywhere; every accepted tx
    # lands on its accepting node's chain
    for i in range(spec.nodes):
        committed = cluster.committed_txs(i)
        seen = set()
        for tx in committed:
            if tx in seen:
                violations.append({
                    "invariant": "exactly_once",
                    "detail": f"node{i} committed {tx!r} twice",
                })
                break
            seen.add(tx)
    lost = 0
    for payload, acceptor in cluster.accepted.items():
        if payload not in committed_sets[acceptor]:
            lost += 1
    if lost:
        violations.append({
            "invariant": "exactly_once",
            "detail": f"{lost}/{len(cluster.accepted)} accepted txs "
                      "never committed on their accepting node",
        })

    # the deliberate failure used to exercise shrinking end-to-end
    if spec.inject_failure and nemesis_fired:
        violations.append({
            "invariant": "injected_failure",
            "detail": f"nemesis ops fired: {sorted(set(nemesis_fired))}",
        })
    return violations
