"""babble_tpu CLI: keygen | run | version.

Reference semantics: /root/reference/cmd/babble/main.go:10,
commands/keygen.go:21-60, commands/run.go:14-141 — config resolution is
layered: built-in defaults < ``babble.toml`` in the datadir < CLI flags
(run.go:112-141). The reference uses cobra+viper; here argparse +
stdlib tomllib.

Usage:
    python -m babble_tpu.cli keygen [--pem FILE]
    python -m babble_tpu.cli run [--datadir D] [--listen H:P] ...
    python -m babble_tpu.cli version
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from ..config.config import Config, default_data_dir
from ..crypto.keyfile import SimpleKeyfile
from ..crypto.keys import generate_key
from ..version import __version__ as VERSION

# flag name -> (Config attr, type)
_RUN_FLAGS = {
    "datadir": ("data_dir", str),
    "log": ("log_level", str),
    "log_json": ("log_json", bool),
    "listen": ("bind_addr", str),
    "advertise": ("advertise_addr", str),
    "service_listen": ("service_addr", str),
    "no_service": ("no_service", bool),
    "heartbeat": ("heartbeat_timeout", float),
    "slow_heartbeat": ("slow_heartbeat_timeout", float),
    "timeout": ("tcp_timeout", float),
    "join_timeout": ("join_timeout", float),
    "max_pool": ("max_pool", int),
    "cache_size": ("cache_size", int),
    "sync_limit": ("sync_limit", int),
    "suspend_limit": ("suspend_limit", int),
    "fast_sync": ("enable_fast_sync", bool),
    "store": ("store", bool),
    "db": ("database_dir", str),
    "bootstrap": ("bootstrap", bool),
    "maintenance_mode": ("maintenance_mode", bool),
    "moniker": ("moniker", str),
    "accelerator": ("accelerator", bool),
    "accelerator_mesh": ("accelerator_mesh", int),
    "transport": ("transport", str),
    # lint: allow(knobs: toml-only; the CLI route is the negative-polarity --no-gossip-pipeline)
    "gossip_pipeline": ("gossip_pipeline", bool),
    "gossip_pipeline_depth": ("gossip_pipeline_depth", int),
    # lint: allow(knobs: toml-only; the CLI route is the negative-polarity --no-adaptive)
    "adaptive_gossip": ("adaptive_gossip", bool),
    "gossip_max_fanout": ("gossip_max_fanout", int),
    "selfevent_burst": ("selfevent_burst", int),
    "fast_forward_deadline": ("fast_forward_deadline", float),
    "join_backoff_cap": ("join_backoff_cap", float),
    "mempool_max_txs": ("mempool_max_txs", int),
    "mempool_max_bytes": ("mempool_max_bytes", int),
    "mempool_overflow": ("mempool_overflow", str),
    "mempool_event_max_txs": ("mempool_event_max_txs", int),
    "mempool_event_max_bytes": ("mempool_event_max_bytes", int),
    "mempool_committed_lru": ("mempool_committed_lru", int),
    "mempool_rate": ("mempool_rate", float),
    "mempool_burst": ("mempool_burst", float),
    "submit_batch": ("submit_batch", int),
    "sentry_threshold": ("sentry_threshold", float),
    "sentry_quarantine": ("sentry_quarantine_s", float),
    "sentry_decay_halflife": ("sentry_decay_halflife_s", float),
    "client_listen": ("client_listen", str),
    "sub_queue": ("sub_queue_frames", int),
    "sub_stall_timeout": ("sub_stall_timeout_s", float),
    "sub_shed_lag": ("sub_shed_lag", int),
    "sub_sndbuf": ("sub_sndbuf", int),
    "txindex_cap": ("txindex_cap", int),
    "trace_sample": ("trace_sample", float),
    "trace_table_cap": ("trace_table_cap", int),
    "watchdog_stall": ("watchdog_stall_s", float),
    "watchdog_interval": ("watchdog_interval_s", float),
    "flight_dir": ("flight_dir", str),
    "profile_hz": ("profile_hz", float),
    "signal": ("signal", bool),
    "signal_addr": ("signal_addr", str),
    "signal_ca": ("signal_ca", str),
    "signal_direct": ("signal_direct", str),
    "prune_every_rounds": ("prune_every_rounds", int),
    "prune_keep_rounds": ("prune_keep_rounds", int),
    # lint: allow(knobs: toml-only; the CLI route is the negative-polarity --no-prune-vacuum)
    "prune_vacuum": ("prune_vacuum", bool),
}


def _load_toml(path: str) -> dict:
    """babble.toml layer (reference: run.go:112-141)."""
    try:
        import tomllib
    except ImportError:  # pragma: no cover - py<3.11
        return {}
    try:
        with open(path, "rb") as f:
            return tomllib.load(f)
    except FileNotFoundError:
        return {}


def _build_config(args: argparse.Namespace) -> Config:
    datadir = args.datadir or default_data_dir()
    layered: dict = {"data_dir": datadir}
    # layer 2: babble.toml
    toml_conf = _load_toml(os.path.join(datadir, "babble.toml"))
    for flag, (attr, typ) in _RUN_FLAGS.items():
        if flag in toml_conf:
            layered[attr] = typ(toml_conf[flag])
    # layer 3: explicit CLI flags beat the file
    for flag, (attr, _) in _RUN_FLAGS.items():
        v = getattr(args, flag, None)
        if v is not None and v is not False:
            layered[attr] = v
    # negative-polarity flags (the store_true pattern above can only turn
    # booleans ON): --no-adaptive pins the fixed two-speed timer,
    # --no-gossip-pipeline keeps inbound syncs inline on handler threads
    if getattr(args, "no_adaptive", False):
        layered["adaptive_gossip"] = False
    if getattr(args, "no_gossip_pipeline", False):
        layered["gossip_pipeline"] = False
    if getattr(args, "no_prune_vacuum", False):
        layered["prune_vacuum"] = False
    return Config(**layered)


def cmd_keygen(args: argparse.Namespace) -> int:
    """Generate a key pair; refuses to overwrite (keygen.go:33-52)."""
    datadir = args.datadir or default_data_dir()
    path = args.pem or os.path.join(datadir, "priv_key")
    if os.path.exists(path):
        print(
            f"A key already lives under: {path}\n"
            "Remove it first if you really want to overwrite.",
            file=sys.stderr,
        )
        return 1
    key = generate_key()
    SimpleKeyfile(path).write_key(key)
    print(f"Your private key has been saved to: {path}")
    print(f"Public key: {key.public_key.hex()}")
    return 0


def cmd_run(args: argparse.Namespace) -> int:
    """Assemble and run the engine with a socket app proxy, or the dummy
    in-memory app with --inmem-dummy (run.go:29-60)."""
    from ..engine import Babble
    from ..obs import log as obs_log

    conf = _build_config(args)
    # One logging entry point for the whole process (obs/log.py):
    # level/JSON toggle from config+flags, node correlation stamped.
    obs_log.configure_from(conf)
    proxy = None
    if not args.inmem_dummy:
        from ..proxy.socket_proxy import SocketAppProxy

        proxy = SocketAppProxy(args.proxy_listen, args.client_connect)
    engine = Babble(conf, proxy=proxy)
    engine.init()

    def _stop(signum, frame):
        engine.shutdown()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    engine.run()
    return 0


def cmd_signal(args: argparse.Namespace) -> int:
    """Standalone signal/relay server daemon (reference: cmd/signal)."""
    import time as _time

    from ..net.signal import SignalServer
    from ..obs import log as obs_log

    obs_log.configure()
    if bool(args.cert) != bool(args.key):
        print("--cert and --key must be given together", file=sys.stderr)
        return 2
    server = SignalServer(args.listen, cert_file=args.cert,
                          key_file=args.key)
    addr = server.listen()
    mode = "TLS" if args.cert else "plaintext"
    print(f"signal server listening on {addr} ({mode})")

    stop = {"flag": False}

    def _stop(signum, frame):
        stop["flag"] = True
        server.close()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    while not stop["flag"]:
        _time.sleep(0.2)  # lint: allow(clock: signal-server daemon wait loop; CLI entry point, never under sim)
    return 0


def cmd_dummy(args: argparse.Namespace) -> int:
    """Interactive dummy chat-app client over the socket proxy pair
    (reference: cmd/dummy/commands/root.go:33-60). Lines typed on stdin
    are submitted as transactions; committed blocks print as they land.
    With --no-repl it serves commits silently (for scripted testnets)."""
    import time as _time

    from ..dummy.socket_client import DummySocketClient

    client = DummySocketClient(args.listen, args.connect)
    print(f"dummy app serving on {args.listen}, submitting to {args.connect}")

    orig_commit = client.state.commit_handler

    def loud_commit(block):
        resp = orig_commit(block)
        for tx in block.transactions():
            print(f"[block {block.index()}] {tx.decode(errors='replace')}")
        return resp

    if not args.no_repl:
        client.state.commit_handler = loud_commit

    stop = {"flag": False}

    def _stop(signum, frame):
        stop["flag"] = True
        if signum == signal.SIGINT:
            # let the blocking readline() in the REPL unwind via
            # KeyboardInterrupt instead of resuming on EINTR (PEP 475)
            raise KeyboardInterrupt

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)

    try:
        if args.no_repl:
            while not stop["flag"]:
                _time.sleep(0.2)  # lint: allow(clock: dummy-app daemon wait loop; CLI entry point, never under sim)
        else:
            while not stop["flag"]:
                line = sys.stdin.readline()
                if not line:
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    verdict = client.submit_tx(line.encode())
                    if verdict != "accepted":
                        # shed/duplicate verdicts (docs/mempool.md) must
                        # reach the user — the message will NOT commit
                        print(f"submit verdict: {verdict}", file=sys.stderr)
                except Exception as err:
                    # a dropped tx is recoverable; keep the chat alive
                    print(f"submit failed ({err}); is the node up?",
                          file=sys.stderr)
    except KeyboardInterrupt:
        pass
    finally:
        client.close()
    return 0


def cmd_version(_: argparse.Namespace) -> int:
    print(VERSION)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="babble_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    kg = sub.add_parser("keygen", help="generate a new key pair")
    kg.add_argument("--datadir", default=None)
    kg.add_argument("--pem", default=None, help="explicit key file path")
    kg.set_defaults(fn=cmd_keygen)

    run = sub.add_parser("run", help="run a node")
    run.add_argument("--datadir", default=None)
    run.add_argument("--log", default=None)
    run.add_argument(
        "--log-json", dest="log_json", action="store_true",
        help="structured JSON log lines (one object per line, node "
        "correlation fields included)",
    )
    run.add_argument("--listen", default=None, help="bind host:port")
    run.add_argument("--advertise", default=None)
    run.add_argument("--service-listen", dest="service_listen", default=None)
    run.add_argument("--no-service", dest="no_service", action="store_true")
    run.add_argument("--heartbeat", type=float, default=None)
    run.add_argument("--slow-heartbeat", dest="slow_heartbeat", type=float, default=None)
    run.add_argument("--timeout", type=float, default=None)
    run.add_argument("--join-timeout", dest="join_timeout", type=float, default=None)
    run.add_argument("--max-pool", dest="max_pool", type=int, default=None)
    run.add_argument("--cache-size", dest="cache_size", type=int, default=None)
    run.add_argument("--sync-limit", dest="sync_limit", type=int, default=None)
    run.add_argument("--suspend-limit", dest="suspend_limit", type=int, default=None)
    run.add_argument("--fast-sync", dest="fast_sync", action="store_true")
    run.add_argument("--store", action="store_true")
    run.add_argument("--db", default=None)
    run.add_argument("--bootstrap", action="store_true")
    run.add_argument("--maintenance-mode", dest="maintenance_mode", action="store_true")
    run.add_argument("--moniker", default=None)
    run.add_argument("--accelerator", action="store_true")
    run.add_argument(
        "--accelerator-mesh", dest="accelerator_mesh", type=int, default=None,
        help="shard voting sweeps over this many devices (multi-chip)",
    )
    run.add_argument(
        "--transport", default=None, choices=("tcp", "async"),
        help="gossip transport: 'async' = event-driven selector engine "
        "with the binary framed codec (docs/gossip.md); 'tcp' = "
        "thread-per-connection JSON fallback (default)",
    )
    run.add_argument(
        "--gossip-pipeline-depth", dest="gossip_pipeline_depth", type=int,
        default=None,
        help="bounded insert-queue depth of the inbound-sync pipeline",
    )
    run.add_argument(
        "--no-gossip-pipeline", dest="no_gossip_pipeline",
        action="store_true",
        help="disable the staged inbound-sync pipeline: decode, verify "
        "and insert run inline on handler threads (the pre-pipeline "
        "shape; docs/gossip.md)",
    )
    run.add_argument(
        "--no-adaptive", dest="no_adaptive", action="store_true",
        help="disable the adaptive gossip scheduler: fixed two-speed "
        "heartbeat, one partner per tick (same as BABBLE_ADAPT=0)",
    )
    run.add_argument(
        "--fast-forward-deadline", dest="fast_forward_deadline",
        type=float, default=None,
        help="total budget in seconds for the catching-up node's "
        "fast-forward poll loop (docs/robustness.md)",
    )
    run.add_argument(
        "--join-backoff-cap", dest="join_backoff_cap", type=float,
        default=None,
        help="cap in seconds on the joining node's retry backoff",
    )
    run.add_argument(
        "--gossip-max-fanout", dest="gossip_max_fanout", type=int,
        default=None,
        help="adaptive scheduler's fan-out ceiling: max distinct gossip "
        "partners per tick (docs/gossip.md §Adaptive scheduling)",
    )
    run.add_argument(
        "--selfevent-burst", dest="selfevent_burst", type=int, default=None,
        help="max extra self-events coalesced per tick while the mempool "
        "holds a full event's worth of pending txs (0 disables)",
    )
    run.add_argument(
        "--mempool-max-txs", dest="mempool_max_txs", type=int, default=None,
        help="mempool capacity in transactions (admission cap)",
    )
    run.add_argument(
        "--mempool-max-bytes", dest="mempool_max_bytes", type=int,
        default=None, help="mempool capacity in bytes",
    )
    run.add_argument(
        "--mempool-overflow", dest="mempool_overflow", default=None,
        choices=("reject", "evict-oldest"),
        help="behavior at capacity: reject new txs (default) or evict oldest",
    )
    run.add_argument(
        "--mempool-event-max-txs", dest="mempool_event_max_txs", type=int,
        default=None, help="max client txs packaged per self-event",
    )
    run.add_argument(
        "--mempool-event-max-bytes", dest="mempool_event_max_bytes",
        type=int, default=None, help="max client tx bytes per self-event",
    )
    run.add_argument(
        "--mempool-committed-lru", dest="mempool_committed_lru", type=int,
        default=None,
        help="committed-transaction-hash LRU size (turns retries of "
        "committed txs into `already_committed`)",
    )
    run.add_argument(
        "--mempool-rate", dest="mempool_rate", type=float, default=None,
        help="token-bucket admission rate in tx/s (0 = unlimited)",
    )
    run.add_argument(
        "--mempool-burst", dest="mempool_burst", type=float, default=None,
        help="token-bucket burst size in txs (0 = one second's worth)",
    )
    run.add_argument(
        "--submit-batch", dest="submit_batch", type=int, default=None,
        help="submit-queue transactions drained per background pass",
    )
    run.add_argument(
        "--sentry-threshold", dest="sentry_threshold", type=float,
        default=None,
        help="misbehavior score at which a peer is quarantined",
    )
    run.add_argument(
        "--sentry-quarantine", dest="sentry_quarantine", type=float,
        default=None, help="quarantine duration in seconds",
    )
    run.add_argument(
        "--sentry-decay-halflife", dest="sentry_decay_halflife", type=float,
        default=None, help="misbehavior score decay half-life in seconds",
    )
    run.add_argument(
        "--client-listen", dest="client_listen", default=None,
        help="bind the light-client SubscriptionHub here (streaming "
        "commit subscriptions, docs/clients.md); empty = off",
    )
    run.add_argument(
        "--sub-queue", dest="sub_queue", type=int, default=None,
        help="bounded per-subscriber frame queue (docs/clients.md)",
    )
    run.add_argument(
        "--sub-stall-timeout", dest="sub_stall_timeout", type=float,
        default=None,
        help="seconds a subscriber may stall with queued frames before "
        "being shed",
    )
    run.add_argument(
        "--sub-shed-lag", dest="sub_shed_lag", type=int, default=None,
        help="delivery deficit in blocks beyond which a chronically "
        "slow subscriber is shed",
    )
    run.add_argument(
        "--sub-sndbuf", dest="sub_sndbuf", type=int, default=None,
        help="kernel send-buffer cap per subscriber socket (0 = OS "
        "default); small values make slow-consumer shedding prompt",
    )
    run.add_argument(
        "--txindex-cap", dest="txindex_cap", type=int, default=None,
        help="max transactions indexed for GET /proof/<txid>",
    )
    run.add_argument(
        "--trace-sample", dest="trace_sample", type=float, default=None,
        help="commit-provenance sampling rate (deterministic across "
        "nodes; 1.0 traces every tx, 0 disables)",
    )
    run.add_argument(
        "--trace-table-cap", dest="trace_table_cap", type=int,
        default=None, help="max provenance records kept per node",
    )
    run.add_argument(
        "--watchdog-stall", dest="watchdog_stall", type=float,
        default=None,
        help="stall seconds before the flight recorder trips (0 = off)",
    )
    run.add_argument(
        "--watchdog-interval", dest="watchdog_interval", type=float,
        default=None, help="stall-watchdog poll interval in seconds",
    )
    run.add_argument(
        "--flight-dir", dest="flight_dir", default=None,
        help="directory for flight-recorder artifacts",
    )
    run.add_argument(
        "--profile-hz", dest="profile_hz", type=float, default=None,
        help="always-on sampling-profiler rate (thread-stack samples/s "
        "served at GET /profile; 0 disables; default 50)",
    )
    run.add_argument(
        "--signal", action="store_true",
        help="relay mode: route gossip via a signal server, addressed by pubkey",
    )
    run.add_argument(
        "--signal-addr", dest="signal_addr", default=None,
        help="signal/relay server host:port (default 127.0.0.1:2443)",
    )
    run.add_argument(
        "--signal-ca", dest="signal_ca", default=None,
        help="pinned relay TLS cert (PEM); default datadir/cert.pem if present",
    )
    run.add_argument(
        "--signal-direct", dest="signal_direct", default=None,
        help="direct p2p upgrade listen addr for signal mode (e.g. "
        "0.0.0.0:0); gossip then leaves the relay after the handshake",
    )
    run.add_argument(
        "--prune-every-rounds", dest="prune_every_rounds", type=int,
        default=None,
        help="checkpoint-prune cadence: compact the store every N "
        "committed rounds past the last prune floor (0 disables; "
        "docs/lifecycle.md)",
    )
    run.add_argument(
        "--prune-keep-rounds", dest="prune_keep_rounds", type=int,
        default=None,
        help="straggler margin: retain this many rounds below the "
        "anchor when pruning",
    )
    run.add_argument(
        "--no-prune-vacuum", dest="no_prune_vacuum", action="store_true",
        help="skip the incremental SQLite vacuum after each prune "
        "(pages are still reused, just not returned to the OS)",
    )
    run.add_argument(
        "--proxy-listen", dest="proxy_listen", default="127.0.0.1:1338",
        help="where Babble serves SubmitTx for the app",
    )
    run.add_argument(
        "--client-connect", dest="client_connect", default="127.0.0.1:1339",
        help="where the app serves State.*",
    )
    run.add_argument(
        "--inmem-dummy", dest="inmem_dummy", action="store_true",
        help="run the built-in dummy app in-process instead of the socket proxy",
    )
    run.set_defaults(fn=cmd_run)

    dmy = sub.add_parser(
        "dummy", help="interactive dummy chat app over the socket proxy"
    )
    dmy.add_argument(
        "--listen", default="127.0.0.1:1339", help="app-side bind host:port"
    )
    dmy.add_argument(
        "--connect", default="127.0.0.1:1338",
        help="babble-side proxy host:port",
    )
    dmy.add_argument(
        "--no-repl", dest="no_repl", action="store_true",
        help="serve commits without the stdin chat loop",
    )
    dmy.set_defaults(fn=cmd_dummy)

    sig = sub.add_parser(
        "signal", help="run a standalone signal/relay server"
    )
    sig.add_argument(
        "--listen", default="0.0.0.0:2443", help="bind host:port"
    )
    sig.add_argument(
        "--cert", default=None, help="TLS certificate (PEM); enables TLS"
    )
    sig.add_argument(
        "--key", default=None, help="TLS private key (PEM)"
    )
    sig.set_defaults(fn=cmd_signal)

    ver = sub.add_parser("version", help="print the version")
    ver.set_defaults(fn=cmd_version)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
