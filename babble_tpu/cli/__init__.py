"""Command-line interface (reference: cmd/babble/)."""
