"""Device mesh construction and sharding placement for consensus tensors.

Mesh axes:
- ``dp`` (data parallel): independent DAG windows / signature batches.
- ``sp`` (sequence parallel): the event dimension within one window — the
  analogue of context parallelism for the undetermined-event window
  (SURVEY.md §5 "long-context" mapping).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def consensus_mesh(
    n_devices: Optional[int] = None, dp: Optional[int] = None
) -> Mesh:
    """Build a (dp, sp) mesh over the first n_devices devices.

    ``dp`` defaults to the largest power-of-two ≤ sqrt(n); the rest of the
    devices go to the ``sp`` axis.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if dp is None:
        dp = 1
        while dp * 2 <= int(np.sqrt(n_devices)) and n_devices % (dp * 2) == 0:
            dp *= 2
    if n_devices % dp != 0:
        raise ValueError(f"dp={dp} does not divide n_devices={n_devices}")
    sp = n_devices // dp
    mesh_devices = np.array(devices).reshape(dp, sp)
    return Mesh(mesh_devices, axis_names=("dp", "sp"))


def ring_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over all (or the first n) devices with a single ``ring``
    axis — the topology for the ppermute-based ring kernels, where blocks
    rotate neighbour-to-neighbour instead of all-gathering."""
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    return Mesh(np.array(devices[:n_devices]), axis_names=("ring",))


def shard_batched_snapshot(mesh: Mesh, arrays: Tuple):
    """Place a batch of snapshot tensors on the mesh: batch dim over ``dp``,
    event dim over ``sp``, peer dim replicated.

    ``arrays`` = (creator, index, sp_idx, op_idx, la, fd, mid), each with a
    leading [B, E, ...] layout.
    """
    creator, index, sp_idx, op_idx, la, fd, mid = arrays
    s2 = NamedSharding(mesh, P("dp", "sp"))
    s3 = NamedSharding(mesh, P("dp", "sp", None))
    return (
        jax.device_put(creator, s2),
        jax.device_put(index, s2),
        jax.device_put(sp_idx, s2),
        jax.device_put(op_idx, s2),
        jax.device_put(la, s3),
        jax.device_put(fd, s3),
        jax.device_put(mid, s2),
    )
