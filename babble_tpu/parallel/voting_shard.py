"""Multi-device variant of the LIVE voting sweep (babble_tpu.ops.voting).

Shards the witness axis W of the fused fame + decidedness + round-received
kernel over a device mesh with explicit collectives (shard_map):

- each chip owns a W/n slice of the witness coordinate rows (la/fd), so
  the [W, W, P] strongly-see compare — the sweep's biggest tensor — is
  computed as [W_loc, W, P] per chip;
- the per-round vote recursion all-gathers the vote matrix once per round
  (votes[y, x]: voter rows y sharded, candidate columns x full) — the
  ring/context-parallel analogue for the undetermined-event window
  (SURVEY.md §2.5/§5: CP ≙ sharding the window with boundary exchange);
- fame decisions and the round-received scan reduce across chips with
  ``psum``, so every chip ends with identical replicated (fame, rr)
  outputs — consensus decisions must be bit-identical everywhere, so the
  outputs are replicated, not sharded.

Semantics are identical to ops.voting._sweep_core (differentially tested
on real VotingWindows, including per-round peer-set changes); only the
data placement differs. Oracle being reproduced: DecideFame
hashgraph.go:875-998, DecideRoundReceived hashgraph.go:1002-1095.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from babble_tpu.ops.voting import COIN_ROUND_FREQ, VotingWindow

from babble_tpu.parallel.collectives import shard_map  # version-normalized

if hasattr(lax, "pcast"):
    _pcast = lax.pcast
else:  # pragma: no cover - version-dependent
    # jax 0.4.x has no varying-manual-axes (vma) type system; with the
    # replication check off, marking a carry device-varying is a no-op.
    def _pcast(x, axes, to):
        return x

AXES = ("dp", "sp")


def _n_shards(mesh: Mesh) -> int:
    return mesh.devices.size


def sharded_sweep_fn(mesh: Mesh):
    """Build the sharded fused-sweep callable for a mesh. Takes the same
    18 arrays as ops.voting._sweep_core (W-axis arrays sharded over the
    flattened mesh, everything else replicated) and returns the replicated
    concatenated [fame | rr] vector."""
    n_shards = _n_shards(mesh)
    sp_size = mesh.devices.shape[1]

    def kernel(creator, index, la_loc, fd_loc, rounds_loc, valid_loc,
               fame0_loc, mid_loc, wit_idx, member, sm_s, psi, sm_r,
               rounds_e, undet_e, exists_r, prior_dec_r, lb_gate_r):
        W_loc = la_loc.shape[0]
        R = psi.shape[0]
        shard = lax.axis_index("dp") * sp_size + lax.axis_index("sp")
        offset = shard * W_loc

        # candidate-axis (x) data must be full on every chip: fd for the
        # all-pairs strongly-see compare, plus the tiny per-witness
        # round/valid/fame vectors; voter-axis (y) data stays sharded
        fd_full = lax.all_gather(fd_loc, AXES, axis=0, tiled=True)
        rounds_full = lax.all_gather(rounds_loc, AXES, axis=0, tiled=True)
        valid_full = lax.all_gather(valid_loc, AXES, axis=0, tiled=True)
        fame0_full = lax.all_gather(fame0_loc, AXES, axis=0, tiled=True)

        # SEE for local voter rows (oracle: hashgraph.go:96-128)
        see_loc = (la_loc[:, creator] >= index[None, :]) & valid_loc[:, None]
        see_ww_loc = see_loc[:, wit_idx]  # [W_loc(y), W(x)]

        # strongly-see per peer-set slot, local voter rows
        # (oracle: hashgraph.go:172-206)
        ge = (la_loc[:, None, :] >= fd_full[None, :, :]).astype(jnp.int32)
        counts = jnp.einsum("vwp,sp->svw", ge, member.astype(jnp.int32))
        ss_all_loc = counts >= sm_s[:, None, None]  # [S, W_loc, W]

        def per_round(j, state):
            votes_loc, fame_full = state
            voter_loc = valid_loc & (rounds_loc == j)
            diff = j - rounds_full  # [W(x)]

            # full vote matrix for the derived-vote matmul: the per-round
            # boundary exchange of the ring formulation
            votes_full = lax.all_gather(votes_loc, AXES, axis=0, tiled=True)

            prev_full = valid_full & (rounds_full == (j - 1))
            slot_prev = psi[jnp.clip(j - 1, 0, R - 1)]
            ss_prev_loc = ss_all_loc[slot_prev] & prev_full[None, :]
            n_ss = jnp.sum(ss_prev_loc, axis=1, dtype=jnp.int32)
            yays = ss_prev_loc.astype(jnp.int32) @ votes_full.astype(jnp.int32)
            nays = n_ss[:, None] - yays
            v = yays >= nays
            t = jnp.maximum(yays, nays)
            sm_j = sm_r[jnp.clip(j, 0, R - 1)]
            settled = t >= sm_j

            is_coin = (diff % COIN_ROUND_FREQ) == 0
            derived = jnp.where(
                is_coin[None, :] & ~settled, mid_loc[:, None], v
            )
            new_vote = jnp.where((diff == 1)[None, :], see_ww_loc, derived)
            active = (
                voter_loc[:, None] & valid_full[None, :] & (diff >= 1)[None, :]
            )
            votes_loc = jnp.where(active, new_vote, votes_loc)

            decide_pair = (
                active & ~is_coin[None, :] & (diff > 1)[None, :] & settled
            )
            # any-over-voters crosses shards: reduce with psum
            decided_now = lax.psum(
                jnp.any(decide_pair, axis=0).astype(jnp.int32), AXES
            ) > 0
            decided_val = lax.psum(
                jnp.any(decide_pair & v, axis=0).astype(jnp.int32), AXES
            ) > 0
            newly = decided_now & (fame_full == 0)
            fame_full = jnp.where(
                newly, jnp.where(decided_val, 1, -1), fame_full
            )
            return votes_loc, fame_full

        W = rounds_full.shape[0]
        # mark the all-zeros initial carry as device-varying so the loop
        # carry types line up (shard_map varying-manual-axes rule)
        votes0 = _pcast(jnp.zeros((W_loc, W), bool), AXES, to="varying")
        _, fame_full = lax.fori_loop(1, R, per_round, (votes0, fame0_full))

        # per-round decidedness (oracle: roundInfo.go:78-96) — replicated
        r_ax = jnp.arange(R)
        m_rw = valid_full[None, :] & (rounds_full[None, :] == r_ax[:, None])
        undecided_w = fame_full == 0
        has_undec = jnp.any(m_rw & undecided_w[None, :], axis=1)
        cnt = jnp.sum(m_rw & (~undecided_w)[None, :], axis=1, dtype=jnp.int32)
        decided_r = prior_dec_r | (exists_r & ~has_undec & (cnt >= sm_r))
        hard_block_r = (~exists_r) | ((~decided_r) & lb_gate_r)

        # round-received with the witness reduction psum-ed across shards
        # (oracle: hashgraph.go:1002-1095)
        fame_loc = lax.dynamic_slice(fame_full, (offset,), (W_loc,))
        E = rounds_e.shape[0]

        def per_round_rr(i, state):
            rr, blocked = state
            fw_loc = valid_loc & (rounds_loc == i) & (fame_loc == 1)
            n_fw = lax.psum(jnp.sum(fw_loc, dtype=jnp.int32), AXES)
            # all famous witnesses see x  <=>  no local fw fails to see x
            miss_loc = jnp.any(fw_loc[:, None] & ~see_loc, axis=0)
            missing = lax.psum(miss_loc.astype(jnp.int32), AXES) > 0
            all_see = (~missing) & (n_fw >= sm_r[jnp.clip(i, 0, R - 1)])
            relevant = rounds_e < i
            eligible = (
                decided_r[i] & ~blocked & relevant & (rr < 0) & all_see
                & undet_e
            )
            rr = jnp.where(eligible, i, rr)
            blocked = blocked | (relevant & hard_block_r[i])
            return rr, blocked

        rr0 = _pcast(jnp.full(E, -1, jnp.int32), AXES, to="varying")
        blocked0 = _pcast(jnp.zeros(E, bool), AXES, to="varying")
        rr, _ = lax.fori_loop(1, R, per_round_rr, (rr0, blocked0))
        return jnp.concatenate([fame_full, rr])

    w_spec = P(AXES)  # W axis split over the flattened mesh
    w_spec2 = P(AXES, None)  # [W, P]
    rep = P(None)
    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(
            rep,      # creator [E]
            rep,      # index [E]
            w_spec2,  # la_w [W, P]
            w_spec2,  # fd_w [W, P]
            w_spec,   # rounds_w [W]
            w_spec,   # valid_w [W]
            w_spec,   # fame0_w [W]
            w_spec,   # mid_w [W]
            rep,      # wit_idx [W] — candidate-axis lookup, replicated
            rep,      # member [S, P]
            rep,      # sm_s [S]
            rep,      # psi [R]
            rep,      # sm_r [R]
            rep,      # rounds_e [E]
            rep,      # undet_e [E]
            rep,      # exists_r [R]
            rep,      # prior_dec_r [R]
            rep,      # lb_gate_r [R]
        ),
        out_specs=rep,
        # The output IS replicated: every cross-shard value flows through
        # psum/all_gather before touching fame/rr. The static varying-axes
        # checker cannot prove that through the fori_loop carries (the vote
        # matrix is legitimately shard-varying), so the check is disabled
        # here and replication is enforced by the differential tests
        # (sharded output == single-device, tests/test_parallel.py).
        check_vma=False,
    )


def place_window(mesh: Mesh, win: VotingWindow):
    """Device-place a VotingWindow's arrays with the sweep's shardings."""
    w_sh = NamedSharding(mesh, P(AXES))
    w2_sh = NamedSharding(mesh, P(AXES, None))
    rep = NamedSharding(mesh, P(None))
    put = jax.device_put
    return (
        put(win.creator, rep),
        put(win.index, rep),
        put(win.la_w, w2_sh),
        put(win.fd_w, w2_sh),
        put(win.rounds_w, w_sh),
        put(win.valid_w, w_sh),
        put(win.fame0_w, w_sh),
        put(win.mid_w, w_sh),
        put(win.wit_idx, rep),
        put(win.member, rep),
        put(win.sm_s, rep),
        put(win.psi, rep),
        put(win.sm_r, rep),
        put(win.rounds, rep),
        put(win.undet, rep),
        put(win.exists_r, rep),
        put(win.prior_dec_r, rep),
        put(win.lb_gate_r, rep),
    )


# jitted sweep per mesh, so repeated sweeps reuse the trace/compile cache
# like the single-device _sweep_jit does
_jit_cache: dict = {}


def _jitted(mesh: Mesh):
    key = (
        mesh.devices.shape,
        tuple(d.id for d in mesh.devices.flatten()),
    )
    fn = _jit_cache.get(key)
    if fn is None:
        fn = jax.jit(sharded_sweep_fn(mesh))
        _jit_cache[key] = fn
    return fn


def resident_shardings(mesh: Mesh) -> tuple:
    """NamedShardings for the 11 resident buffers in RESIDENT_FIELDS order
    (ops.window_state): per-event vectors and the candidate-axis witness
    index replicated, witness coordinate rows W-sharded like the sweep's
    in_specs, so the resident buffers ARE the sweep's operands — no
    resharding between the delta scatter and the kernel."""
    w_sh = NamedSharding(mesh, P(AXES))
    w2_sh = NamedSharding(mesh, P(AXES, None))
    rep = NamedSharding(mesh, P(None))
    # (creator, index, rounds, undet, wit_idx,
    #  la_w, fd_w, rounds_w, valid_w, fame0_w, mid_w)
    return (rep, rep, rep, rep, rep, w2_sh, w2_sh, w_sh, w_sh, w_sh, w_sh)


def resident_sweep_fn(mesh: Mesh):
    """The mesh analogue of ops.window_state._resident_core: scatter a
    bucket-padded delta into the per-shard resident buffers (GSPMD keeps
    the scatter local — delta row indexes are replicated, the W-sharded
    operands stay put), then run the SHARDED sweep over them. Returns
    (new resident buffers, replicated [fame | rr])."""
    sweep = sharded_sweep_fn(mesh)

    def fn(creator, index, rounds, undet, wit_idx, la_w, fd_w,
           rounds_w, valid_w, fame0_w, mid_w,
           e_idx, e_creator, e_index, e_rounds, e_undet,
           w_idx, w_wit_idx, w_la, w_fd, w_rounds, w_valid,
           w_fame0, w_mid,
           member, sm_s, psi, sm_r, exists_r, prior_dec_r, lb_gate_r):
        creator = creator.at[e_idx].set(e_creator, mode="drop")
        index = index.at[e_idx].set(e_index, mode="drop")
        rounds = rounds.at[e_idx].set(e_rounds, mode="drop")
        undet = undet.at[e_idx].set(e_undet, mode="drop")
        wit_idx = wit_idx.at[w_idx].set(w_wit_idx, mode="drop")
        la_w = la_w.at[w_idx].set(w_la, mode="drop")
        fd_w = fd_w.at[w_idx].set(w_fd, mode="drop")
        rounds_w = rounds_w.at[w_idx].set(w_rounds, mode="drop")
        valid_w = valid_w.at[w_idx].set(w_valid, mode="drop")
        fame0_w = fame0_w.at[w_idx].set(w_fame0, mode="drop")
        mid_w = mid_w.at[w_idx].set(w_mid, mode="drop")
        out = sweep(
            creator, index, la_w, fd_w, rounds_w, valid_w, fame0_w, mid_w,
            wit_idx, member, sm_s, psi, sm_r, rounds, undet,
            exists_r, prior_dec_r, lb_gate_r,
        )
        return (
            (creator, index, rounds, undet, wit_idx, la_w, fd_w, rounds_w,
             valid_w, fame0_w, mid_w),
            out,
        )

    return fn


# per-mesh jitted resident program: donates the 11 sharded buffers (the
# delta updates them in place per shard) and pins their output shardings
# so residency never drifts placement between sweeps
_resident_jit_cache: dict = {}


def resident_jitted(mesh: Mesh):
    key = _mesh_key(mesh)
    fn = _resident_jit_cache.get(key)
    if fn is None:
        fn = jax.jit(
            resident_sweep_fn(mesh),
            donate_argnums=tuple(range(11)),
            out_shardings=(
                resident_shardings(mesh),
                NamedSharding(mesh, P(None)),
            ),
        )
        _resident_jit_cache[key] = fn
    return fn


def place_resident(mesh: Mesh, win) -> tuple:
    """Device-place a window's 11 per-row arrays with the resident
    shardings (RESIDENT_FIELDS order) — the residency seed the full-upload
    dispatch path keeps for the next delta sweep."""
    from babble_tpu.ops.window_state import RESIDENT_FIELDS

    shardings = resident_shardings(mesh)
    return tuple(
        jax.device_put(np.asarray(getattr(win, f)), s)
        for f, s in zip(RESIDENT_FIELDS, shardings)
    )


# per-mesh compiled-bucket registry for the resident delta program
# (a separate executable from the plain sharded sweep)
_ready_resident: dict = {}


def resident_bucket_ready(mesh: Mesh, key: tuple) -> bool:
    return key in _ready_resident.get(_mesh_key(mesh), set())


def mark_resident_bucket_ready(mesh: Mesh, key: tuple) -> None:
    _ready_resident.setdefault(_mesh_key(mesh), set()).add(key)


def precompile_resident(mesh: Mesh, W: int, E: int, P_: int, S: int,
                        R: int) -> None:
    """Compile the mesh resident delta program for a shape bucket: dummy
    window placed with the resident shardings + an all-padding delta."""
    from babble_tpu.ops.voting import dummy_window
    from babble_tpu.ops.window_state import FRESH_FIELDS, _empty_delta

    key = (W, E, P_, S, R)
    win = dummy_window(*key)
    bufs = place_resident(mesh, win)
    fresh = tuple(np.asarray(getattr(win, f)) for f in FRESH_FIELDS)
    _new_bufs, out = resident_jitted(mesh)(*bufs, *_empty_delta(key), *fresh)
    np.asarray(out)  # block until the executable is really ready
    mark_resident_bucket_ready(mesh, key)


# per-mesh compiled-bucket registry, mirroring ops.voting's single-device
# one (the two jit caches are separate programs, so readiness is too)
_ready_buckets: dict = {}


def _mesh_key(mesh: Mesh) -> tuple:
    return (
        mesh.devices.shape,
        tuple(d.id for d in mesh.devices.flatten()),
    )


def bucket_ready(mesh: Mesh, key: tuple) -> bool:
    return key in _ready_buckets.get(_mesh_key(mesh), set())


def precompile(mesh: Mesh, W: int, E: int, P: int, S: int, R: int) -> None:
    """Compile the SHARDED sweep for a shape bucket on this mesh (dummy
    window through the per-mesh jit), so live flushes never stall on it."""
    from babble_tpu.ops.voting import dummy_window

    win = dummy_window(W, E, P, S, R)
    np.asarray(_jitted(mesh)(*place_window(mesh, win)))
    _ready_buckets.setdefault(_mesh_key(mesh), set()).add((W, E, P, S, R))


def run_sharded_sweep(mesh: Mesh, win: VotingWindow):
    """One sharded sweep over a live VotingWindow; returns (fame, rr)
    numpy arrays, identical to ops.voting.run_sweep's."""
    if win.n_witnesses % _n_shards(mesh) != 0:
        raise ValueError(
            f"W={win.n_witnesses} not divisible by mesh size {_n_shards(mesh)}"
        )
    out = np.asarray(_jitted(mesh)(*place_window(mesh, win)))
    W = win.n_witnesses
    return out[:W], out[W:W + win.n_events]


def synthetic_voting_window(
    n_peers: int = 6, n_events: int = 160, seed: int = 3,
    peer_change: bool = True,
) -> Tuple[object, VotingWindow]:
    """A real Hashgraph (random gossip stream, voting deferred) and its
    VotingWindow — with an optional mid-stream peer-set change so the
    window carries MULTIPLE peer-set slots (S >= 2), exercising the
    psi/member machinery end to end."""
    import random

    from babble_tpu.crypto.keys import generate_key
    from babble_tpu.hashgraph import Event, Hashgraph, InmemStore
    from babble_tpu.ops import voting
    from babble_tpu.peers.peer import Peer
    from babble_tpu.peers.peer_set import PeerSet

    rng = random.Random(seed)
    keys = [generate_key() for _ in range(n_peers)]
    peers = PeerSet(
        [
            Peer(f"inmem://p{i}", k.public_key.hex(), f"p{i}")
            for i, k in enumerate(keys)
        ]
    )
    h = Hashgraph(InmemStore(100000))
    h.init(peers)
    if peer_change:
        # drop the last peer from round 3 onward: rounds in the window use
        # two different member masks and super-majorities
        smaller = peers.with_removed_peer(peers.peers[-1])
        h.store.set_peer_set(3, smaller)

    heads = [""] * n_peers
    seqs = [-1] * n_peers
    count = 0
    order = list(range(n_peers))
    while count < n_events:
        rng.shuffle(order)
        for i in order:
            if count >= n_events:
                break
            op = ""
            if count:
                j = rng.randrange(n_peers - 1)
                j = j if j < i else j + 1
                op = heads[j]
                if op == "":
                    continue
            idx = seqs[i] + 1
            e = Event.new(
                [b"t"] if idx else [], [], [], [heads[i], op],
                keys[i].public_key.bytes(), idx, timestamp=count,
            )
            e.sign(keys[i])
            e.prevalidate(True)
            heads[i] = e.hex()
            seqs[i] = idx
            h.insert_event(e, set_wire_info=True)
            h.divide_rounds()
            count += 1
    win = voting.build_voting_window(h)
    assert win is not None
    return h, win
