"""Explicit-collective consensus kernels via shard_map.

Where ``pipeline.sharded_batched_pipeline`` lets GSPMD infer collectives,
these kernels spell them out: event rows live on different chips and
super-majority reductions ride ICI as ``psum``/``all_gather``. They are the
building blocks for streaming consensus where each chip owns a slice of
the undetermined-event window (ring/CP analogue, SURVEY.md §5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# jax >= 0.6 exposes shard_map at top level and spells the replication
# check `check_vma`; 0.4.x keeps it experimental as `check_rep`. Normalize
# both here so kernel code can target the modern spelling.
_raw_shard_map = getattr(jax, "shard_map", None)
if _raw_shard_map is None:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _raw_shard_map

import inspect as _inspect

if "check_vma" in _inspect.signature(_raw_shard_map).parameters:
    shard_map = _raw_shard_map
else:  # pragma: no cover - version-dependent

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _raw_shard_map(*args, **kwargs)


def sharded_strongly_see(mesh: Mesh, super_majority: int):
    """stronglySee with x-rows sharded over the full mesh.

    la is sharded on rows; fd is all-gathered (each chip needs every
    candidate y to compare against its local x rows). Returns a function
    (la [E, P] sharded, fd [E, P] sharded) -> ss [E, E] row-sharded.
    """
    axes = ("dp", "sp")

    def kernel(la_local, fd_local):
        fd_full = lax.all_gather(fd_local, axes, axis=0, tiled=True)
        ge = la_local[:, None, :] >= fd_full[None, :, :]  # [e_loc, E, P]
        counts = jnp.sum(ge, axis=-1, dtype=jnp.int32)
        return counts >= super_majority

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None)),
        out_specs=P(axes, None),
    )


def ring_strongly_see(mesh: Mesh, super_majority: int):
    """stronglySee with BOTH coordinate tensors sharded and NO all-gather:
    first-descendant blocks rotate around the device ring (``ppermute``)
    while each chip accumulates compare-counts for its local
    last-ancestor rows — ring attention's KV-rotation pattern applied to
    the consensus window (KV blocks ≙ first-descendant blocks, queries ≙
    last-ancestor rows; SURVEY.md §2.5/§5 CP mapping).

    Versus ``sharded_strongly_see``'s all-gather, peak per-chip live
    memory drops from O(E·P) to O(E·P/n), and each of the n steps moves
    one block over a single ICI hop, overlappable with the block compare.
    Requires a 1-D mesh (``mesh.ring_mesh``). Returns a function
    (la [E, P] row-sharded, fd [E, P] row-sharded) -> ss [E, E]
    row-sharded, bit-identical to the all-gather kernel.
    """
    n = mesh.devices.size
    perm = [(i, (i + 1) % n) for i in range(n)]

    def kernel(la_local, fd_block):
        e_loc = la_local.shape[0]
        me = lax.axis_index("ring")

        def compare(out, fd_blk, src):
            ge = la_local[:, None, :] >= fd_blk[None, :, :]
            counts = jnp.sum(ge, axis=-1, dtype=jnp.int32)
            return lax.dynamic_update_slice(
                out, counts >= super_majority, (0, src * e_loc)
            )

        # local block first, then n-1 rotations: after s forward rotations
        # this chip holds the block that started on shard (me - s) mod n
        out0 = compare(
            jnp.zeros((e_loc, e_loc * n), bool), fd_block, me
        )

        def step(s, state):
            fd_blk, out = state
            fd_blk = lax.ppermute(fd_blk, "ring", perm)
            out = compare(out, fd_blk, (me - s) % n)
            return fd_blk, out

        _, out = lax.fori_loop(1, n, step, (fd_block, out0))
        return out

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P("ring", None), P("ring", None)),
        out_specs=P("ring", None),
        check_vma=False,
    )


def sharded_vote_counts(mesh: Mesh):
    """Super-majority vote tally with voters sharded across chips.

    votes [W, W'] bool (voter w says yay about candidate w') with voter
    rows sharded; eligible [W] bool marks voters that strongly-see the
    candidate's round. Yay counts are psum-reduced over the mesh — the
    DecideFame tally (oracle: hashgraph.go:930-960) as an ICI collective.
    """
    axes = ("dp", "sp")

    def kernel(votes_local, eligible_local):
        local = jnp.sum(
            votes_local & eligible_local[:, None], axis=0, dtype=jnp.int32
        )
        return lax.psum(local, axes)

    return shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes)),
        out_specs=P(None),
    )
