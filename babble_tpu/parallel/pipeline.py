"""Batched + sharded consensus pipeline.

``batched_pipeline`` vmaps the single-window sweep (ops.dag.pipeline_core)
over a batch of DAG windows; ``sharded_batched_pipeline`` jits it over a
(dp, sp) mesh so XLA partitions the batch across ``dp`` and the event
dimension across ``sp``, inserting ICI collectives for the cross-shard
compare/reduce steps (the [E, E] see/vote matrices contract over the
sharded event axis).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dag import DagSnapshot, pipeline_core, synthetic_snapshot


def batched_pipeline(sm: int, round_bound: int):
    """Return a jittable fn over [B, ...] batched snapshot tensors."""

    def one(creator, index, sp, op, la, fd, mid):
        return pipeline_core(creator, index, sp, op, la, fd, mid, sm, round_bound)

    return jax.vmap(one)


def sharded_batched_pipeline(mesh: Mesh, sm: int, round_bound: int):
    """The batched pipeline jitted with mesh shardings on inputs/outputs.

    Outputs: the per-window scalars (rounds, witness, lamport, fame,
    round_received) stay sharded [B, E] over (dp, sp); the [B, E, E]
    see/strongly-see matrices are row-sharded.
    """
    fn = batched_pipeline(sm, round_bound)
    s2 = NamedSharding(mesh, P("dp", "sp"))
    s3 = NamedSharding(mesh, P("dp", "sp", None))
    s_packed = NamedSharding(mesh, P("dp", None, "sp"))
    in_shardings = (s2, s2, s2, s2, s3, s3, s2)
    out_shardings = (s3, s3, s_packed)
    return jax.jit(fn, in_shardings=in_shardings, out_shardings=out_shardings)


def batch_of_snapshots(n_windows: int, n_peers: int, n_events: int):
    """Stack synthetic windows into [B, ...] arrays for benchmarks and the
    multi-chip dry run. Returns (arrays, super_majority)."""
    snaps = [
        synthetic_snapshot(n_peers, n_events, seed=11 + i) for i in range(n_windows)
    ]
    arrays = (
        np.stack([s.creator for s in snaps]),
        np.stack([s.index for s in snaps]),
        np.stack([s.self_parent for s in snaps]),
        np.stack([s.other_parent for s in snaps]),
        np.stack([s.last_ancestors for s in snaps]),
        np.stack([s.first_descendants for s in snaps]),
        np.stack([s.middle_bit for s in snaps]),
    )
    return arrays, snaps[0].super_majority
