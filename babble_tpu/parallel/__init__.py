"""Multi-chip parallelism: device meshes, sharded consensus kernels, and
explicit-collective reductions.

This is the framework's ICI data plane (SURVEY.md §2.3 "TPU-native
equivalent", §2.5): the gossip transport stays the DCN control plane while
per-chip batch work — DAG windows and vote reductions — is sharded over a
``jax.sharding.Mesh`` and reduced with XLA collectives.
"""

from .mesh import consensus_mesh, shard_batched_snapshot
from .pipeline import batched_pipeline, sharded_batched_pipeline
from .collectives import sharded_vote_counts, sharded_strongly_see

__all__ = [
    "consensus_mesh",
    "shard_batched_snapshot",
    "batched_pipeline",
    "sharded_batched_pipeline",
    "sharded_vote_counts",
    "sharded_strongly_see",
]
