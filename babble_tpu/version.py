"""Version info (reference: src/version/version.go:5-24).

``FLAG`` is the pre-release suffix ("-dev", "-rc1", ...). CI enforces that
it is EMPTY on the main branch (the reference's flagtest does the same via
TestFlagEmpty), so tagged releases can never carry a stray dev marker.
"""

MAJOR = 0
MINOR = 1
PATCH = 0
FLAG = ""

__version__ = f"{MAJOR}.{MINOR}.{PATCH}{FLAG}"
