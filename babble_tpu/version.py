"""Version info (reference: src/version/version.go)."""

MAJOR = 0
MINOR = 1
PATCH = 0

__version__ = f"{MAJOR}.{MINOR}.{PATCH}"
