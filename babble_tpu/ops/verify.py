"""Batched secp256k1 ECDSA verification on the accelerator (JAX).

Replaces the reference's per-event host verification
(/root/reference/src/hashgraph/hashgraph.go:672-687 ->
/root/reference/src/crypto/keys/signature.go:20) with a batch kernel.

Hybrid split — the right one for TPU:
- HOST (cheap, inherently sequential, ~us per signature): range checks on
  (r, s), on-curve check of the pubkey, e = H(m) truncation, w = s^-1 mod n,
  u1 = e*w, u2 = r*w, and the tiny per-pubkey precompute G+Q.
- DEVICE (the FLOPs): R = u1*G + u2*Q by interleaved Shamir double-and-add
  in Jacobian coordinates — 256 doublings + <=256 mixed additions of
  16x16-bit limb field ops, `vmap`-batched over signatures and shardable
  over a device mesh. No modular inversion on device: the affine check
  x(R) mod n == r is done projectively as X == (r or r+n) * Z^2 (valid
  because r < n and x < p < 2n).

Degenerate cases (point doubling inside an add, the point at infinity,
Q == -G making the G+Q table entry infinite) are all handled with limb
selects so the kernel is branch-free and fully jittable.

Differential oracle: babble_tpu/crypto/secp256k1.py (pure Python).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from babble_tpu.crypto import secp256k1 as ref
from babble_tpu.ops import limbs as fl
from babble_tpu.ops.limbs import (
    NLIMB,
    add_mod_p,
    eq,
    int_to_limbs,
    ints_to_limbs,
    is_zero,
    mul_mod_p,
    select,
    sqr_mod_p,
    sub_mod_p,
)

# ---------------------------------------------------------------------------
# Jacobian point ops. A point is (X, Y, Z) limb arrays; Z == 0 <=> infinity.
# Curve: y^2 = x^3 + 7 (a = 0), so the a-term vanishes in doubling.
# ---------------------------------------------------------------------------


def _double(X, Y, Z):
    """dbl-2009-l formulas for a=0; infinity (Z=0) maps to Z3=0."""
    A = sqr_mod_p(X)
    B = sqr_mod_p(Y)
    Cc = sqr_mod_p(B)
    t = sqr_mod_p(add_mod_p(X, B))
    D = sub_mod_p(sub_mod_p(t, A), Cc)
    D = add_mod_p(D, D)  # 2*((X+B)^2 - A - C)
    E = add_mod_p(add_mod_p(A, A), A)  # 3*A
    F = sqr_mod_p(E)
    X3 = sub_mod_p(F, add_mod_p(D, D))
    eightC = add_mod_p(add_mod_p(Cc, Cc), add_mod_p(Cc, Cc))
    eightC = add_mod_p(eightC, eightC)
    Y3 = sub_mod_p(mul_mod_p(E, sub_mod_p(D, X3)), eightC)
    YZ = mul_mod_p(Y, Z)
    Z3 = add_mod_p(YZ, YZ)
    return X3, Y3, Z3


def _add_mixed(X1, Y1, Z1, x2, y2, inf2):
    """Jacobian += affine (z2 = 1), branch-free.

    Handles: P1 infinite -> P2 lifted; P2 infinite -> P1; P1 == P2 ->
    doubling; P1 == -P2 -> infinity.
    """
    inf1 = is_zero(Z1)
    Z1Z1 = sqr_mod_p(Z1)
    U2 = mul_mod_p(x2, Z1Z1)
    S2 = mul_mod_p(y2, mul_mod_p(Z1, Z1Z1))
    H = sub_mod_p(U2, X1)
    R = sub_mod_p(S2, Y1)
    h_zero = is_zero(H)
    r_zero = is_zero(R)
    same_point = h_zero & r_zero & ~inf1 & ~inf2
    negated = h_zero & ~r_zero & ~inf1 & ~inf2  # P1 == -P2

    HH = sqr_mod_p(H)
    HHH = mul_mod_p(H, HH)
    U1HH = mul_mod_p(X1, HH)
    X3 = sub_mod_p(
        sub_mod_p(sqr_mod_p(R), HHH), add_mod_p(U1HH, U1HH)
    )
    Y3 = sub_mod_p(
        mul_mod_p(R, sub_mod_p(U1HH, X3)), mul_mod_p(Y1, HHH)
    )
    Z3 = mul_mod_p(Z1, H)

    dX, dY, dZ = _double(X1, Y1, Z1)

    one = jnp.zeros_like(X1).at[..., 0].set(1)
    zero = jnp.zeros_like(X1)

    # priority: P2 inf -> P1; P1 inf -> lift(P2); same -> double;
    # negated -> infinity; else general add
    X_out = select(same_point, dX, X3)
    Y_out = select(same_point, dY, Y3)
    Z_out = select(same_point, dZ, Z3)
    Z_out = select(negated, zero, Z_out)
    X_out = select(inf1, x2, X_out)
    Y_out = select(inf1, y2, Y_out)
    Z_out = select(inf1, jnp.where(inf2[..., None], zero, one), Z_out)
    X_out = select(inf2, X1, X_out)
    Y_out = select(inf2, Y1, Y_out)
    Z_out = select(inf2, Z1, Z_out)
    return X_out, Y_out, Z_out


# ---------------------------------------------------------------------------
# Shamir ladder kernel
# ---------------------------------------------------------------------------


def _shamir_kernel(
    u1: jnp.ndarray,  # [B, 16] scalar limbs
    u2: jnp.ndarray,  # [B, 16]
    table_x: jnp.ndarray,  # [B, 4, 16]  (index 0 unused, 1=G, 2=Q, 3=G+Q)
    table_y: jnp.ndarray,  # [B, 4, 16]
    table_inf: jnp.ndarray,  # [B, 4] bool
    r: jnp.ndarray,  # [B, 16] signature r
    rn: jnp.ndarray,  # [B, 16] r + n (only checked when rn_ok)
    rn_ok: jnp.ndarray,  # [B] bool: r + n < p
) -> jnp.ndarray:
    """Returns [B] bool: u1*G + u2*Q has x-coordinate === r (mod n)."""
    B = u1.shape[0]
    X = jnp.zeros((B, NLIMB), dtype=jnp.uint32)
    Y = jnp.zeros((B, NLIMB), dtype=jnp.uint32)
    Z = jnp.zeros((B, NLIMB), dtype=jnp.uint32)  # infinity

    def body(i, state):
        X, Y, Z = state
        bit = 255 - i
        limb_i = bit // fl.LIMB_BITS
        shift = bit % fl.LIMB_BITS
        b1 = (jax.lax.dynamic_index_in_dim(u1, limb_i, axis=1, keepdims=False) >> shift) & 1
        b2 = (jax.lax.dynamic_index_in_dim(u2, limb_i, axis=1, keepdims=False) >> shift) & 1
        sel = (b1 + 2 * b2).astype(jnp.int32)  # [B] in {0,1,2,3}

        X, Y, Z = _double(X, Y, Z)

        ax = jnp.take_along_axis(table_x, sel[:, None, None], axis=1)[:, 0]
        ay = jnp.take_along_axis(table_y, sel[:, None, None], axis=1)[:, 0]
        ainf = jnp.take_along_axis(table_inf, sel[:, None], axis=1)[:, 0]
        ainf = ainf | (sel == 0)

        X, Y, Z = _add_mixed(X, Y, Z, ax, ay, ainf)
        return X, Y, Z

    X, Y, Z = jax.lax.fori_loop(0, 256, body, (X, Y, Z))

    not_inf = ~is_zero(Z)
    Z2 = sqr_mod_p(Z)
    lhs = X  # X === x * Z^2
    ok_r = eq(lhs, mul_mod_p(r, Z2))
    ok_rn = eq(lhs, mul_mod_p(rn, Z2)) & rn_ok
    return not_inf & (ok_r | ok_rn)


_kernel_jit = jax.jit(_shamir_kernel)

# Fixed device batch: every call is padded to a multiple of this, so the
# kernel compiles once. 64 lanes is negligible waste on TPU vector units.
TILE = 64


def warmup() -> None:
    """Compile the kernel ahead of the gossip hot path (call at node init
    when the accelerator flag is on)."""
    dummy = [((ref.GX, ref.GY), b"\x00" * 32, 1, 1)]
    batch_verify(dummy)


# ---------------------------------------------------------------------------
# Host wrapper
# ---------------------------------------------------------------------------


def _prepare(
    items: Sequence[Tuple[Tuple[int, int], bytes, int, int]]
) -> Tuple[np.ndarray, ...]:
    """items: [(pub(x,y), msg_hash bytes, r, s)] -> device-ready arrays.

    Host-side rejects (bad range, off-curve) are encoded by a pre-mask;
    their slots get dummy-but-wellformed data so the kernel stays uniform.
    """
    B = len(items)
    pre_ok = np.zeros(B, dtype=bool)
    u1s: List[int] = []
    u2s: List[int] = []
    tx = np.zeros((B, 4, NLIMB), dtype=np.uint32)
    ty = np.zeros((B, 4, NLIMB), dtype=np.uint32)
    tinf = np.ones((B, 4), dtype=bool)
    rs: List[int] = []
    rns: List[int] = []
    rn_ok = np.zeros(B, dtype=bool)

    g_limbs = (int_to_limbs(ref.GX), int_to_limbs(ref.GY))

    for b, (pub, msg_hash, r, s) in enumerate(items):
        if not (1 <= r < ref.N and 1 <= s < ref.N) or not ref.is_on_curve(pub):
            u1s.append(1)
            u2s.append(1)
            rs.append(1)
            rns.append(1)
            continue
        pre_ok[b] = True
        e = ref._bits2int(msg_hash)
        w = pow(s, -1, ref.N)
        u1s.append((e * w) % ref.N)
        u2s.append((r * w) % ref.N)
        rs.append(r)
        rn = r + ref.N
        rns.append(rn if rn < ref.P else 1)
        rn_ok[b] = rn < ref.P
        # table: 1 = G, 2 = Q, 3 = G + Q
        tx[b, 1], ty[b, 1] = g_limbs
        tinf[b, 1] = False
        tx[b, 2] = int_to_limbs(pub[0])
        ty[b, 2] = int_to_limbs(pub[1])
        tinf[b, 2] = False
        gq = ref.point_add(ref.G, pub)
        if gq is not None:
            tx[b, 3] = int_to_limbs(gq[0])
            ty[b, 3] = int_to_limbs(gq[1])
            tinf[b, 3] = False

    return (
        pre_ok,
        ints_to_limbs(u1s),
        ints_to_limbs(u2s),
        tx,
        ty,
        tinf,
        ints_to_limbs(rs),
        ints_to_limbs(rns),
        rn_ok,
    )


def batch_verify(
    items: Sequence[Tuple[Tuple[int, int], bytes, int, int]]
) -> np.ndarray:
    """Verify a batch of ECDSA signatures; returns [B] bool.

    items: [(pub(x,y), msg_hash, r, s)]. Semantics identical to
    babble_tpu.crypto.secp256k1.verify applied elementwise.
    """
    if len(items) == 0:
        return np.zeros(0, dtype=bool)
    n = len(items)
    # Pad to a multiple of one fixed tile size so XLA compiles exactly one
    # kernel, ever — variable batch sizes would each trigger a ~15 s
    # compile, which would stall the gossip hot path.
    padded = ((n + TILE - 1) // TILE) * TILE
    dummy = ((ref.GX, ref.GY), b"\x00" * 32, 1, 1)
    items = list(items) + [dummy] * (padded - n)
    pre_ok, u1, u2, tx, ty, tinf, r, rn, rn_ok = _prepare(items)
    outs = []
    for t in range(padded // TILE):
        sl = slice(t * TILE, (t + 1) * TILE)
        outs.append(
            _kernel_jit(
                jnp.asarray(u1[sl]),
                jnp.asarray(u2[sl]),
                jnp.asarray(tx[sl]),
                jnp.asarray(ty[sl]),
                jnp.asarray(tinf[sl]),
                jnp.asarray(r[sl]),
                jnp.asarray(rn[sl]),
                jnp.asarray(rn_ok[sl]),
            )
        )
    out = np.concatenate([np.asarray(o) for o in outs])
    return (out & pre_ok)[:n]


def prevalidate_events(events) -> None:
    """Batch-verify the signatures of a list of hashgraph Events on the
    accelerator and cache the verdicts on the events, so the subsequent
    per-event ``Event.verify()`` in the insert path
    (babble_tpu/hashgraph/hashgraph.py insert_event; reference
    hashgraph.go:672-687) becomes a cache hit.

    Each event contributes one item for the creator signature plus one per
    internal transaction; the event verdict is the AND of its items.
    Structurally invalid items (undecodable signature / off-curve key) fail
    host-side, same as the scalar path. Item collection is shared with the
    host batch verifier (babble_tpu.crypto.batch) so the two backends can
    never diverge on what counts as a consensus-relevant signature.
    """
    from babble_tpu.crypto.batch import collect_signature_items

    items, spans = collect_signature_items(events)
    results = batch_verify(items)
    for ev, start, count, ok_static in spans:
        ok = ok_static and bool(results[start : start + count].all())
        ev.prevalidate(ok)


class BatchVerifier:
    """Accumulates (pub, hash, r, s) work items and flushes them through the
    device kernel in one batch — the tpu-side replacement for the tight
    per-event verify in the reference insert path (hashgraph.go:672-687).
    """

    def __init__(self) -> None:
        self._items: List[Tuple[Tuple[int, int], bytes, int, int]] = []

    def add(self, pub: Tuple[int, int], msg_hash: bytes, r: int, s: int) -> int:
        self._items.append((pub, msg_hash, r, s))
        return len(self._items) - 1

    def __len__(self) -> int:
        return len(self._items)

    def flush(self) -> np.ndarray:
        out = batch_verify(self._items)
        self._items = []
        return out
