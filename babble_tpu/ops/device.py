"""Device resolution for live nodes: probe the accelerator, fall back to CPU.

Under the axon tunnel, ``jax.devices()`` blocks indefinitely when the TPU
link is down (observed as the round-2 bench's "device tunnel timeout"). A
node started with ``--accelerator`` must not wedge on that, so before any
in-process jax backend initialization we probe the configured platform in a
throwaway subprocess with a timeout; on failure this process is switched to
the CPU backend — the same kernels run, just on host XLA — and the node
keeps its accelerated code path.

Also installs the persistent XLA compilation cache for live processes (the
test conftest does this only for pytest runs): the secp256k1 ladder kernel
takes ~15 s to compile per batch bucket, and the voting kernels compile per
window-shape bucket, so warm restarts matter.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
from typing import Optional

logger = logging.getLogger("babble_tpu.ops.device")

_lock = threading.Lock()
_resolved: Optional[str] = None


def resolved() -> Optional[str]:
    """The platform ensure_device() settled on, or None before any probe."""
    return _resolved


def on_accelerator() -> bool:
    """True when jax dispatches to a real accelerator in this process —
    resolved platform if a probe ran, else the actual default backend.
    Drives the economics switches (pipelined sweeps, crossover windows):
    on host XLA readback is free and synchronous sweeps win; through an
    accelerator tunnel readback costs ~65-100 ms and must be pipelined."""
    r = _resolved
    if r is not None and r.split(",")[0] == "cpu":
        return False
    import jax

    return jax.default_backend() != "cpu"


def is_cpu_fallback() -> bool:
    """True when the accelerated path is running on host XLA (resolved
    platform is cpu). Callers use this to route work where host XLA loses
    to native host code — e.g. signature verification goes to the C++
    batch verifier instead of the JAX limb kernel, whose only advantage is
    a real matrix unit."""
    r = _resolved
    return r is not None and r.split(",")[0] == "cpu"

PROBE_TIMEOUT_S = float(os.environ.get("BABBLE_DEVICE_PROBE_TIMEOUT", "60"))


def _setup_compile_cache(jax) -> None:
    cache = os.environ.get(
        "BABBLE_JAX_CACHE", os.path.expanduser("~/.cache/babble_tpu/jax")
    )
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # cache is an optimization, never fatal
        logger.debug("compilation cache unavailable", exc_info=True)


def ensure_device(timeout_s: float = PROBE_TIMEOUT_S) -> str:
    """Resolve the jax platform once per process, before any backend init.

    Returns the platform this process will use ("cpu", the configured
    platform, or "default"). Thread-safe; the probe runs at most once.
    """
    global _resolved
    with _lock:
        if _resolved is not None:
            return _resolved
        import jax

        _setup_compile_cache(jax)

        cfg = jax.config.jax_platforms  # set by conftest or earlier callers
        target = cfg or os.environ.get("JAX_PLATFORMS", "")
        # Only the FIRST platform matters: "axon,cpu" initializes axon and
        # blocks on a dead tunnel despite the cpu entry behind it.
        preferred = target.split(",")[0] if target else ""
        if preferred in ("", "cpu"):
            _resolved = target or "default"
            return _resolved

        try:
            # The child only inherits os.environ, so pin the platform there
            # in case it was configured via jax.config in this process.
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s,
                capture_output=True,
                env={**os.environ, "JAX_PLATFORMS": target},
            )
            ok = proc.returncode == 0
        except subprocess.TimeoutExpired:
            ok = False
        if ok:
            _resolved = target
        else:
            logger.warning(
                "platform %r unreachable (probe timeout %.0fs); "
                "falling back to CPU XLA for the accelerated path",
                target,
                timeout_s,
            )
            jax.config.update("jax_platforms", "cpu")
            _resolved = "cpu"
        return _resolved
