"""Device resolution for live nodes: probe the accelerator, fall back to CPU.

Under the axon tunnel, ``import jax`` itself can block indefinitely when the
TPU link is down or wedged (the site hook registers the PJRT plugin at
interpreter start; backend discovery then waits on the dead link — observed
as the round-2 bench's "device tunnel timeout" and reproduced in round 4 by
killing a bench mid-run). A node started with ``--accelerator`` must not
wedge on that, so the health of the configured platform is decided in a
throwaway SUBPROCESS with a timeout, BEFORE this process ever imports jax:

- probe succeeds      -> use the configured platform;
- probe fails quickly -> the platform errors cleanly; this process imports
  jax and runs the same kernels on host XLA ("cpu" fallback);
- probe TIMES OUT     -> the link is wedged and any jax import would hang;
  the device is marked DEAD and nothing in this process may import jax —
  the oracle carries consensus (``jax_usable()`` gates every jax path).

Also installs the persistent XLA compilation cache for live processes (the
test conftest does this only for pytest runs): voting kernels compile per
window-shape bucket (seconds each), so warm restarts matter.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
from typing import Optional

logger = logging.getLogger("babble_tpu.ops.device")

_lock = threading.Lock()
_resolved: Optional[str] = None

#: sentinel platform value: the link is wedged; importing jax would hang.
DEAD = "dead"


def resolved() -> Optional[str]:
    """The platform ensure_device() settled on, or None before any probe."""
    return _resolved


def jax_usable() -> bool:
    """False when importing jax in this process would hang (wedged tunnel).
    Every accelerated code path must check this before touching jax."""
    return _resolved != DEAD


def on_accelerator() -> bool:
    """True when jax dispatches to a real accelerator in this process —
    resolved platform if a probe ran, else the actual default backend.
    Drives the economics switches (pipelined sweeps, crossover windows):
    on host XLA readback is free and synchronous sweeps win; through an
    accelerator tunnel readback costs ~65-100 ms and must be pipelined."""
    r = _resolved
    if r is not None and r.split(",")[0] in ("", "cpu", DEAD):
        return False
    if r is not None and r.split(",")[0] != "default":
        return True
    # unresolved, or resolved to "default": ask the actual backend
    import jax

    return jax.default_backend() != "cpu"


def _is_tpu_device(dev) -> bool:
    """Shared TPU classifier for on_tpu() and describe() — one predicate so
    the bench's capture label and the TPU-layout code paths can't drift."""
    return (
        dev.platform in ("tpu", "axon")
        or "TPU" in getattr(dev, "device_kind", "")
        or "TPU" in str(dev)
    )


def on_tpu() -> bool:
    """True when the actual default backend is a TPU (incl. the axon
    tunnel). TPU-layout-specific code (Pallas kernels) gates on this, not
    on the looser on_accelerator()."""
    if not jax_usable() or not on_accelerator():
        return False
    import jax

    return _is_tpu_device(jax.devices()[0])


def is_cpu_fallback() -> bool:
    """True when the accelerated path is running on host XLA (resolved
    platform is cpu) or the device is dead. Callers use this to route work
    where host XLA loses to native host code — e.g. signature verification
    goes to the C++ batch verifier instead of the JAX limb kernel, whose
    only advantage is a real matrix unit."""
    r = _resolved
    return r is not None and r.split(",")[0] in ("cpu", DEAD)


def describe() -> dict:
    """The resolved device, as evidence: every bench capture stamps this so
    a CPU-XLA fallback can never masquerade as a TPU run (the round-4
    failure mode). ``capture_class`` derives from the ACTUAL live device
    string, never from the configured intent."""
    r = _resolved
    if r == DEAD:
        return {
            "resolved": DEAD,
            "device": None,
            "capture_class": "dead",
        }
    import jax

    dev = jax.devices()[0]
    return {
        "resolved": r or "default",
        "device": str(dev),
        "capture_class": "tpu" if _is_tpu_device(dev) else "cpu-xla",
    }


PROBE_TIMEOUT_S = float(os.environ.get("BABBLE_DEVICE_PROBE_TIMEOUT", "60"))


def _setup_compile_cache(jax) -> None:
    cache = os.environ.get(
        "BABBLE_JAX_CACHE", os.path.expanduser("~/.cache/babble_tpu/jax")
    )
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # cache is an optimization, never fatal
        logger.debug("compilation cache unavailable", exc_info=True)


def ensure_device(timeout_s: float = PROBE_TIMEOUT_S) -> str:
    """Resolve the jax platform once per process, before any backend init.

    Returns the platform this process will use ("cpu", the configured
    platform, "default", or DEAD). Thread-safe; the probe runs at most
    once. jax is imported in-process only when that is known to be safe.
    """
    global _resolved
    with _lock:
        if _resolved is not None:
            return _resolved

        # Resolution handoff from a parent process (exported below): child
        # processes of an already-probed parent (bench subprocesses, node
        # children) must not re-pay the probe — and with a wedged link
        # they would HANG importing jax before their own probe could run,
        # because the site hook pins the platform at interpreter start.
        pre = os.environ.get("BABBLE_DEVICE_RESOLVED")
        if pre:
            _resolved = pre
            if pre == DEAD:
                return _resolved
            import jax

            if pre != "default":
                # pin the actual platform, not just the bookkeeping —
                # otherwise a child could record "axon" while its backend
                # quietly initializes to something else, and the
                # economics switches (on_accelerator) would mis-dispatch
                os.environ["JAX_PLATFORMS"] = pre
                jax.config.update("jax_platforms", pre)
            _setup_compile_cache(jax)
            return _resolved

        target = os.environ.get("JAX_PLATFORMS", "")
        if "jax" in sys.modules:
            # jax already imported (and so already survived backend
            # discovery); respect any config-level platform override.
            import jax

            target = jax.config.jax_platforms or target
        # Only the FIRST platform matters: "axon,cpu" initializes axon and
        # blocks on a dead tunnel despite the cpu entry behind it.
        preferred = target.split(",")[0] if target else ""
        if preferred == "cpu" and "jax" in sys.modules:
            # CPU explicitly pinned and the import already survived (test
            # conftest): nothing to probe. Export the handoff like every
            # other resolution path so children skip their probe too.
            import jax

            _setup_compile_cache(jax)
            _resolved = target
            os.environ["BABBLE_DEVICE_RESOLVED"] = _resolved
            return _resolved

        # Bounded retry with backoff (BABBLE_DEVICE_PROBE_RETRIES, default
        # 0): the axon tunnel wedges transiently, and round 4's bench
        # silently published CPU-fallback numbers because one failed probe
        # was final. Long-running captures opt into a few retries so a
        # tunnel that comes back within minutes still yields a real-TPU
        # capture; nodes keep the fail-fast default (a node must start
        # serving gossip, and its oracle carries consensus meanwhile).
        retries = int(os.environ.get("BABBLE_DEVICE_PROBE_RETRIES", "0"))
        backoff_s = float(os.environ.get("BABBLE_DEVICE_PROBE_BACKOFF", "30"))
        fast_failures = 0
        for attempt in range(retries + 1):
            if attempt:
                logger.warning(
                    "device probe attempt %d/%d failed; retrying in %.0fs",
                    attempt, retries + 1, backoff_s,
                )
                import time as _time

                _time.sleep(backoff_s)
            timed_out = False
            try:
                # The child only inherits os.environ, so pin the platform
                # there in case it was configured via jax.config here.
                proc = subprocess.run(
                    [sys.executable, "-c", "import jax; jax.devices()"],
                    timeout=timeout_s,
                    capture_output=True,
                    env={**os.environ, "JAX_PLATFORMS": target or ""},
                )
                ok = proc.returncode == 0
            except subprocess.TimeoutExpired:
                ok = False
                timed_out = True
            if ok:
                break
            if not timed_out:
                # A fast non-zero exit is usually deterministic (platform
                # not installed, plugin error): one retry covers transient
                # connection refusals, but burning the full retry budget
                # on an outcome that cannot change just stalls the
                # fallback. Timeouts (wedged tunnel) keep the full budget.
                fast_failures += 1
                if fast_failures >= 2:
                    break

        if ok:
            _resolved = target or "default"
        elif timed_out and "jax" not in sys.modules:
            # Wedged link: importing jax here would hang this process too.
            logger.warning(
                "jax backend init hung past %.0fs (wedged device link); "
                "marking the device DEAD — the oracle carries consensus",
                timeout_s,
            )
            _resolved = DEAD
            os.environ["BABBLE_DEVICE_RESOLVED"] = DEAD
            return _resolved
        else:
            logger.warning(
                "platform %r unreachable (probe failed, timeout %.0fs); "
                "falling back to CPU XLA for the accelerated path",
                target,
                timeout_s,
            )
            _resolved = "cpu"
            os.environ["JAX_PLATFORMS"] = "cpu"

        # Export for child processes (see the handoff above).
        os.environ["BABBLE_DEVICE_RESOLVED"] = _resolved

        import jax

        if _resolved == "cpu":
            jax.config.update("jax_platforms", "cpu")
        _setup_compile_cache(jax)
        return _resolved
