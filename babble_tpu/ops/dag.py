"""Tensorized hashgraph pipeline — the DAG consensus math as XLA programs.

This is the TPU-first re-expression of the consensus hot loops (SURVEY.md §7
step 4b-d). Instead of the oracle's per-event recursion with LRU caches
(reference: src/hashgraph/hashgraph.go:172-206 stronglySee, 208-282 round,
875-998 DecideFame, 1002-1095 DecideRoundReceived), the whole undetermined
window is packed into dense struct-of-arrays tensors and processed with
masked comparisons, matmuls, and fixpoint sweeps:

- events are rows; peers are columns (``PeerSet.peer_index`` fixes the
  coordinate of each peer).
- ``last_ancestors``/``first_descendants`` become ``[E, P] int32`` tensors.
- ``stronglySee`` becomes a broadcast compare + super-majority reduction —
  an ``[E, E, P]`` masked tensor summed over P.
- round assignment becomes a bounded fixpoint sweep (``lax.while_loop``):
  each pass propagates parent rounds one DAG level further.
- virtual voting becomes per-round vote matrices ``[E, E]`` updated by
  masked matmuls (yay counts = SS @ votes), with coin-round hash bits.
- round-received becomes famous-witness see-mask reductions.

Everything is jittable with static shapes (pad E to a bucket size for
compile-cache friendliness). Differential-tested against the CPU oracle on
the golden DAGs in tests/test_ops_dag.py.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from babble_tpu.ops.intdot import vote_matmul

INT32_MAX = np.int32(2**31 - 1)


@dataclass
class DagSnapshot:
    """Dense struct-of-arrays view of a DAG window.

    E = number of events (topological order), P = number of peers.
    Missing coordinates: last_ancestors = -1, first_descendants = INT32_MAX.
    """

    creator: np.ndarray  # [E] int32, peer index of each event's creator
    index: np.ndarray  # [E] int32, per-creator sequence number
    self_parent: np.ndarray  # [E] int32, event row of self-parent, -1 if none
    other_parent: np.ndarray  # [E] int32, event row of other-parent, -1 if none
    last_ancestors: np.ndarray  # [E, P] int32
    first_descendants: np.ndarray  # [E, P] int32
    middle_bit: np.ndarray  # [E] bool, coin-round bit of each event's hash
    n_peers: int
    hashes: List[str]  # row -> event hex (host-side bookkeeping only)

    @property
    def n_events(self) -> int:
        return int(self.creator.shape[0])

    # super-majority threshold of the window's peer-set; filled by
    # snapshot_from_hashgraph from PeerSet.super_majority() so the tensor
    # pipeline can never drift from the oracle's rule.
    super_majority: int = 0


def snapshot_from_hashgraph(h, event_hashes: Optional[List[str]] = None) -> DagSnapshot:
    """Extract a DagSnapshot from a Hashgraph (oracle) store.

    ``event_hashes`` defaults to all events in topological order. The peer
    coordinate is the sorted-PeerSet index (PeerSet.peer_index).
    """
    from babble_tpu.hashgraph.hashgraph import middle_bit

    store = h.store
    peer_set = store.get_peer_set(0)
    pub_keys = peer_set.pub_keys()
    peer_col = {pk: i for i, pk in enumerate(pub_keys)}
    n_peers = len(pub_keys)

    if event_hashes is None:
        from babble_tpu.common.errors import StoreError

        events = []
        for pk in pub_keys:
            try:
                hashes = store.participant_events(pk, -1)
            except StoreError:
                continue  # participant has no events yet
            events.extend(store.get_event(eh) for eh in hashes)
        events.sort(key=lambda e: e.topological_index)
        event_hashes = [e.hex() for e in events]

    row = {eh: i for i, eh in enumerate(event_hashes)}
    E = len(event_hashes)

    creator = np.full(E, -1, np.int32)
    index = np.full(E, -1, np.int32)
    self_parent = np.full(E, -1, np.int32)
    other_parent = np.full(E, -1, np.int32)
    la = np.full((E, n_peers), -1, np.int32)
    fd = np.full((E, n_peers), INT32_MAX, np.int32)
    mid = np.zeros(E, bool)

    for i, eh in enumerate(event_hashes):
        ev = store.get_event(eh)
        creator[i] = peer_col[ev.creator()]
        index[i] = ev.index()
        self_parent[i] = row.get(ev.self_parent(), -1)
        other_parent[i] = row.get(ev.other_parent(), -1)
        for pk, coords in ev.last_ancestors.items():
            if pk in peer_col:
                la[i, peer_col[pk]] = coords.index
        for pk, coords in ev.first_descendants.items():
            if pk in peer_col:
                fd[i, peer_col[pk]] = coords.index
        mid[i] = middle_bit(eh)

    return DagSnapshot(
        creator=creator,
        index=index,
        self_parent=self_parent,
        other_parent=other_parent,
        last_ancestors=la,
        first_descendants=fd,
        middle_bit=mid,
        n_peers=n_peers,
        hashes=list(event_hashes),
        super_majority=peer_set.super_majority(),
    )


# =============================================================================
# Predicates as tensor ops
# =============================================================================


def see_matrix(creator: jnp.ndarray, index: jnp.ndarray, la: jnp.ndarray) -> jnp.ndarray:
    """SEE[x, y] = x sees y = la[x, creator(y)] >= index(y)
    (oracle: Hashgraph._ancestor via lastAncestors, hashgraph.go:108-128)."""
    # gather la[x, creator[y]] -> [E, E]
    la_xc = la[:, creator]  # [E(x), E(y)]
    return la_xc >= index[None, :]


def strongly_see_matrix(
    la: jnp.ndarray, fd: jnp.ndarray, super_majority: int
) -> jnp.ndarray:
    """SS[x, y] = #{p : la[x,p] >= fd[y,p]} >= super_majority, with missing
    coordinates excluded by the -1 / INT32_MAX sentinels
    (oracle: hashgraph.go:184-206).

    Memory note: materializes [E, E, P]; BABBLE_PALLAS=1 on a real TPU
    routes this through the Pallas tiled kernel
    (ops/pallas_kernels.strongly_see_pallas), which streams the peer axis
    through VMEM instead — O(TILE_X * E) peak, no [E, E, P] intermediate.
    """
    import os

    if os.environ.get("BABBLE_PALLAS") == "1":
        from babble_tpu.ops.device import on_tpu

        if on_tpu():
            from babble_tpu.ops.pallas_kernels import strongly_see_pallas

            return strongly_see_pallas(la, fd, super_majority)
    ge = la[:, None, :] >= fd[None, :, :]  # [E, E, P]
    counts = jnp.sum(ge, axis=-1, dtype=jnp.int32)
    return counts >= super_majority


# =============================================================================
# Round assignment — fixpoint frontier sweep
# =============================================================================


def compute_rounds(
    creator: jnp.ndarray,
    self_parent: jnp.ndarray,
    other_parent: jnp.ndarray,
    ss: jnp.ndarray,
    super_majority: int,
    max_iters: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Iteratively compute (rounds, witness flags) for every event.

    Replaces the oracle's recursive ``round``/``witness`` (hashgraph.go:
    208-327): each sweep recomputes every event's round from its parents'
    current rounds and the strongly-seen witnesses of the parent round;
    sweeping to fixpoint propagates one DAG level per pass. All ops are
    static-shape tensor ops, so XLA fuses the whole sweep into one program.
    """
    E = creator.shape[0]
    if max_iters is None:
        max_iters = E + 2

    has_sp = self_parent >= 0
    has_op = other_parent >= 0
    sp = jnp.where(has_sp, self_parent, 0)
    op = jnp.where(has_op, other_parent, 0)

    def witness_of(rounds: jnp.ndarray) -> jnp.ndarray:
        # witness = first event of its round on its creator's chain:
        # no self-parent, or round > self-parent's round (hashgraph.go:297-327).
        sp_round = jnp.where(has_sp, rounds[sp], -1)
        return rounds > sp_round

    def sweep(rounds: jnp.ndarray) -> jnp.ndarray:
        sp_round = jnp.where(has_sp, rounds[sp], -1)
        op_round = jnp.where(has_op, rounds[op], -1)
        parent_round = jnp.maximum(sp_round, op_round)  # [E]

        wit = witness_of(rounds)
        # count witnesses w of round parent_round[x] strongly seen by x
        same_round = rounds[None, :] == parent_round[:, None]  # [E(x), E(w)]
        seen = ss & same_round & wit[None, :]
        counts = jnp.sum(seen, axis=1)
        inc = counts >= super_majority
        return jnp.where(parent_round < 0, 0, parent_round + inc)

    def cond(state):
        i, rounds, changed = state
        return jnp.logical_and(i < max_iters, changed)

    def body(state):
        i, rounds, _ = state
        new_rounds = sweep(rounds)
        return i + 1, new_rounds, jnp.any(new_rounds != rounds)

    rounds0 = jnp.zeros(E, jnp.int32)
    _, rounds, _ = lax.while_loop(cond, body, (0, rounds0, jnp.array(True)))
    return rounds, witness_of(rounds)


def compute_lamport(
    self_parent: jnp.ndarray, other_parent: jnp.ndarray, max_iters: Optional[int] = None
) -> jnp.ndarray:
    """Lamport timestamps via the same fixpoint pattern
    (oracle: hashgraph.go:355-387)."""
    E = self_parent.shape[0]
    if max_iters is None:
        max_iters = E + 2
    has_sp = self_parent >= 0
    has_op = other_parent >= 0
    sp = jnp.where(has_sp, self_parent, 0)
    op = jnp.where(has_op, other_parent, 0)

    def body(state):
        i, lt, _ = state
        plt = jnp.maximum(
            jnp.where(has_sp, lt[sp], -1), jnp.where(has_op, lt[op], -1)
        )
        new_lt = plt + 1
        return i + 1, new_lt, jnp.any(new_lt != lt)

    def cond(state):
        i, _, changed = state
        return jnp.logical_and(i < max_iters, changed)

    _, lt, _ = lax.while_loop(
        cond, body, (0, jnp.zeros(E, jnp.int32), jnp.array(True))
    )
    return lt


# =============================================================================
# Virtual voting — fame as masked matmuls
# =============================================================================


def decide_fame(
    rounds: jnp.ndarray,
    witness: jnp.ndarray,
    see: jnp.ndarray,
    ss: jnp.ndarray,
    middle_bit: jnp.ndarray,
    super_majority: int,
    last_round: int,
    coin_round_freq: int = 4,
) -> jnp.ndarray:
    """Fame of every witness: +1 famous, 0 undecided, -1 not famous.

    Vectorization of the oracle's VOTE_LOOP (hashgraph.go:875-998): for each
    voting round j, every remaining witness-pair (y in round j, x any earlier
    witness) updates in parallel:

    - diff == 1: votes[y, x] = SEE[y, x]
    - else: yays[y, x] = Σ_w SS_j-1[y, w] · votes[w, x] over witnesses w of
      round j-1 — one boolean matmul for ALL (y, x) pairs at once; majority
      and super-majority thresholds decide or carry the vote; coin rounds
      (diff % freq == 0) fall back to y's hash bit when not settled.

    Decisions freeze (first decision wins), exactly like the sticky
    roundEvent.Famous in the oracle.
    """
    E = rounds.shape[0]

    def per_round(j, state):
        votes, fame = state
        # voters: witnesses of round j
        voter = witness & (rounds == j)  # [E]
        diff = j - rounds  # [E(x)] per candidate

        # --- direct vote at diff 1
        direct = see  # [E(y), E(x)]

        # --- derived vote: majority among strongly-seen witnesses of j-1
        prev_wit = witness & (rounds == (j - 1))  # [E(w)]
        ss_prev = ss & prev_wit[None, :]  # [E(y), E(w)]
        n_ss = jnp.sum(ss_prev, axis=1)  # [E(y)]
        # the pipeline's FLOPs center, as an exact int8->int32 MXU tally
        yays = vote_matmul(ss_prev, votes)  # [E(y), E(x)]
        nays = n_ss[:, None] - yays
        v = yays >= nays
        t = jnp.maximum(yays, nays)
        settled = t >= super_majority

        is_coin = (diff % coin_round_freq) == 0  # [E(x)]
        # normal round: vote = v; decided when settled
        # coin round: vote = v if settled else middle_bit(y)
        derived_vote = jnp.where(
            is_coin[None, :] & ~settled, middle_bit[:, None], v
        )
        new_vote = jnp.where((diff == 1)[None, :], direct, derived_vote)

        # A (y, x) pair only participates when y is a voter and x is an
        # earlier witness (diff >= 1).
        active = voter[:, None] & witness[None, :] & (diff >= 1)[None, :]
        votes = jnp.where(active, new_vote, votes)

        # Decisions: normal rounds only, settled pairs, undecided candidates.
        decide_pair = (
            active & ~is_coin[None, :] & (diff > 1)[None, :] & settled
        )  # [E(y), E(x)]
        decided_now = jnp.any(decide_pair, axis=0)  # [E(x)]
        # value decided: v from any deciding voter (all deciding voters of the
        # same x agree by construction — they share the settled super-majority)
        decided_val = jnp.any(decide_pair & v, axis=0)
        newly = decided_now & (fame == 0)
        fame = jnp.where(newly, jnp.where(decided_val, 1, -1), fame)
        return votes, fame

    votes0 = jnp.zeros((E, E), bool)
    fame0 = jnp.zeros(E, jnp.int32)
    votes, fame = lax.fori_loop(1, last_round + 1, per_round, (votes0, fame0))
    return fame


def decide_round_received(
    rounds: jnp.ndarray,
    witness: jnp.ndarray,
    fame: jnp.ndarray,
    see: jnp.ndarray,
    super_majority: int,
    last_round: int,
) -> jnp.ndarray:
    """round_received[x], or -1 if undetermined (oracle: hashgraph.go:1002-1095).

    For each decided round i (all witnesses decided), an event x is received
    at the FIRST i > round(x) where every famous witness of i sees x and the
    famous count reaches the super-majority — a per-round boolean reduction
    over the SEE mask.
    """
    E = rounds.shape[0]

    # decided round: has witnesses, none undecided, famous count... The oracle
    # requires a super-majority of decided witnesses and zero undecided.
    def round_decided(i):
        wits = witness & (rounds == i)
        undecided = wits & (fame == 0)
        n_decided = jnp.sum(wits & (fame != 0))
        return (~jnp.any(undecided)) & (n_decided >= super_majority)

    def per_round(i, state):
        rr, blocked = state
        decided = round_decided(i)
        fw = witness & (rounds == i) & (fame == 1)  # famous witnesses of i
        n_fw = jnp.sum(fw)
        # x received at i: every famous witness sees x, count >= sm
        sees_x = see | (~fw)[:, None]  # ignore non-famous rows
        all_see = jnp.all(sees_x, axis=0) & (n_fw >= super_majority)
        relevant = rounds < i  # the oracle's i loop starts at round(x)+1
        eligible = decided & ~blocked & relevant & (rr < 0) & all_see
        rr = jnp.where(eligible, i, rr)
        # An event stops scanning at its first undecided round AFTER its own
        # round (the oracle breaks out of the per-event i loop) — per-event,
        # because the scan starts at round(x)+1.
        blocked = blocked | (relevant & ~decided)
        return rr, blocked

    rr0 = jnp.full(E, -1, jnp.int32)
    blocked0 = jnp.zeros(E, bool)
    rr, _ = lax.fori_loop(1, last_round + 1, per_round, (rr0, blocked0))
    return rr


# =============================================================================
# Full pipeline entry
# =============================================================================


# Counts traces of _run_jit, so tests can pin the compile-cache property.
_trace_count = 0


def pipeline_core(creator, index, sp, op, la, fd, mid, sm, round_bound):
    """The whole consensus sweep as one traceable function. ``sm`` and
    ``round_bound`` must be Python ints (static under jit).

    Returns (see, ss, packed) where packed is [5, E] int32 stacking
    (rounds, witness, lamport, fame, round_received) — one tensor so hosts
    behind a high-latency device link pay a single transfer for all
    per-event results (each fetch costs ~50 ms flat over the axon tunnel).
    """
    global _trace_count
    _trace_count += 1
    see = see_matrix(creator, index, la)
    ss = strongly_see_matrix(la, fd, sm)
    rounds, wit = compute_rounds(creator, sp, op, ss, sm)
    lamport = compute_lamport(sp, op)
    fame = decide_fame(rounds, wit, see, ss, mid, sm, round_bound)
    rr = decide_round_received(rounds, wit, fame, see, sm, round_bound)
    packed = jnp.stack(
        [
            rounds.astype(jnp.int32),
            wit.astype(jnp.int32),
            lamport.astype(jnp.int32),
            fame.astype(jnp.int32),
            rr.astype(jnp.int32),
        ]
    )
    return see, ss, packed


_run_jit = partial(jax.jit, static_argnums=(7, 8))(pipeline_core)


def run_pipeline(
    snapshot: DagSnapshot, return_matrices: bool = False
) -> Dict[str, np.ndarray]:
    """Run the tensorized pipeline on a snapshot; returns host arrays.

    This is the all-at-once (batch) formulation: given the DAG window, it
    computes rounds, witnesses, lamport timestamps, fame, and round-received
    in one jit-compiled program, cached per (shape, super-majority, bound).

    Only the [E] per-event outputs are fetched to the host; the [E, E]
    see/strongly-see matrices are device intermediates and are only
    transferred when ``return_matrices`` is set (host<->device bandwidth is
    the bottleneck, not FLOPs — fetching them costs ~7x the compute).
    """
    sm = snapshot.super_majority

    # Loop bound for the voting/receiving sweeps. Rounds are data-dependent,
    # but advancing past round r requires strongly seeing a super-majority
    # of round-r witnesses, so every passed round contains >= sm distinct
    # witness events: last_round <= E // sm + 1. The bound is derived from
    # (shape, sm) only — both already static — so the jit cache stays warm
    # across windows. Iterations past the real last round see empty voter
    # masks and are no-ops.
    round_bound = snapshot.n_events // max(1, sm) + 2

    see, ss, packed = _run_jit(
        jnp.asarray(snapshot.creator),
        jnp.asarray(snapshot.index),
        jnp.asarray(snapshot.self_parent),
        jnp.asarray(snapshot.other_parent),
        jnp.asarray(snapshot.last_ancestors),
        jnp.asarray(snapshot.first_descendants),
        jnp.asarray(snapshot.middle_bit),
        sm,
        round_bound,
    )
    host = np.asarray(packed)  # one transfer for all per-event outputs
    out = {
        "rounds": host[0],
        "witness": host[1].astype(bool),
        "lamport": host[2],
        "fame": host[3],
        "round_received": host[4],
    }
    if return_matrices:
        out["see"] = np.asarray(see)
        out["strongly_see"] = np.asarray(ss)
    return out


# =============================================================================
# Synthetic DAG windows (benchmarks, multi-chip dry runs)
# =============================================================================


def synthetic_snapshot(n_peers: int, n_events: int, seed: int = 7) -> DagSnapshot:
    """Build a deterministic gossip-shaped DagSnapshot without any crypto.

    Simulates round-robin-with-jitter gossip: after one root per peer, each
    new event's creator self-parents on its head and other-parents on
    another peer's head. Coordinates (last_ancestors/first_descendants) are
    derived from the exact ancestry closure, so the window is a valid DAG
    in the same dense form snapshot_from_hashgraph produces.
    """
    assert n_events >= n_peers
    rng = np.random.RandomState(seed)

    creator = np.full(n_events, -1, np.int32)
    index = np.full(n_events, -1, np.int32)
    sp = np.full(n_events, -1, np.int32)
    op = np.full(n_events, -1, np.int32)

    heads = [-1] * n_peers
    per_creator_seq = [0] * n_peers
    # ancestry[i, j] = event j is an ancestor of event i (incl. self)
    anc = np.zeros((n_events, n_events), bool)

    for i in range(n_events):
        if i < n_peers:
            c = i  # roots, one per peer
        else:
            c = int(rng.randint(n_peers))
        creator[i] = c
        index[i] = per_creator_seq[c]
        per_creator_seq[c] += 1
        anc[i, i] = True
        if heads[c] >= 0:
            sp[i] = heads[c]
            anc[i] |= anc[heads[c]]
        if i >= n_peers:
            others = [p for p in range(n_peers) if p != c and heads[p] >= 0]
            if others:
                o = int(rng.choice(others))
                op[i] = heads[o]
                anc[i] |= anc[heads[o]]
        heads[c] = i

    la = np.full((n_events, n_peers), -1, np.int32)
    fd = np.full((n_events, n_peers), INT32_MAX, np.int32)
    for i in range(n_events):
        for p in range(n_peers):
            rows = np.where(anc[i] & (creator == p))[0]
            if rows.size:
                la[i, p] = index[rows].max()
        # first descendant of i per peer: min index among events that have
        # i as an ancestor
        desc = np.where(anc[:, i])[0]
        for p in range(n_peers):
            rows = desc[creator[desc] == p]
            if rows.size:
                fd[i, p] = index[rows].min()

    # deterministic pseudo-random coin bits
    mid = ((np.arange(n_events, dtype=np.uint64) * 2654435761) >> 16) & 1 == 1

    sm_threshold = 2 * n_peers // 3 + 1
    return DagSnapshot(
        creator=creator,
        index=index,
        self_parent=sp,
        other_parent=op,
        last_ancestors=la,
        first_descendants=fd,
        middle_bit=mid,
        n_peers=n_peers,
        hashes=[f"synthetic-{i}" for i in range(n_events)],
        super_majority=sm_threshold,
    )
