"""TPU compute kernels (JAX/XLA) for the consensus hot loops.

- ``babble_tpu.ops.dag`` — tensorized DAG pipeline: stronglySee, round
  assignment, virtual voting, round-received. Replaces the per-event
  recursive predicates of the CPU oracle (reference hot loops:
  src/hashgraph/hashgraph.go:172-206, 807-998, 1002-1095).
- ``babble_tpu.ops.verify`` — batched secp256k1 signature verification
  (replaces per-event Verify, reference: src/hashgraph/event.go:219-247).
"""
