"""Incremental, device-resident voting windows (ISSUE 2).

``ops.voting.build_voting_window`` rebuilds the dense window snapshot from
scratch on every flush — one store fetch per row, fresh numpy allocation,
and a full host→device upload — even though consecutive sweeps share
almost all rows. :class:`WindowState` replaces that with the persistent-
device-state discipline a training/inference stack applies to KV caches:

- **Host mirrors** of the per-row window arrays live across sweeps, with a
  row-recycling free-list. Each snapshot is updated in O(ΔE): new
  undetermined events and newly-minted witnesses append rows (fed by the
  hashgraph's delta channels — see ``Hashgraph.drain_accel_delta``),
  events received by a sweep release their rows, and witness rows are
  repacked only when their ``first_descendants`` actually changed (the one
  per-row field the insert path mutates after the fact) or their fame was
  applied.
- **Device residency**: the 11 per-row arrays stay on the device between
  sweeps. The compiled resident program takes the previous buffers plus a
  compact, bucket-padded delta (row indexes + replacement rows; padding
  indexes point past the array so the scatter drops them) and applies it
  in place via ``jax.jit(donate_argnums=...)`` — host→device traffic
  scales with the delta, not the padded window.
- **Rebuild fallback**: any situation the delta protocol cannot express
  falls back to a from-scratch ``build_voting_window`` rebuild (with
  headroom added to the shape buckets so steady-state growth doesn't
  immediately rebuild again). Triggers: repertoire change, R/S/E/W bucket
  overflow, a round evicted from the store, a laggard event assigned a
  round below the frozen window floor, or any oracle pass having mutated
  consensus state behind the window's back (``mark_dirty``). The rebuild
  IS the correctness oracle: tests/test_incremental_window.py asserts the
  incremental mirrors equal a fresh rebuild after every mutation step.

Ownership rules for the donated buffers (see docs/tpu.md "Resident window
state"): ``WindowState.device`` holds the ONLY live reference to the
resident buffers. ``dispatch`` consumes them (donation invalidates the
inputs) and immediately replaces them with the program's outputs; any
failure drops residency and marks the state dirty, so a stale handle can
never be redispatched. Results are applied only while
``Snapshot.generation == WindowState.generation`` — a readback that lands
after a later mutation is discarded, never applied through moved row maps.

The window floor (``base``) is FROZEN between rebuilds: rows of rounds
that decide under a frozen floor stay in the window as settled voters —
harmless by exactly the repad argument (settled fame is never refilled,
determined events have ``undet`` False) — until the R bucket overflows and
a rebuild re-bases. This keeps per-row rounds immutable, which is what
makes the delta protocol O(ΔE).
"""

from __future__ import annotations

import threading
import time
import warnings
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from babble_tpu.common.errors import StoreError
from babble_tpu.ops import voting
from babble_tpu.ops.voting import (
    INT32_MAX,
    VotingWindow,
    _bucket_mult,
    _bucket_pow2,
    _fame_init,
)

# CPU XLA ignores buffer donation (it still runs correctly, copy-on-write);
# the per-compile warning would otherwise spam every CPU-fallback node.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


class StaleWindowError(RuntimeError):
    """A window snapshot's WindowState mutated before its results could be
    used; the owner must discard them (and ride the oracle fallback)."""


# The per-row ("resident") window fields, in VotingWindow attribute order.
RESIDENT_FIELDS = (
    "creator", "index", "rounds", "undet", "wit_idx",
    "la_w", "fd_w", "rounds_w", "valid_w", "fame0_w", "mid_w",
)
# The per-sweep ("fresh") fields — tiny [R]/[S,P] arrays recomputed from
# the store every snapshot and uploaded whole (peer-set membership masks
# are cached by peer-set hash, so mask construction only happens when
# membership actually changes).
FRESH_FIELDS = (
    "member", "sm_s", "psi", "sm_r", "exists_r", "prior_dec_r", "lb_gate_r",
)


def delta_shape(key: tuple) -> Tuple[int, int]:
    """(DE, DW) delta-row buckets for a window bucket — fixed per bucket so
    each bucket compiles exactly ONE resident program. Sized for a gossip
    round's worth of churn; bigger deltas take the full-refresh path."""
    W, E, _P, _S, _R = key
    return max(32, E // 8), max(8, W // 8)


def _resident_core(creator, index, rounds, undet, wit_idx, la_w, fd_w,
                   rounds_w, valid_w, fame0_w, mid_w,
                   e_idx, e_creator, e_index, e_rounds, e_undet,
                   w_idx, w_wit_idx, w_la, w_fd, w_rounds, w_valid,
                   w_fame0, w_mid,
                   member, sm_s, psi, sm_r, exists_r, prior_dec_r, lb_gate_r):
    """Scatter the delta rows into the resident buffers, then run the same
    fused sweep as ops.voting._sweep_core. Padding delta rows carry an
    out-of-bounds index (E / W), which mode="drop" discards — so one
    compiled program serves every delta size up to the bucket. Returns
    (new resident buffers, [fame | rr])."""
    creator = creator.at[e_idx].set(e_creator, mode="drop")
    index = index.at[e_idx].set(e_index, mode="drop")
    rounds = rounds.at[e_idx].set(e_rounds, mode="drop")
    undet = undet.at[e_idx].set(e_undet, mode="drop")
    wit_idx = wit_idx.at[w_idx].set(w_wit_idx, mode="drop")
    la_w = la_w.at[w_idx].set(w_la, mode="drop")
    fd_w = fd_w.at[w_idx].set(w_fd, mode="drop")
    rounds_w = rounds_w.at[w_idx].set(w_rounds, mode="drop")
    valid_w = valid_w.at[w_idx].set(w_valid, mode="drop")
    fame0_w = fame0_w.at[w_idx].set(w_fame0, mode="drop")
    mid_w = mid_w.at[w_idx].set(w_mid, mode="drop")
    out = voting._sweep_core(
        creator, index, la_w, fd_w, rounds_w, valid_w, fame0_w, mid_w,
        wit_idx, member, sm_s, psi, sm_r, rounds, undet,
        exists_r, prior_dec_r, lb_gate_r,
    )
    return (
        (creator, index, rounds, undet, wit_idx, la_w, fd_w, rounds_w,
         valid_w, fame0_w, mid_w),
        out,
    )


# Donating the 11 resident buffers lets XLA update them in place: the
# host→device transfer per sweep is the delta pack plus the tiny [R]/[S,P]
# fresh arrays, never the padded window.
_resident_jit = jax.jit(_resident_core, donate_argnums=tuple(range(11)))

# Compiled-bucket registry for the resident program, mirroring ops.voting's
# (separate executables, so separate readiness).
_ready_resident: set = set()


def resident_ready(key: tuple) -> bool:
    with voting._bucket_lock():
        return key in _ready_resident


def mark_resident_ready(key: tuple) -> None:
    with voting._bucket_lock():
        _ready_resident.add(key)


def _empty_delta(key: tuple) -> tuple:
    """An all-padding delta pack (every index out of bounds → dropped)."""
    W, E, P, _S, _R = key
    DE, DW = delta_shape(key)
    return (
        np.full(DE, E, np.int32),          # e_idx (OOB → dropped)
        np.zeros(DE, np.int32),            # e_creator
        np.full(DE, -1, np.int32),         # e_index
        np.full(DE, -10, np.int32),        # e_rounds
        np.zeros(DE, bool),                # e_undet
        np.full(DW, W, np.int32),          # w_idx (OOB → dropped)
        np.zeros(DW, np.int32),            # w_wit_idx
        np.full((DW, P), -1, np.int32),    # w_la
        np.full((DW, P), INT32_MAX, np.int32),  # w_fd
        np.full(DW, -10, np.int32),        # w_rounds
        np.zeros(DW, bool),                # w_valid
        np.zeros(DW, np.int32),            # w_fame0
        np.zeros(DW, bool),                # w_mid
    )


def precompile_resident(W: int, E: int, P: int, S: int, R: int) -> None:
    """Compile (or load from the persistent cache) the resident delta
    program for a bucket on an all-invalid dummy window + empty delta."""
    key = (W, E, P, S, R)
    win = voting.dummy_window(*key)
    bufs = tuple(jnp.asarray(getattr(win, f)) for f in RESIDENT_FIELDS)
    fresh = tuple(jnp.asarray(getattr(win, f)) for f in FRESH_FIELDS)
    new_bufs, out = _resident_jit(*bufs, *_empty_delta(key), *fresh)
    np.asarray(out)  # block until the executable is really ready
    mark_resident_ready(key)


@dataclass
class Snapshot:
    """One sweep's immutable view of the WindowState: the mirror-backed
    VotingWindow, the state generation it was taken at, and the packed
    delta (None ⇒ the dispatch must do a full upload / residency reseed)."""

    win: VotingWindow
    generation: int
    delta: Optional[tuple]
    rebuilt: bool
    rows_delta: int
    rows_reused: int


class _Rebuild(Exception):
    """Internal: the delta protocol cannot express this mutation."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class _NotReady(Exception):
    """Internal: an undetermined event has no round yet (divide_rounds has
    not run) — same condition build_voting_window returns None for."""


class WindowState:
    """Persistent incremental window for ONE hashgraph (owned by its
    TensorConsensus). All methods run on the consensus thread."""

    def __init__(self, mesh=None) -> None:
        # Optional jax.sharding.Mesh: residency lives as per-shard device
        # buffers (parallel/voting_shard.py shardings) and dispatch runs
        # the sharded resident program; None keeps the single-device
        # program. The W bucket is aligned to the mesh size at rebuild so
        # the witness axis always divides the shard count.
        self.mesh = mesh
        self.generation = 0  # bumped on every mirror mutation or rebuild
        self.dirty = True  # force a rebuild on the next snapshot
        self.dirty_reason = "initial"
        self.rebuilds = 0
        self.mirror: Optional[Dict[str, np.ndarray]] = None
        self.row: Dict[str, int] = {}
        self.wit_row: Dict[str, int] = {}
        self.undet_set: Set[str] = set()
        self.free_e: List[int] = []
        self.free_w: List[int] = []
        self.base = 0
        self.key: Optional[tuple] = None  # (W, E, P, S, R)
        self.pub_keys: tuple = ()
        self.peer_col: Dict[str, int] = {}
        self.exists_prev: Optional[np.ndarray] = None
        # The ONLY live reference to the resident device buffers (donation
        # ownership rule: dispatch consumes and replaces it atomically).
        self.device: Optional[tuple] = None
        # membership-mask cache keyed by peer-set hash: masks are rebuilt
        # only when membership actually changes
        self._mask_cache: Dict[bytes, Tuple[np.ndarray, int]] = {}
        # feedback from the owning TensorConsensus's apply step
        self._pending_fame: List[Tuple[str, int]] = []
        self._pending_received: List[str] = []

    # -- lifecycle -----------------------------------------------------------

    def mark_dirty(self, reason: str = "oracle") -> None:
        """Anything mutated consensus state behind the window's back (an
        oracle pass, a reset, a failed sweep): drop residency and rebuild
        at the next snapshot. Bumping the generation here is what makes
        in-flight sweeps from the old state detectably stale."""
        self.dirty = True
        self.dirty_reason = reason
        self.device = None
        self.generation += 1
        self._pending_fame = []
        self._pending_received = []

    def drop_residency(self) -> None:
        """A snapshot's delta was committed to the mirrors but no dispatch
        carried it to the device (compile wait, admission loss, batcher
        backlog): the resident buffers now trail the mirrors. Keep the
        mirrors — the delta protocol is still exact — but force the next
        dispatched sweep to reseed residency with a full upload."""
        self.device = None

    def note_applied(self, fame_pairs: List[Tuple[str, int]],
                     received: List[str]) -> None:
        """Record what apply_fame/apply_round_received just wrote to the
        store, so the next delta scan updates the mirrors to match."""
        self._pending_fame.extend(fame_pairs)
        self._pending_received.extend(received)

    # -- snapshot ------------------------------------------------------------

    def snapshot(self, hg, timers: Dict[str, float],
                 copy_rows: bool = False) -> Optional[Snapshot]:
        """Bring the mirrors up to date with the hashgraph (O(ΔE) delta, or
        a from-scratch rebuild when a trigger fires) and return this
        sweep's Snapshot. None ⇒ nothing to decide. Raises StoreError on
        eviction mid-scan (the caller falls back to the oracle; the state
        is marked dirty so the next snapshot rebuilds)."""
        try:
            if self.dirty or self.mirror is None:
                return self._rebuild(hg, timers, copy_rows,
                                     self.dirty_reason)
            try:
                return self._delta_snapshot(hg, timers, copy_rows)
            except _Rebuild as why:
                return self._rebuild(hg, timers, copy_rows, why.reason)
            except _NotReady:
                # an undetermined event has no round yet (divide_rounds
                # mid-retry) — no sweep this flush. The scan may already
                # have consumed channels/feedback and touched bookkeeping,
                # so resync via a rebuild next time.
                self.mark_dirty("round-pending")
                return None
        except (_Rebuild, _NotReady):
            raise AssertionError("unreachable")  # pragma: no cover
        except BaseException:
            # A half-applied delta scan (store eviction mid-fetch) leaves
            # the mirrors inconsistent: discard them.
            self.mark_dirty("snapshot-error")
            raise

    def _rebuild(self, hg, timers, copy_rows: bool,
                 reason: str) -> Optional[Snapshot]:
        t0 = time.perf_counter()
        # stale channels/feedback describe the pre-rebuild world
        hg.drain_accel_delta()
        self._pending_fame = []
        self._pending_received = []
        win = voting.build_voting_window(hg)
        if win is None:
            # nothing to decide; stay dirty so the next snapshot rebuilds
            self.mark_dirty("empty")
            timers["build"] = timers.get("build", 0.0) + (
                time.perf_counter() - t0
            )
            return None
        # Headroom: grow an axis past the builder's bucket ONLY when the
        # real count is already within ``slack`` of the boundary (a
        # rebuild would otherwise fire again within a sweep or two).
        # Everywhere else the state keeps the builder's exact buckets —
        # that keeps rebuilt keys on the shapes prewarm_buckets compiled,
        # so a freshly (re)built state meets warm programs instead of
        # kicking compiles, and it keeps the kernel small (every bucket
        # step inflates W quadratically; a premature rebuild only costs
        # one more host build). R's slack covers the frozen floor: the
        # round span grows by one per new round until a rebuild re-bases.
        W0, E0, P0, S0, R0 = voting.bucket_key(win)

        def head(n_real: int, bucket: int, minimum: int, slack: int) -> int:
            if n_real + slack <= bucket:
                return bucket
            return _bucket_pow2(n_real + slack, minimum)

        E_real = len(win.hashes)
        W_real = len(win.wit_hashes)
        R_real = hg.store.last_round() - win.base + 2
        key = (
            head(W_real, W0, 16, max(2, W_real // 16)),
            head(E_real, E0, 32, max(8, E_real // 16)),
            P0,
            S0,
            head(R_real, R0, 8, 2),
        )
        if self.mesh is not None:
            # the sharded sweep splits the witness axis over every device:
            # align the W bucket so it always divides the mesh size (both
            # are powers of two in practice; a mesh with an odd factor can
            # never divide a doubled power-of-two bucket, so cap the climb
            # at one doubling past W*n and leave the bucket unaligned —
            # the dispatch layer falls back to the single program)
            n = int(self.mesh.devices.size)
            W_m = key[0]
            while W_m % n and W_m <= key[0] * n:
                W_m *= 2
            if W_m % n == 0:
                key = (W_m,) + key[1:]
        win = voting.repad_window(win, key)
        self.mirror = {f: np.asarray(getattr(win, f)) for f in RESIDENT_FIELDS}
        self.row = dict(win.row)
        self.wit_row = dict(win.wit_row)
        self.undet_set = set(hg.undetermined_events)
        W, E = key[0], key[1]
        self.free_e = list(range(E - 1, E_real - 1, -1))
        self.free_w = list(range(W - 1, W_real - 1, -1))
        self.base = win.base
        self.key = key
        rep = hg.store.repertoire_by_pub_key()
        self.pub_keys = tuple(sorted(rep.keys()))
        self.peer_col = {pk: i for i, pk in enumerate(self.pub_keys)}
        self.exists_prev = np.asarray(win.exists_r)
        self.device = None  # reseeded by the next full dispatch
        self._mask_cache.clear()
        self.generation += 1
        self.rebuilds += 1
        self.dirty = False
        timers["build"] = timers.get("build", 0.0) + (time.perf_counter() - t0)
        rows = len(self.row) + len(self.wit_row)
        fresh = {f: np.asarray(getattr(win, f)) for f in FRESH_FIELDS}
        return Snapshot(
            win=self._window(fresh, copy_rows),
            generation=self.generation,
            delta=None,
            rebuilt=True,
            rows_delta=rows,
            rows_reused=0,
        )

    def _delta_snapshot(self, hg, timers, copy_rows: bool) -> Optional[Snapshot]:
        t0 = time.perf_counter()
        store = hg.store
        m = self.mirror
        W, E, P, S, R = self.key

        rep = store.repertoire_by_pub_key()
        if len(rep) != len(self.pub_keys) or tuple(sorted(rep)) != self.pub_keys:
            raise _Rebuild("repertoire-change")
        last_round = store.last_round()
        if last_round - self.base + 2 > R:
            raise _Rebuild("round-bucket-overflow")

        new_wits, fd_dirty = hg.drain_accel_delta()
        fame_pairs, self._pending_fame = self._pending_fame, []
        received, self._pending_received = self._pending_received, []

        # New undetermined events are a strict suffix of the list: inserts
        # append, and the only removals since the last snapshot were our
        # own apply (recorded in ``received``) — any other mutation path
        # marks the state dirty and never reaches this scan.
        undet = hg.undetermined_events
        new_undet: List[str] = []
        for h in reversed(undet):
            if h in self.undet_set:
                break
            new_undet.append(h)
        new_undet.reverse()

        e_upd: Dict[int, tuple] = {}  # row -> (creator, index, round, undet)
        w_upd: Dict[int, dict] = {}  # w-row -> field dict

        # 1. events our apply received: witnesses keep their row with the
        #    undet flag cleared; plain events release their row.
        for h in received:
            i = self.row.get(h)
            if i is None:
                continue
            self.undet_set.discard(h)
            if h in self.wit_row:
                e_upd[i] = (
                    int(m["creator"][i]), int(m["index"][i]),
                    int(m["rounds"][i]), False,
                )
            else:
                e_upd[i] = (0, -1, -10, False)
                del self.row[h]
                self.free_e.append(i)

        # 2. fresh undetermined events append rows.
        ev_cache: Dict[str, object] = {}
        for h in new_undet:
            ev = store.get_event(h)
            ev_cache[h] = ev
            if ev.round is None:
                raise _NotReady()
            if ev.round < self.base:
                raise _Rebuild("round-below-floor")
            i = self.row.get(h)
            if i is None:
                if not self.free_e:
                    raise _Rebuild("event-bucket-overflow")
                i = self.free_e.pop()
                self.row[h] = i
            self.undet_set.add(h)
            e_upd[i] = (
                self.peer_col[ev.creator()], ev.index(),
                ev.round - self.base, True,
            )

        # 3. newly-minted witnesses gain W rows (packed from the store).
        for r, h in new_wits:
            if h in self.wit_row:
                continue
            if r < self.base:
                raise _Rebuild("witness-below-floor")
            ev = ev_cache.get(h)
            if ev is None:
                ev = store.get_event(h)
                ev_cache[h] = ev
            i = self.row.get(h)
            if i is None:
                if not self.free_e:
                    raise _Rebuild("event-bucket-overflow")
                i = self.free_e.pop()
                self.row[h] = i
                e_upd[i] = (
                    self.peer_col[ev.creator()], ev.index(),
                    r - self.base, h in self.undet_set,
                )
            if not self.free_w:
                raise _Rebuild("witness-bucket-overflow")
            w = self.free_w.pop()
            self.wit_row[h] = w
            w_upd[w] = self._pack_witness(ev, i, r - self.base, fame0=0)

        # 4. witnesses whose first_descendants mutated since the last
        #    snapshot (the one post-insert per-row mutation) repack.
        for h in fd_dirty:
            w = self.wit_row.get(h)
            if w is None or w in w_upd:
                continue
            ev = ev_cache.get(h)
            if ev is None:
                ev = store.get_event(h)
            w_upd[w] = self._pack_witness(
                ev, int(m["wit_idx"][w]), int(m["rounds_w"][w]),
                fame0=int(m["fame0_w"][w]),
            )

        # 5. fame our apply wrote settles witness rows in place.
        for h, f in fame_pairs:
            w = self.wit_row.get(h)
            if w is None:
                continue
            if w in w_upd:
                w_upd[w]["fame0_w"] = f
            else:
                w_upd[w] = {
                    "wit_idx": int(m["wit_idx"][w]),
                    "la_w": np.array(m["la_w"][w]),
                    "fd_w": np.array(m["fd_w"][w]),
                    "rounds_w": int(m["rounds_w"][w]),
                    "valid_w": bool(m["valid_w"][w]),
                    "fame0_w": f,
                    "mid_w": bool(m["mid_w"][w]),
                }

        if len(self.undet_set) != len(undet):
            raise _Rebuild("undetermined-bookkeeping-divergence")

        # apply to the mirrors
        for i, (c, idx, rr_, ud) in e_upd.items():
            m["creator"][i] = c
            m["index"][i] = idx
            m["rounds"][i] = rr_
            m["undet"][i] = ud
        for w, row in w_upd.items():
            m["wit_idx"][w] = row["wit_idx"]
            m["la_w"][w] = row["la_w"]
            m["fd_w"][w] = row["fd_w"]
            m["rounds_w"][w] = row["rounds_w"]
            m["valid_w"][w] = row["valid_w"]
            m["fame0_w"][w] = row["fame0_w"]
            m["mid_w"][w] = row["mid_w"]
        if e_upd or w_upd:
            self.generation += 1
        timers["delta_scan"] = timers.get("delta_scan", 0.0) + (
            time.perf_counter() - t0
        )

        if not self.undet_set and not (
            hg.pending_rounds.get_ordered_pending_rounds()
        ):
            # Nothing left to decide, so no dispatch will carry this delta
            # to the device: the resident buffers now trail the mirrors.
            # Drop residency — the next dispatched sweep full-uploads.
            if e_upd or w_upd:
                self.device = None
            return None

        t1 = time.perf_counter()
        fresh = self._round_block(hg)  # may raise _Rebuild (eviction, S)
        DE, DW = delta_shape(self.key)
        delta = None
        if (
            not copy_rows  # batcher snapshots never dispatch a delta
            and len(e_upd) <= DE
            and len(w_upd) <= DW
        ):
            delta = self._pack_delta(e_upd, w_upd, DE, DW)
        win = self._window(fresh, copy_rows)
        timers["pack"] = timers.get("pack", 0.0) + (time.perf_counter() - t1)
        rows_delta = len(e_upd) + len(w_upd)
        return Snapshot(
            win=win,
            generation=self.generation,
            delta=delta,
            rebuilt=False,
            rows_delta=rows_delta,
            rows_reused=max(
                0, len(self.row) + len(self.wit_row) - rows_delta
            ),
        )

    def _pack_witness(self, ev, e_row: int, round_rebased: int,
                      fame0: int) -> dict:
        from babble_tpu.hashgraph.hashgraph import middle_bit

        P = self.key[2]
        la = np.full(P, -1, np.int32)
        fd = np.full(P, INT32_MAX, np.int32)
        for pk, coords in ev.last_ancestors.items():
            c = self.peer_col.get(pk)
            if c is not None:
                la[c] = coords.index
        for pk, coords in ev.first_descendants.items():
            c = self.peer_col.get(pk)
            if c is not None:
                fd[c] = coords.index
        return {
            "wit_idx": e_row,
            "la_w": la,
            "fd_w": fd,
            "rounds_w": round_rebased,
            "valid_w": True,
            "fame0_w": fame0,
            "mid_w": middle_bit(ev.hex()),
        }

    def _pack_delta(self, e_upd: Dict[int, tuple], w_upd: Dict[int, dict],
                    DE: int, DW: int) -> tuple:
        W, E, P, _S, _R = self.key
        e_idx = np.full(DE, E, np.int32)
        e_creator = np.zeros(DE, np.int32)
        e_index = np.full(DE, -1, np.int32)
        e_rounds = np.full(DE, -10, np.int32)
        e_undet = np.zeros(DE, bool)
        for k, (i, (c, idx, rr_, ud)) in enumerate(e_upd.items()):
            e_idx[k] = i
            e_creator[k] = c
            e_index[k] = idx
            e_rounds[k] = rr_
            e_undet[k] = ud
        w_idx = np.full(DW, W, np.int32)
        w_wit_idx = np.zeros(DW, np.int32)
        w_la = np.full((DW, P), -1, np.int32)
        w_fd = np.full((DW, P), INT32_MAX, np.int32)
        w_rounds = np.full(DW, -10, np.int32)
        w_valid = np.zeros(DW, bool)
        w_fame0 = np.zeros(DW, np.int32)
        w_mid = np.zeros(DW, bool)
        for k, (w, row) in enumerate(w_upd.items()):
            w_idx[k] = w
            w_wit_idx[k] = row["wit_idx"]
            w_la[k] = row["la_w"]
            w_fd[k] = row["fd_w"]
            w_rounds[k] = row["rounds_w"]
            w_valid[k] = row["valid_w"]
            w_fame0[k] = row["fame0_w"]
            w_mid[k] = row["mid_w"]
        return (e_idx, e_creator, e_index, e_rounds, e_undet,
                w_idx, w_wit_idx, w_la, w_fd, w_rounds, w_valid,
                w_fame0, w_mid)

    # -- per-sweep round/peer-set block --------------------------------------

    def _round_block(self, hg) -> dict:
        """The [R]/[S,P] fresh arrays, recomputed from the store each sweep
        (they're tiny and prior_dec_r/exists_r genuinely change per sweep).
        Raises _Rebuild when a previously-readable round was evicted or the
        distinct peer-set count outgrows the S bucket."""
        store = hg.store
        W, E, P, S, R = self.key
        slot_of: Dict[bytes, int] = {}
        members: List[np.ndarray] = []
        sms: List[int] = []
        psi = np.zeros(R, np.int32)
        sm_r = np.full(R, 2**30, np.int32)
        exists_r = np.zeros(R, bool)
        prior_dec_r = np.zeros(R, bool)
        lb_gate_r = np.zeros(R, bool)
        lb = hg.round_lower_bound
        for r in range(R):
            a = self.base + r
            lb_gate_r[r] = lb is None or lb < a
            try:
                ri = store.get_round(a)
            except StoreError:
                if self.exists_prev is not None and self.exists_prev[r]:
                    raise _Rebuild("round-evicted")
            else:
                exists_r[r] = True
                prior_dec_r[r] = ri.decided
            ps = store.get_peer_set(a)
            key = ps.hash()
            s = slot_of.get(key)
            if s is None:
                s = len(members)
                if s >= S:
                    raise _Rebuild("peer-set-slot-overflow")
                slot_of[key] = s
                cached = self._mask_cache.get(key)
                if cached is None:
                    mask = np.zeros(P, bool)
                    for pk in ps.pub_keys():
                        c = self.peer_col.get(pk)
                        if c is not None:
                            mask[c] = True
                    cached = (mask, ps.super_majority())
                    self._mask_cache[key] = cached
                members.append(cached[0])
                sms.append(cached[1])
            psi[r] = s
            sm_r[r] = sms[s]
        member = np.zeros((S, P), bool)
        sm_s = np.full(S, 2**30, np.int32)
        for s, mk in enumerate(members):
            member[s] = mk
            sm_s[s] = sms[s]
        self.exists_prev = exists_r
        return {
            "member": member, "sm_s": sm_s, "psi": psi, "sm_r": sm_r,
            "exists_r": exists_r, "prior_dec_r": prior_dec_r,
            "lb_gate_r": lb_gate_r,
        }

    def _window(self, fresh: dict, copy_rows: bool) -> VotingWindow:
        """A VotingWindow over the mirrors plus this sweep's fresh [R]/[S,P]
        arrays. ``copy_rows`` copies the per-row arrays (batcher
        submissions outlive the snapshot and must not see later in-place
        delta mutations); otherwise the arrays are shared and consumers
        rely on the generation check."""
        m = self.mirror
        rows = {
            f: (np.array(m[f]) if copy_rows else m[f])
            for f in RESIDENT_FIELDS
        }
        return VotingWindow(
            **rows,
            **fresh,
            base=self.base,
            hashes=list(self.row),
            row=self.row if not copy_rows else dict(self.row),
            wit_hashes=list(self.wit_row),
            wit_row=self.wit_row if not copy_rows else dict(self.wit_row),
            generation=self.generation,
            state=self,
        )

    # -- dispatch ------------------------------------------------------------

    def dispatch(self, snap: Snapshot, allow_inline_compile: bool = True):
        """Launch the sweep for a snapshot, keeping the window device-
        resident. Delta path: donate the previous buffers + scatter the
        delta (transfer scales with ΔE). Full path (no delta / no
        residency / resident program not warm): upload the mirrors once
        through the plain fused program and keep the uploaded buffers as
        the new residency seed. Returns the unread [fame | rr] device
        buffer. Returns (out, used_delta)."""
        if self.mesh is not None:
            return self._dispatch_mesh(snap, allow_inline_compile)
        key = self.key
        win = snap.win
        if (
            snap.delta is not None
            and self.device is not None
            and (allow_inline_compile or resident_ready(key))
        ):
            bufs, self.device = self.device, None  # consume: donation
            fresh = tuple(jnp.asarray(getattr(win, f)) for f in FRESH_FIELDS)
            try:
                new_bufs, out = _resident_jit(*bufs, *snap.delta, *fresh)
            except BaseException:
                self.mark_dirty("dispatch-error")
                raise
            mark_resident_ready(key)
            self.device = tuple(new_bufs)
            return out, True
        # full upload; the uploaded buffers seed residency for next sweep
        bufs = tuple(jnp.asarray(getattr(win, f)) for f in RESIDENT_FIELDS)
        named = dict(zip(RESIDENT_FIELDS, bufs))
        args = [
            named[f] if f in named else jnp.asarray(getattr(win, f))
            for f in voting._WIN_FIELDS
        ]
        try:
            out = voting._sweep_jit(*args)
        except BaseException:
            self.mark_dirty("dispatch-error")
            raise
        self.device = bufs
        return out, False

    # index of each RESIDENT_FIELD inside voting._WIN_FIELDS order — the
    # mesh full-upload path keeps those placed operands as the residency
    # seed (creator, index, rounds, undet, wit_idx, la_w, fd_w, rounds_w,
    # valid_w, fame0_w, mid_w)
    _PLACED_RESIDENT_IDX = (0, 1, 13, 14, 8, 2, 3, 4, 5, 6, 7)

    def _dispatch_mesh(self, snap: Snapshot, allow_inline_compile: bool):
        """Mesh variant of dispatch: residency is a tuple of per-shard
        device buffers (voting_shard.resident_shardings), the delta path
        donates them to the sharded resident program, and the full path
        seeds them by placing the mirrors with the sweep's shardings.
        Same ownership rules as the single-device path."""
        from babble_tpu.parallel import voting_shard as vshard

        mesh = self.mesh
        key = self.key
        win = snap.win
        if (
            snap.delta is not None
            and self.device is not None
            and (allow_inline_compile
                 or vshard.resident_bucket_ready(mesh, key))
        ):
            bufs, self.device = self.device, None  # consume: donation
            fresh = tuple(np.asarray(getattr(win, f)) for f in FRESH_FIELDS)
            try:
                new_bufs, out = vshard.resident_jitted(mesh)(
                    *bufs, *snap.delta, *fresh
                )
            except BaseException:
                self.mark_dirty("dispatch-error")
                raise
            vshard.mark_resident_bucket_ready(mesh, key)
            self.device = tuple(new_bufs)
            return out, True
        # full upload through the plain sharded sweep; the placed per-row
        # operands seed residency for the next delta sweep
        placed = vshard.place_window(mesh, win)
        try:
            out = vshard._jitted(mesh)(*placed)
        except BaseException:
            self.mark_dirty("dispatch-error")
            raise
        self.device = tuple(placed[i] for i in self._PLACED_RESIDENT_IDX)
        return out, False
