"""Pallas TPU kernels for the consensus hot ops.

``strongly_see_pallas`` computes SS[x, y] = #{p : la[x,p] >= fd[y,p]} >= sm
(oracle: hashgraph.go:172-206) WITHOUT materializing the [E, E, P] compare
tensor the jnp formulation builds (ops/dag.py notes it as the big-window
memory problem: E=4096, P=40 -> 2.7 GB of int8 intermediates for XLA to
fuse away — or not). The kernel tiles the x axis over a grid; each program
holds one [P, TILE_X] slice of the (transposed) last-ancestor coordinates
plus the full [P, E] first-descendant matrix in VMEM and accumulates the
peer axis with a static loop, so peak memory is O(TILE_X * E).

Layout notes (guide: pallas_guide.md "Tiling Constraints"): operands are
passed TRANSPOSED ([P, E] instead of [E, P]) so the fast last dimension is
the big event axis (a multiple of 128 for every bucketed window) and the
sublane dimension is the peer axis (already padded to a multiple of 8).

Used by ops.dag.strongly_see_matrix when BABBLE_PALLAS=1 on a real TPU;
always differentially tested in interpreter mode on CPU
(tests/test_ops_dag.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# the missing-coordinate sentinel — single source of truth in ops.dag
# (cycle-free: dag imports this module only lazily inside
# strongly_see_matrix)
from babble_tpu.ops.dag import INT32_MAX

TILE_X = 128


def _ss_kernel(n_peers: int, super_majority: int, la_t_ref, fd_t_ref,
               out_ref):
    """One [TILE_X, E] output tile: count peers p with la[x,p] >= fd[y,p].
    The peer loop is a static unroll (P <= a few dozen); every iteration
    is one [TILE_X, E] broadcast compare+add on the VPU."""
    acc = jnp.zeros(out_ref.shape, jnp.int32)
    for p in range(n_peers):
        la_row = la_t_ref[p, :]  # [TILE_X] this block's x coordinates
        fd_row = fd_t_ref[p, :]  # [E] all candidates' y coordinates
        acc += (la_row[:, None] >= fd_row[None, :]).astype(jnp.int32)
    out_ref[:] = acc >= super_majority


TILE_V = 128


def _member_ss_kernel(n_peers: int, la_t_ref, fdm_ref, out_ref):
    """One [1, TILE_V, W] tile of the per-slot strongly-see counts:
    counts[s, v, w] = #{p : la[v,p] >= fd_masked[s,w,p]}. The peer-set
    membership is pre-folded into ``fd_masked`` (non-members carry the
    INT32_MAX sentinel, so their compare can never pass) — that keeps the
    kernel free of data-dependent scalar loads; the peer axis is a static
    unroll of [TILE_V, W] VPU compare+adds, as in _ss_kernel."""
    acc = jnp.zeros(out_ref.shape[1:], jnp.int32)
    for p in range(n_peers):
        la_row = la_t_ref[p, :]  # [TILE_V] this block's voter coordinates
        fd_row = fdm_ref[0, p, :]  # [W] this slot's masked candidates
        acc += (la_row[:, None] >= fd_row[None, :]).astype(jnp.int32)
    out_ref[0, :, :] = acc


@partial(jax.jit, static_argnames=("interpret",))
def member_ss_counts_pallas(la, fd, member, interpret: bool = False):
    """Per-peer-set strongly-see counts for the LIVE voting sweep — the
    Pallas form of ops/voting.py's dominant [W, W, P] membership einsum:

        counts[s, v, w] = sum_p member[s, p] * (la[v, p] >= fd[w, p])

    without materializing the [W, W, P] compare tensor: each grid step
    holds one [P, TILE_V] coordinate slice and one [P, W] masked-candidate
    slab in VMEM. Inputs la/fd are [W, P] (voting window W-space), member
    is [S, P] bool; returns int32 [S, W, W] (the >= super-majority compare
    stays outside — it is a cheap XLA elementwise over a small output).

    The membership mask folds into the operands host-side: a non-member
    peer's first-descendant becomes INT32_MAX, which no last-ancestor can
    reach — bit-identical to multiplying the compare by member[s, p].
    """
    W, P = la.shape
    S = member.shape[0]
    P_pad = -P % 8
    W_pad = -W % TILE_V
    if P_pad:
        la = jnp.pad(la, ((0, 0), (0, P_pad)), constant_values=-1)
        fd = jnp.pad(fd, ((0, 0), (0, P_pad)), constant_values=INT32_MAX)
        member = jnp.pad(member, ((0, 0), (0, P_pad)), constant_values=False)
    if W_pad:
        la = jnp.pad(la, ((0, W_pad), (0, 0)), constant_values=-1)
        fd = jnp.pad(fd, ((0, W_pad), (0, 0)), constant_values=INT32_MAX)
    Wp, Pp = la.shape
    la_t = la.T  # [Pp, Wp]
    # [S, Pp, Wp]: slot-masked candidates, transposed so the fast axis is W
    fd_masked = jnp.where(
        member[:, :, None], fd.T[None, :, :], INT32_MAX
    )
    kernel = partial(_member_ss_kernel, Pp)
    out = pl.pallas_call(
        kernel,
        grid=(S, Wp // TILE_V),
        in_specs=[
            pl.BlockSpec((Pp, TILE_V), lambda s, i: (0, i)),
            pl.BlockSpec((1, Pp, Wp), lambda s, i: (s, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, TILE_V, Wp), lambda s, i: (s, i, 0)),
        out_shape=jax.ShapeDtypeStruct((S, Wp, Wp), jnp.int32),
        interpret=interpret,
    )(la_t, fd_masked)
    return out[:, :W, :W]


@partial(jax.jit, static_argnames=("super_majority", "interpret"))
def strongly_see_pallas(la, fd, super_majority: int, interpret: bool = False):
    """SS[x, y] over [E, P] coordinate tensors, Pallas-tiled.

    Semantics identical to ops.dag.strongly_see_matrix (missing
    coordinates excluded by the -1 / INT32_MAX sentinels). Inputs of ANY
    shape are accepted: the peer axis is padded to a multiple of 8
    (sublane tiling) with sentinel pairs that can never satisfy the
    compare (la=-1 vs fd=INT32_MAX), and the event axis to a multiple of
    TILE_X (lane tiling); the pad rows/columns are sliced off the result.
    """
    E, P = la.shape
    P_pad = -P % 8
    E_pad = -E % TILE_X
    if P_pad:
        la = jnp.pad(la, ((0, 0), (0, P_pad)), constant_values=-1)
        fd = jnp.pad(fd, ((0, 0), (0, P_pad)), constant_values=INT32_MAX)
    if E_pad:
        la = jnp.pad(la, ((0, E_pad), (0, 0)), constant_values=-1)
        fd = jnp.pad(fd, ((0, E_pad), (0, 0)), constant_values=INT32_MAX)
    Ep, Pp = la.shape
    la_t = la.T  # [Pp, Ep]
    fd_t = fd.T
    kernel = partial(_ss_kernel, Pp, super_majority)
    out = pl.pallas_call(
        kernel,
        grid=(Ep // TILE_X,),
        in_specs=[
            pl.BlockSpec((Pp, TILE_X), lambda i: (0, i)),  # block's x rows
            pl.BlockSpec((Pp, Ep), lambda i: (0, 0)),  # all candidates
        ],
        out_specs=pl.BlockSpec((TILE_X, Ep), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Ep, Ep), jnp.bool_),
        interpret=interpret,
    )(la_t, fd_t)
    return out[:E, :E]
