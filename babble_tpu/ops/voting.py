"""Live consensus offload: fame + round-received as device tensor programs.

This is the kernel behind ``--accelerator``'s consensus path. The division of
labour with the host is deliberate and reference-exact:

- The host keeps the *incremental* bookkeeping the reference does per insert —
  signature checks, fork prevention, coordinate maintenance, and round/witness
  assignment (reference: src/hashgraph/hashgraph.go:672-750, 807-872). These
  walks gate insert-time semantics (the first-descendant walk stops at
  witnesses, hashgraph.go:503-512) so they must observe exactly the state the
  reference would; they are O(depth) per event and cheap.
- The device takes the *batch* work that dominates the pipeline — virtual
  voting (DecideFame, hashgraph.go:875-998) and round-received
  (DecideRoundReceived, hashgraph.go:1002-1095) — as masked matmuls and
  boolean reductions over a dense window snapshot.

Only witnesses vote and are voted on, so the vote state lives on a compact
witness axis W instead of the full event axis E: fame is O(R·W²) and the
see-visibility mask is [W, E], which keeps warm sweeps at
milliseconds even when a large undecided window (E in the hundreds) has
accumulated. (A dense [E, E] formulation measurably death-spirals: slow
sweeps grow the window, which slows sweeps further.)

Unlike :mod:`babble_tpu.ops.dag` (the all-at-once pipeline used by the bench
and the multi-chip dryrun), these kernels support **dynamic membership**:
peer-sets vary per round, so the peer axis is padded to the full repertoire
and each round carries a peer-set slot (``psi``) selecting a membership mask
and super-majority threshold (reference: per-round peer-sets in DecideFame,
hashgraph.go:875-998, interval lookup caches.go:126-222).

The whole sweep — fame voting, per-round decidedness, and round-received —
is ONE fused device call returning ONE concatenated int32 vector
``[fame | round_received]``. This shape is forced by the measured transport
economics of the target: a device→host readback of a fresh buffer costs
~65-100 ms through the accelerator tunnel regardless of size, while kernel
execution and host→device transfers are sub-millisecond. Any design with a
host step in the middle (the round-3 two-call split) pays that latency twice
and can never win; the fused kernel pays it once — and the async pipeline in
:mod:`babble_tpu.hashgraph.accel` hides even that behind gossip.

The oracle's *sticky* round-decided flag (roundInfo.go:73-96; a round once
decided stays decided even if a laggard later inserts an undecided witness)
is preserved by passing the host's pre-sweep sticky flags in and computing
post-sweep decidedness on device: fame decisions are monotone (the kernel
only fills UNDEFINED slots), so device decidedness from (sticky | recompute
over post-sweep fame) equals the oracle's post-apply ``witnesses_decided``.

Shapes are padded to buckets (W, E, R and S to powers of two, P to a
multiple of 8) so XLA compiles once per bucket and the jit cache stays warm
across sweeps; compiled buckets are tracked module-wide so every node in a
process shares warm-up work.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from babble_tpu.common.errors import StoreError
from babble_tpu.ops.intdot import vote_matmul
from babble_tpu.common.trilean import Trilean

INT32_MAX = np.int32(2**31 - 1)

# Frequency of coin rounds (reference: hashgraph.go:24-25). Kept in sync with
# babble_tpu.hashgraph.hashgraph.COIN_ROUND_FREQ.
COIN_ROUND_FREQ = 4


def _bucket_pow2(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _bucket_mult(n: int, m: int) -> int:
    return max(m, ((n + m - 1) // m) * m)


@dataclass
class VotingWindow:
    """Dense window over the undecided suffix of the hashgraph.

    Two row spaces:
    - E rows: undetermined events + all witnesses of rounds >= the window
      floor (``hashes``/``row``). Carries creator/index/rounds/undet.
    - W rows: the witness subset (``wit_hashes``/``wit_row``), indexing into
      E rows via ``wit_idx``. Carries coordinates, fame state, coin bits.

    Rounds are rebased by ``base`` so in-kernel round indexes stay small
    regardless of absolute round numbers.
    """

    # E-space
    creator: np.ndarray  # [E] int32 peer column of creator (0 for padding)
    index: np.ndarray  # [E] int32 per-creator sequence (-1 padding)
    rounds: np.ndarray  # [E] int32 rebased round (-10 padding)
    undet: np.ndarray  # [E] bool — rows eligible for round-received
    # W-space (witnesses)
    wit_idx: np.ndarray  # [W] int32 row in E-space (0 for padding)
    la_w: np.ndarray  # [W, P] int32, -1 missing
    fd_w: np.ndarray  # [W, P] int32, INT32_MAX missing
    rounds_w: np.ndarray  # [W] int32 rebased (-10 padding)
    valid_w: np.ndarray  # [W] bool
    fame0_w: np.ndarray  # [W] int32 {-1, 0, 1} initial fame from round infos
    mid_w: np.ndarray  # [W] bool coin bits
    # peer-sets per round
    member: np.ndarray  # [S, P] bool membership masks
    sm_s: np.ndarray  # [S] int32 super-majority per slot
    psi: np.ndarray  # [R] int32 rebased-round -> peer-set slot
    sm_r: np.ndarray  # [R] int32 rebased-round -> super-majority
    # round-scan state for the fused decided/hard-block computation
    exists_r: np.ndarray  # [R] bool — round info readable from the store
    prior_dec_r: np.ndarray  # [R] bool — pre-sweep sticky decided flags
    lb_gate_r: np.ndarray  # [R] bool — round above the fast-sync lower bound
    base: int  # absolute round of rebased round 0
    hashes: List[str] = field(default_factory=list)  # real E rows
    row: Dict[str, int] = field(default_factory=dict)
    wit_hashes: List[str] = field(default_factory=list)  # real W rows
    wit_row: Dict[str, int] = field(default_factory=dict)
    # Resident-window provenance (ops/window_state.py): windows snapshotted
    # from a persistent WindowState carry the state's generation at
    # snapshot time plus a back-reference, so downstream consumers (the
    # sweep batcher, TensorConsensus._apply) can detect that the state
    # mutated underneath them and discard stale results instead of
    # applying them through moved row maps.
    generation: int = 0
    state: Optional[object] = None

    @property
    def n_events(self) -> int:
        return int(self.creator.shape[0])

    @property
    def n_witnesses(self) -> int:
        return int(self.wit_idx.shape[0])


# =============================================================================
# Kernels
# =============================================================================


def pallas_mode() -> Optional[str]:
    """How the live sweep's membership strongly-see should run:
    ``"tpu"`` (BABBLE_PALLAS=1 on a real TPU — the Pallas tiled kernel),
    ``"interpret"`` (BABBLE_PALLAS_INTERPRET=1 — the same kernel in
    interpreter mode, for differential tests on CPU), or None (the XLA
    einsum). Evaluated at TRACE time, so it must be set before the first
    sweep of a shape bucket compiles."""
    import os

    if os.environ.get("BABBLE_PALLAS_INTERPRET") == "1":
        return "interpret"
    if os.environ.get("BABBLE_PALLAS") != "1":
        return None
    from babble_tpu.ops.device import on_tpu

    return "tpu" if on_tpu() else None


def _fame_core(creator, index, la_w, fd_w, rounds_w, valid_w, fame0_w, mid_w,
               wit_idx, member, sm_s, psi, sm_r):
    """Virtual voting on the witness axis (oracle: hashgraph.go:875-998)
    with per-round peer-sets. Returns (see_we, fame_w); ``see_we`` ([W, E],
    witness w sees event x) stays on device for the round-received kernel."""
    R = psi.shape[0]

    # SEE[w, x] = w sees x via lastAncestors (oracle: hashgraph.go:96-128).
    see_we = (la_w[:, creator] >= index[None, :]) & valid_w[:, None]
    see_ww = see_we[:, wit_idx]  # witness-to-witness visibility

    # SS[s, w, w'] per peer-set slot (oracle: hashgraph.go:172-206 with the
    # per-round peer-set argument). [W, W, P] compare stays small because W
    # is the witness count, not the event count.
    mode = pallas_mode()
    if mode is not None:
        # Pallas tiled kernel: streams the peer axis through VMEM, no
        # [W, W, P] intermediate (ops/pallas_kernels.py). Bit-identical
        # counts; differential-tested in interpreter mode.
        from babble_tpu.ops.pallas_kernels import member_ss_counts_pallas

        counts = member_ss_counts_pallas(
            la_w, fd_w, member, interpret=(mode == "interpret")
        )
    else:
        # XLA einsum: operands are 0/1, so int8 inputs with an int32
        # accumulator are EXACT while letting the TPU tile the contraction
        # onto the MXU (int8 matmul units) instead of the VPU; counts are
        # bounded by P (peer axis) which fits int32 trivially.
        ge = (la_w[:, None, :] >= fd_w[None, :, :]).astype(jnp.int8)
        counts = jnp.einsum(
            "vwp,sp->svw",
            ge,
            member.astype(jnp.int8),
            preferred_element_type=jnp.int32,
        )
    ss_all = counts >= sm_s[:, None, None]  # [S, W, W]

    def per_round(j, state):
        votes, fame = state
        voter = valid_w & (rounds_w == j)  # [W(y)]
        diff = j - rounds_w  # [W(x)] per candidate

        # Derived vote: majority among strongly-seen witnesses of j-1,
        # evaluated against round j-1's peer-set (hashgraph.go:928-948).
        prev_w = valid_w & (rounds_w == (j - 1))
        slot_prev = psi[jnp.clip(j - 1, 0, R - 1)]
        ss_prev = ss_all[slot_prev] & prev_w[None, :]  # [W(y), W(w)]
        n_ss = jnp.sum(ss_prev, axis=1, dtype=jnp.int32)
        yays = vote_matmul(ss_prev, votes)  # exact int8->int32 MXU tally
        nays = n_ss[:, None] - yays
        v = yays >= nays
        t = jnp.maximum(yays, nays)
        sm_j = sm_r[jnp.clip(j, 0, R - 1)]  # round j's super-majority
        settled = t >= sm_j

        is_coin = (diff % COIN_ROUND_FREQ) == 0
        derived = jnp.where(is_coin[None, :] & ~settled, mid_w[:, None], v)
        new_vote = jnp.where((diff == 1)[None, :], see_ww, derived)

        active = voter[:, None] & valid_w[None, :] & (diff >= 1)[None, :]
        votes = jnp.where(active, new_vote, votes)

        decide_pair = active & ~is_coin[None, :] & (diff > 1)[None, :] & settled
        decided_now = jnp.any(decide_pair, axis=0)
        decided_val = jnp.any(decide_pair & v, axis=0)
        newly = decided_now & (fame == 0)
        fame = jnp.where(newly, jnp.where(decided_val, 1, -1), fame)
        return votes, fame

    W = rounds_w.shape[0]
    votes0 = jnp.zeros((W, W), bool)
    votes, fame = lax.fori_loop(1, R, per_round, (votes0, fame0_w))
    return see_we, fame


def _rr_core(see_we, rounds_w, valid_w, fame_w, rounds_e, undet_e,
             decided_r, hard_block_r, sm_r):
    """Round-received (oracle: hashgraph.go:1002-1095). ``decided_r`` and
    ``hard_block_r`` are host-computed per-round masks carrying the oracle's
    exact scan semantics: an event's ascending round scan stops at the
    first hard-blocking round after its own (a missing round info, or an
    undecided round above the fast-sync lower bound — hashgraph.go:1019-1046)
    and receives only at decided rounds."""
    E = rounds_e.shape[0]
    R = decided_r.shape[0]

    def per_round(i, state):
        rr, blocked = state
        fw = valid_w & (rounds_w == i) & (fame_w == 1)  # famous witnesses of i
        n_fw = jnp.sum(fw, dtype=jnp.int32)
        sees_x = see_we | (~fw)[:, None]
        all_see = jnp.all(sees_x, axis=0) & (n_fw >= sm_r[jnp.clip(i, 0, R - 1)])
        relevant = rounds_e < i
        eligible = (
            decided_r[i] & ~blocked & relevant & (rr < 0) & all_see & undet_e
        )
        rr = jnp.where(eligible, i, rr)
        blocked = blocked | (relevant & hard_block_r[i])
        return rr, blocked

    rr0 = jnp.full(E, -1, jnp.int32)
    blocked0 = jnp.zeros(E, bool)
    rr, _ = lax.fori_loop(1, R, per_round, (rr0, blocked0))
    return rr


def _sweep_core(creator, index, la_w, fd_w, rounds_w, valid_w, fame0_w, mid_w,
                wit_idx, member, sm_s, psi, sm_r,
                rounds_e, undet_e, exists_r, prior_dec_r, lb_gate_r):
    """The fused sweep: fame voting → per-round decidedness → round-received
    in one compiled program, one output buffer, one readback.

    Decidedness replicates ``RoundInfo.witnesses_decided``
    (roundInfo.go:78-96) on device: a round is decided when no witness is
    UNDEFINED and the decided count reaches the round's super-majority —
    OR the host's sticky pre-sweep flag was already set. Hard-blocking
    replicates the oracle's receive-scan stops (hashgraph.go:1019-1046):
    an unreadable round blocks unconditionally; an undecided round blocks
    only above the fast-sync lower bound.
    """
    see_we, fame = _fame_core(
        creator, index, la_w, fd_w, rounds_w, valid_w, fame0_w, mid_w,
        wit_idx, member, sm_s, psi, sm_r,
    )
    R = psi.shape[0]
    r_ax = jnp.arange(R)
    m_rw = valid_w[None, :] & (rounds_w[None, :] == r_ax[:, None])  # [R, W]
    undecided_w = fame == 0
    has_undec = jnp.any(m_rw & undecided_w[None, :], axis=1)
    cnt = jnp.sum(m_rw & (~undecided_w)[None, :], axis=1, dtype=jnp.int32)
    decided_r = prior_dec_r | (exists_r & ~has_undec & (cnt >= sm_r))
    hard_block_r = (~exists_r) | ((~decided_r) & lb_gate_r)
    rr = _rr_core(see_we, rounds_w, valid_w, fame, rounds_e, undet_e,
                  decided_r, hard_block_r, sm_r)
    return jnp.concatenate([fame, rr])


# Counts traces so tests can pin the compile-cache property.
_trace_count = 0


def _counting_sweep(*args):
    global _trace_count
    _trace_count += 1
    return _sweep_core(*args)


_sweep_jit = jax.jit(_counting_sweep)

# Batched sweep: the SAME fused program vmapped over a leading batch axis,
# so co-located nodes' windows ride ONE device dispatch and ONE readback
# (hashgraph/sweep_batcher.py). Exact per-window semantics: vmap adds a
# batch dimension, it never mixes rows.
_batched_sweep_jit = jax.jit(jax.vmap(_counting_sweep))


# =============================================================================
# Host side: window construction and result application
# =============================================================================


def _fame_init(trilean: Trilean) -> int:
    if trilean == Trilean.TRUE:
        return 1
    if trilean == Trilean.FALSE:
        return -1
    return 0


def build_voting_window(hg) -> Optional[VotingWindow]:
    """Snapshot the undecided suffix of a Hashgraph into dense tensors.

    Returns None when there is nothing to decide. Raises StoreError when a
    needed event/round has been evicted — the caller falls back to the
    oracle sweep in that case.

    Window floor = min(first pending round, min round over undetermined
    events): pending rounds can trail the undetermined set when all their
    events were received before fame was decided, and vice versa, so both
    bound the rows the vote and receive scans touch.
    """
    store = hg.store
    undetermined = list(hg.undetermined_events)
    pending = [pr.index for pr in hg.pending_rounds.get_ordered_pending_rounds()]
    if not undetermined and not pending:
        return None

    floors = list(pending)
    undet_rounds: Dict[str, int] = {}
    # Events fetched for the floor computation are reused by the row-fill
    # loop below — the undetermined set dominates E, so fetching each row
    # twice doubled the store traffic of every rebuild.
    ev_cache: Dict[str, object] = {}
    for h in undetermined:
        ev = store.get_event(h)
        if ev.round is None:
            return None  # divide_rounds has not run yet
        ev_cache[h] = ev
        undet_rounds[h] = ev.round
        floors.append(ev.round)
    base = min(floors)
    last_round = store.last_round()

    # Peer columns span the full repertoire so any peer-set's mask and any
    # event's coordinates map onto the same axis.
    rep = store.repertoire_by_pub_key()
    pub_keys = sorted(rep.keys())
    peer_col = {pk: i for i, pk in enumerate(pub_keys)}
    n_peers = len(pub_keys)

    # E rows: all undetermined events first (their list order is the
    # oracle's scan order), then every witness of rounds >= base from the
    # round infos. W rows: the witness subset.
    hashes: List[str] = list(undetermined)
    rows = {h: i for i, h in enumerate(hashes)}
    witness_info: Dict[str, tuple] = {}  # hash -> (round, famous)
    for r in range(base, last_round + 1):
        try:
            ri = store.get_round(r)
        except StoreError:
            continue
        for x, re_ in ri.created_events.items():
            if re_.witness:
                witness_info[x] = (r, re_.famous)
                if x not in rows:
                    rows[x] = len(hashes)
                    hashes.append(x)
    wit_hashes = list(witness_info.keys())
    wit_rows = {h: i for i, h in enumerate(wit_hashes)}

    E_real = len(hashes)
    W_real = len(wit_hashes)
    E = _bucket_pow2(E_real, 32)
    W = _bucket_pow2(W_real, 16)
    P = _bucket_mult(n_peers, 8)
    R_real = last_round - base + 2
    R = _bucket_pow2(R_real, 8)

    creator = np.zeros(E, np.int32)
    index = np.full(E, -1, np.int32)
    rounds = np.full(E, -10, np.int32)
    undet_mask = np.zeros(E, bool)
    wit_idx = np.zeros(W, np.int32)
    la_w = np.full((W, P), -1, np.int32)
    fd_w = np.full((W, P), INT32_MAX, np.int32)
    rounds_w = np.full(W, -10, np.int32)
    valid_w = np.zeros(W, bool)
    fame0_w = np.zeros(W, np.int32)
    mid_w = np.zeros(W, bool)

    from babble_tpu.hashgraph.hashgraph import middle_bit

    for h, i in rows.items():
        ev = ev_cache.get(h)
        if ev is None:
            ev = store.get_event(h)
        creator[i] = peer_col[ev.creator()]
        index[i] = ev.index()
        if h in undet_rounds:
            r_abs = undet_rounds[h]
        else:
            r_abs = witness_info[h][0]
        rounds[i] = r_abs - base
        undet_mask[i] = h in undet_rounds
        w = wit_rows.get(h)
        if w is not None:
            wit_idx[w] = i
            rounds_w[w] = r_abs - base
            valid_w[w] = True
            fame0_w[w] = _fame_init(witness_info[h][1])
            mid_w[w] = middle_bit(h)
            for pk, coords in ev.last_ancestors.items():
                c = peer_col.get(pk)
                if c is not None:
                    la_w[w, c] = coords.index
            for pk, coords in ev.first_descendants.items():
                c = peer_col.get(pk)
                if c is not None:
                    fd_w[w, c] = coords.index

    # Per-round peer-sets: one slot per distinct set effective in the window
    # (interval semantics of PeerSetCache.get, caches.go:169-193). Rounds
    # past the last recorded change reuse the final set, which is exactly
    # what the interval lookup returns.
    slot_of: Dict[bytes, int] = {}
    members: List[np.ndarray] = []
    sms: List[int] = []
    psi = np.zeros(R, np.int32)
    sm_r = np.full(R, 2**30, np.int32)
    exists_r = np.zeros(R, bool)
    prior_dec_r = np.zeros(R, bool)
    lb_gate_r = np.zeros(R, bool)
    lb = hg.round_lower_bound
    for r in range(R):
        a = base + r
        lb_gate_r[r] = lb is None or lb < a
        try:
            ri = store.get_round(a)
        except StoreError:
            pass  # exists_r stays False -> hard-blocks the receive scan
        else:
            exists_r[r] = True
            prior_dec_r[r] = ri.decided
        ps = store.get_peer_set(a)
        key = ps.hash()
        s = slot_of.get(key)
        if s is None:
            s = len(members)
            slot_of[key] = s
            m = np.zeros(P, bool)
            for pk in ps.pub_keys():
                c = peer_col.get(pk)
                if c is not None:
                    m[c] = True
            members.append(m)
            sms.append(ps.super_majority())
        psi[r] = s
        sm_r[r] = sms[s]

    S = _bucket_pow2(len(members), 1)
    member = np.zeros((S, P), bool)
    sm_s = np.full(S, 2**30, np.int32)
    for s, m in enumerate(members):
        member[s] = m
        sm_s[s] = sms[s]

    return VotingWindow(
        creator=creator,
        index=index,
        rounds=rounds,
        undet=undet_mask,
        wit_idx=wit_idx,
        la_w=la_w,
        fd_w=fd_w,
        rounds_w=rounds_w,
        valid_w=valid_w,
        fame0_w=fame0_w,
        mid_w=mid_w,
        member=member,
        sm_s=sm_s,
        psi=psi,
        sm_r=sm_r,
        exists_r=exists_r,
        prior_dec_r=prior_dec_r,
        lb_gate_r=lb_gate_r,
        base=base,
        hashes=hashes,
        row=rows,
        wit_hashes=wit_hashes,
        wit_row=wit_rows,
    )


def bucket_key(win: VotingWindow) -> tuple:
    return (
        win.n_witnesses,
        win.n_events,
        win.member.shape[1],
        win.member.shape[0],
        win.psi.shape[0],
    )


def repad_window(win: VotingWindow, key: tuple) -> VotingWindow:
    """Grow a window to a LARGER shape bucket with the same neutral fills
    build_voting_window pads with — co-located nodes at slightly different
    DAG progress land in different buckets, and the batcher re-pads a
    whole wave to their elementwise-max bucket so it rides one dispatch.

    Safe by the same argument as the builder's own padding: invalid W rows
    (valid_w False) never vote and never count; sentinel E rows (index -1,
    undet False) are seen by nobody and can't receive; extra R rows have no
    voters (no witness carries their round) and, being past every real
    round, their hard-block can't cut an earlier receive scan; extra S
    slots are unreferenced (psi points only at real slots). Row indexes of
    real entries are preserved, so the result maps back through the
    ORIGINAL window's row/wit_row tables."""
    W, E, P, S, R = key
    W0, E0 = win.n_witnesses, win.n_events
    P0, S0, R0 = win.member.shape[1], win.member.shape[0], win.psi.shape[0]
    if (W0, E0, P0, S0, R0) == key:
        return win

    def pad(a, n, fill):
        if n == 0:
            return a
        widths = [(0, n)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths, constant_values=fill)

    la_w = pad(win.la_w, W - W0, -1)
    fd_w = pad(win.fd_w, W - W0, INT32_MAX)
    if P > P0:
        la_w = np.pad(la_w, ((0, 0), (0, P - P0)), constant_values=-1)
        fd_w = np.pad(fd_w, ((0, 0), (0, P - P0)),
                      constant_values=INT32_MAX)
    member = pad(win.member, S - S0, False)
    if P > P0:
        member = np.pad(member, ((0, 0), (0, P - P0)),
                        constant_values=False)
    return VotingWindow(
        creator=pad(win.creator, E - E0, 0),
        index=pad(win.index, E - E0, -1),
        rounds=pad(win.rounds, E - E0, -10),
        undet=pad(win.undet, E - E0, False),
        wit_idx=pad(win.wit_idx, W - W0, 0),
        la_w=la_w,
        fd_w=fd_w,
        rounds_w=pad(win.rounds_w, W - W0, -10),
        valid_w=pad(win.valid_w, W - W0, False),
        fame0_w=pad(win.fame0_w, W - W0, 0),
        mid_w=pad(win.mid_w, W - W0, False),
        member=member,
        sm_s=pad(win.sm_s, S - S0, 2**30),
        psi=pad(win.psi, R - R0, 0),
        sm_r=pad(win.sm_r, R - R0, 2**30),
        exists_r=pad(win.exists_r, R - R0, False),
        prior_dec_r=pad(win.prior_dec_r, R - R0, False),
        lb_gate_r=pad(win.lb_gate_r, R - R0, False),
        base=win.base,
        hashes=win.hashes,
        row=win.row,
        wit_hashes=win.wit_hashes,
        wit_row=win.wit_row,
        generation=win.generation,
        state=win.state,
    )


# Compiled-bucket bookkeeping shared by every TensorConsensus in the process
# (the underlying jit cache is global, so warm-up work must be too).
_ready_buckets: set = set()
_ready_lock = None  # created lazily to keep import cheap


def _bucket_lock():
    global _ready_lock
    if _ready_lock is None:
        import threading

        _ready_lock = threading.Lock()
    return _ready_lock


def bucket_ready(key: tuple) -> bool:
    with _bucket_lock():
        return key in _ready_buckets


def mark_bucket_ready(key: tuple) -> None:
    with _bucket_lock():
        _ready_buckets.add(key)


# The vmapped program is a different executable per (batch, bucket); its
# readiness is tracked separately so the batcher can route unwarmed batch
# shapes through warm single-window dispatches meanwhile.
_ready_batched: set = set()


def batched_ready(key: tuple, batch: int) -> bool:
    with _bucket_lock():
        return (batch, key) in _ready_batched


def precompile_batched(batch: int, W: int, E: int, P: int, S: int,
                       R: int) -> None:
    """Compile (or load from the persistent cache) the batched sweep for a
    (batch, bucket) pair on all-invalid dummy windows."""
    key = (W, E, P, S, R)
    wins = [dummy_window(*key) for _ in range(batch)]
    read_batched(launch_batched(wins, batch), wins)
    with _bucket_lock():
        _ready_batched.add((batch, key))


def dummy_window(W: int, E: int, P: int, S: int, R: int) -> VotingWindow:
    """An all-invalid window of a given shape bucket, for precompilation."""
    return VotingWindow(
        creator=np.zeros(E, np.int32),
        index=np.full(E, -1, np.int32),
        rounds=np.full(E, -10, np.int32),
        undet=np.zeros(E, bool),
        wit_idx=np.zeros(W, np.int32),
        la_w=np.full((W, P), -1, np.int32),
        fd_w=np.full((W, P), INT32_MAX, np.int32),
        rounds_w=np.full(W, -10, np.int32),
        valid_w=np.zeros(W, bool),
        fame0_w=np.zeros(W, np.int32),
        mid_w=np.zeros(W, bool),
        member=np.zeros((S, P), bool),
        sm_s=np.full(S, 2**30, np.int32),
        psi=np.zeros(R, np.int32),
        sm_r=np.full(R, 2**30, np.int32),
        exists_r=np.zeros(R, bool),
        prior_dec_r=np.zeros(R, bool),
        lb_gate_r=np.zeros(R, bool),
        base=0,
    )


def precompile(W: int, E: int, P: int, S: int, R: int) -> None:
    """Compile (or load from the persistent cache) the fused sweep kernel
    for a shape bucket by running it on an all-invalid dummy window. Called
    from a background thread (TensorConsensus / node prewarm) so live
    sweeps never stall on XLA compilation."""
    run_sweep(dummy_window(W, E, P, S, R))
    mark_bucket_ready((W, E, P, S, R))


# VotingWindow attribute names in _sweep_core's positional order (rounds /
# undet are the E-space rounds_e / undet_e arguments).
_WIN_FIELDS = (
    "creator", "index", "la_w", "fd_w", "rounds_w", "valid_w", "fame0_w",
    "mid_w", "wit_idx", "member", "sm_s", "psi", "sm_r", "rounds", "undet",
    "exists_r", "prior_dec_r", "lb_gate_r",
)


def launch_sweep(win: VotingWindow):
    """Dispatch the fused sweep. Returns the device output buffer WITHOUT
    reading it back — dispatch is sub-millisecond; the ~65-100 ms tunnel
    readback is paid by read_sweep (on a background thread in the node's
    pipelined mode)."""
    return _sweep_jit(*(jnp.asarray(getattr(win, f)) for f in _WIN_FIELDS))


_dummy_cache: Dict[tuple, VotingWindow] = {}


def _cached_dummy(key: tuple) -> VotingWindow:
    """Batch-padding dummies are deterministic per bucket; caching one per
    key keeps the ~20-array allocation off the hot flush path (the same
    object is stacked repeatedly — stacking copies the data anyway)."""
    win = _dummy_cache.get(key)
    if win is None:
        win = _dummy_cache[key] = dummy_window(*key)
    return win


def launch_batched(wins: List[VotingWindow], batch: int):
    """Dispatch ONE batched sweep over same-bucket windows, padded with
    all-invalid dummies to ``batch`` rows (one program per (B, bucket)).
    Returns the [B, W+E] device buffer unread."""
    key = bucket_key(wins[0])
    ws = list(wins) + [_cached_dummy(key)] * (batch - len(wins))
    stacked = (
        jnp.asarray(np.stack([np.asarray(getattr(w, f)) for w in ws]))
        for f in _WIN_FIELDS
    )
    return _batched_sweep_jit(*stacked)


def read_batched(out, wins: List[VotingWindow]):
    """ONE readback of the [B, W+E] batched output, split into per-window
    (fame, rr) pairs (padding rows discarded)."""
    host = np.asarray(out)
    res = []
    for i, w in enumerate(wins):
        W = w.n_witnesses
        res.append((host[i, :W], host[i, W:W + w.n_events]))
    return res


def read_sweep(out, win: VotingWindow):
    """One readback of the concatenated [fame | round_received] vector,
    split into (fame[W], rr[E]) numpy arrays."""
    host = np.asarray(out)
    W = win.n_witnesses
    return host[:W], host[W:W + win.n_events]


def run_sweep(win: VotingWindow):
    """Synchronous fused sweep: dispatch + single readback."""
    return read_sweep(launch_sweep(win), win)


def apply_fame(hg, win: VotingWindow, fame: np.ndarray) -> tuple:
    """Write fame into the pending rounds' infos and mark decided rounds
    with the oracle's own sticky rule (mirrors the tail of
    Hashgraph.decide_fame, hashgraph.go:985-996). Returns
    (decided_rounds, applied): ``applied`` is the exact [(hash, ±1)] list
    of set_fame writes, which the incremental WindowState replays into its
    fame mirror at the next snapshot."""
    store = hg.store
    decided_rounds: List[int] = []
    applied: List[tuple] = []
    for pr in hg.pending_rounds.get_ordered_pending_rounds():
        try:
            ri = store.get_round(pr.index)
        except StoreError:
            continue
        ps = store.get_peer_set(pr.index)
        for x, re_ in ri.created_events.items():
            if not re_.witness or re_.famous != Trilean.UNDEFINED:
                continue
            i = win.wit_row.get(x)
            if i is None:
                continue
            f = int(fame[i])
            if f != 0:
                ri.set_fame(x, f == 1)
                applied.append((x, f))
        if ri.witnesses_decided(ps):
            decided_rounds.append(pr.index)
        store.set_round(pr.index, ri)
    hg.pending_rounds.update(decided_rounds)
    return decided_rounds, applied


def apply_round_received(hg, win: VotingWindow, rr: np.ndarray) -> List[str]:
    """Stamp received events and retire them from the undetermined list, in
    the oracle's scan order (mirrors Hashgraph.decide_round_received,
    hashgraph.go:1047-1091). Returns the received hashes — the exact row
    releases the incremental WindowState applies at the next snapshot."""
    store = hg.store
    # Two-phase: gather every fallible store read first so a StoreError can
    # abort BEFORE any mutation — a partially-applied receive pass followed
    # by the oracle fallback would double-receive events (add_received_event
    # has no dedup) and fork the node's blocks from its peers'. Each round's
    # info is fetched ONCE and shared by all its received events: a store
    # that deserializes fresh copies per get (the persistent store) would
    # otherwise keep only the last event of a round.
    new_undetermined: List[str] = []
    updates = []  # (event, round_received_abs)
    round_infos = {}  # round -> RoundInfo, fetched once
    for h in hg.undetermined_events:
        i = win.row.get(h)
        r = int(rr[i]) if i is not None else -1
        if r >= 0:
            a = r + win.base
            if a not in round_infos:
                round_infos[a] = store.get_round(a)
            updates.append((store.get_event(h), a))
        else:
            new_undetermined.append(h)
    for ev, a in updates:
        ev.set_round_received(a)
        store.set_event(ev)
        round_infos[a].add_received_event(ev.hex())
    for a, tr in round_infos.items():
        store.set_round(a, tr)
    hg.undetermined_events = new_undetermined
    return [ev.hex() for ev, _ in updates]
