"""Shared exact integer contraction for vote tallies.

Vote/strongly-see matrices are 0/1, so int8 operands with an int32
accumulator compute the same tallies as int32 x int32 (products are 0/1;
sums are bounded by the contraction length, far below 2^31) while letting
the TPU tile the contraction onto the MXU's int8 units instead of the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp


def vote_matmul(a, b) -> jnp.ndarray:
    """[M, K] x [K, N] 0/1 tally: a @ b with int8 inputs, int32 output."""
    return jnp.matmul(
        a.astype(jnp.int8),
        b.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    )
