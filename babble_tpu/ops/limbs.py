"""256-bit modular field arithmetic for secp256k1 in 16x16-bit limbs (JAX).

TPU has no native 64-bit integer multiply, so big-int math is decomposed
into 16-bit limbs held in uint32 lanes: a 16x16-bit product fits exactly in
32 bits, and a 32-column schoolbook accumulation of 16-bit half-products
stays under 2^21 per column, so no intermediate ever overflows uint32.
Everything here is elementwise over a leading batch dimension and is
designed to be `jax.vmap`/`pjit`-sharded over signature batches.

Field: F_p with p = 2^256 - 2^32 - 977 (secp256k1). The special form makes
reduction a multiply-by-tiny-constant fold: 2^256 === 2^32 + 977 (mod p).

This is the arithmetic layer under babble_tpu/ops/verify.py, the batched
replacement for per-event signature verification in the reference's insert
path (/root/reference/src/hashgraph/hashgraph.go:672-687,
/root/reference/src/crypto/keys/signature.go:20). The portable oracle is
babble_tpu/crypto/secp256k1.py.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

NLIMB = 16  # 16 limbs x 16 bits = 256 bits
LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1

# secp256k1 field prime p = 2^256 - C where C = 2^32 + 977
P_INT = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N_INT = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
C_INT = (1 << 256) - P_INT  # 2^32 + 977


def int_to_limbs(x: int, n: int = NLIMB) -> np.ndarray:
    """Little-endian 16-bit limb decomposition as uint32 numpy array."""
    return np.array(
        [(x >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n)], dtype=np.uint32
    )


def limbs_to_int(limbs) -> int:
    out = 0
    arr = np.asarray(limbs, dtype=np.uint64)
    for i, v in enumerate(arr):
        out |= int(v) << (LIMB_BITS * i)
    return out


def ints_to_limbs(xs, n: int = NLIMB) -> np.ndarray:
    """[B] python ints -> [B, n] uint32 limbs."""
    return np.stack([int_to_limbs(x, n) for x in xs], axis=0)


P_LIMBS = int_to_limbs(P_INT)
N_LIMBS = int_to_limbs(N_INT)
C_LIMBS = int_to_limbs(C_INT)  # [977, 0, 1, 0, ...]

# Static index map for schoolbook column accumulation: column k collects
# lo(a_i*b_j) at i+j == k and hi(a_i*b_j) at i+j == k-1.
_I, _J = np.meshgrid(np.arange(NLIMB), np.arange(NLIMB), indexing="ij")
_COL_LO = (_I + _J).reshape(-1)  # [256] in 0..30
_COL_HI = (_I + _J + 1).reshape(-1)  # [256] in 1..31


def _carry_propagate(cols: jnp.ndarray, n_out: int) -> jnp.ndarray:
    """Sequential carry chain over columns -> n_out clean 16-bit limbs.

    cols: [..., n_cols] uint32 with values < 2^26. Returns [..., n_out]
    limbs plus nothing — callers must size n_out so the final carry is 0
    (guaranteed by the bound analysis at each call site).
    """
    n_cols = cols.shape[-1]
    if n_cols < n_out:
        pad = [(0, 0)] * (cols.ndim - 1) + [(0, n_out - n_cols)]
        cols = jnp.pad(cols, pad)
        n_cols = n_out

    def step(carry, col):
        v = col + carry
        return v >> LIMB_BITS, v & LIMB_MASK

    carry0 = jnp.zeros(cols.shape[:-1], dtype=jnp.uint32)
    # scan over the limb axis (moved to front)
    _, limbs = jax.lax.scan(step, carry0, jnp.moveaxis(cols, -1, 0))
    return jnp.moveaxis(limbs, 0, -1)[..., :n_out]


def mul_wide(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """[..., 16] x [..., 16] -> [..., 32] full 512-bit product limbs."""
    prod = a[..., :, None] * b[..., None, :]  # [..., 16, 16] each < 2^32
    lo = prod & LIMB_MASK
    hi = prod >> LIMB_BITS
    flat_lo = lo.reshape(*lo.shape[:-2], NLIMB * NLIMB)
    flat_hi = hi.reshape(*hi.shape[:-2], NLIMB * NLIMB)
    cols_lo = jax.ops.segment_sum(
        jnp.moveaxis(flat_lo, -1, 0), _COL_LO, num_segments=32
    )
    cols_hi = jax.ops.segment_sum(
        jnp.moveaxis(flat_hi, -1, 0), _COL_HI, num_segments=32
    )
    cols = jnp.moveaxis(cols_lo + cols_hi, 0, -1)  # [..., 32] < 2^21 each
    return _carry_propagate(cols, 32)


def _fold_once(limbs: jnp.ndarray, n_in: int, n_hi: int) -> jnp.ndarray:
    """Fold limbs above 256 bits: z = H*2^256 + L === L + H*C (mod p).

    limbs: [..., n_in]; H has n_hi limbs. Returns [..., 17+] columns
    carried into clean limbs sized to hold L + H*C exactly.
    """
    L = limbs[..., :NLIMB]
    H = limbs[..., NLIMB : NLIMB + n_hi]
    # H*C where C has 3 limbs [977, 0, 1]: H*977 + H<<32
    hc_cols = jnp.zeros(
        (*limbs.shape[:-1], NLIMB + n_hi + 3), dtype=jnp.uint32
    )
    h977 = H * np.uint32(977)  # < 2^26
    hc_cols = hc_cols.at[..., :n_hi].add(h977 & LIMB_MASK)
    hc_cols = hc_cols.at[..., 1 : n_hi + 1].add(h977 >> LIMB_BITS)
    hc_cols = hc_cols.at[..., 2 : n_hi + 2].add(H)  # << 32 = 2 limbs
    hc_cols = hc_cols.at[..., :NLIMB].add(L)
    n_out = max(NLIMB + 1, n_hi + 3)
    return _carry_propagate(hc_cols, n_out)


def _geq(a: jnp.ndarray, b: np.ndarray) -> jnp.ndarray:
    """a >= b for clean limb arrays (b a constant [16] array)."""
    bb = jnp.asarray(b, dtype=jnp.uint32)
    gt = a > bb
    lt = a < bb
    # most-significant difference decides; scan from high limb down
    def step(state, pair):
        decided, result = state
        g, l = pair
        result = jnp.where(~decided & g, True, result)
        result = jnp.where(~decided & l, False, result)
        decided = decided | g | l
        return (decided, result), None

    init = (
        jnp.zeros(a.shape[:-1], dtype=bool),
        jnp.ones(a.shape[:-1], dtype=bool),  # equal => geq True
    )
    pairs = (
        jnp.moveaxis(gt, -1, 0)[::-1],
        jnp.moveaxis(lt, -1, 0)[::-1],
    )
    (decided, result), _ = jax.lax.scan(step, init, pairs)
    return result


def _sub_const(a: jnp.ndarray, b: np.ndarray) -> jnp.ndarray:
    """a - b (mod 2^256) for clean limbs, b constant, assuming a >= b
    where selected; borrow chain in uint32."""
    bb = jnp.asarray(b, dtype=jnp.uint32)

    def step(borrow, pair):
        av, bv = pair
        v = av + (LIMB_MASK + 1) - bv - borrow
        return 1 - (v >> LIMB_BITS), v & LIMB_MASK

    borrow0 = jnp.zeros(a.shape[:-1], dtype=jnp.uint32)
    pairs = (
        jnp.moveaxis(a, -1, 0),
        jnp.moveaxis(jnp.broadcast_to(bb, a.shape), -1, 0),
    )
    _, limbs = jax.lax.scan(step, borrow0, pairs)
    return jnp.moveaxis(limbs, 0, -1)


def cond_sub_p(a: jnp.ndarray) -> jnp.ndarray:
    """a mod p for a < 2p: subtract p when a >= p."""
    ge = _geq(a, P_LIMBS)
    sub = _sub_const(a, P_LIMBS)
    return jnp.where(ge[..., None], sub, a)


def reduce_p(wide: jnp.ndarray) -> jnp.ndarray:
    """[..., 32] 512-bit product -> [..., 16] canonical mod-p residue."""
    # fold 1: H up to 16 limbs -> result <= 2^256 + 2^289ish -> 19 limbs
    f1 = _fold_once(wide, 32, 16)  # [..., 19]
    # fold 2: H up to 3 limbs -> <= 2^256 + 2^81 -> 17 limbs
    f2 = _fold_once(f1, f1.shape[-1], max(1, f1.shape[-1] - NLIMB))
    # fold 3: H at most 1 limb, tiny -> < 2^256 + 2^49
    f3 = _fold_once(f2, f2.shape[-1], max(1, f2.shape[-1] - NLIMB))
    r = f3[..., :NLIMB]
    # at most 2 conditional subtractions of p remain
    r = cond_sub_p(r)
    r = cond_sub_p(r)
    return r


def mul_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return reduce_p(mul_wide(a, b))


def sqr_mod_p(a: jnp.ndarray) -> jnp.ndarray:
    return mul_mod_p(a, a)


def add_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    cols = a + b  # < 2^17 per column
    s = _carry_propagate(cols, NLIMB + 1)
    # s < 2p < 2^257; if bit 256 set or s >= p, subtract p
    top = s[..., NLIMB]
    r = s[..., :NLIMB]
    ge = _geq(r, P_LIMBS) | (top > 0)
    # when top is set, r + 2^256 - p = r + C
    sub = _sub_const(r, P_LIMBS)
    with_top = _carry_propagate(
        r + jnp.asarray(C_LIMBS, dtype=jnp.uint32), NLIMB
    )
    out = jnp.where((top > 0)[..., None], with_top, jnp.where(ge[..., None], sub, r))
    return out


def sub_mod_p(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """(a - b) mod p for canonical residues: a + (p - b) then reduce."""
    pp = jnp.asarray(P_LIMBS, dtype=jnp.uint32)

    def step(borrow, pair):
        pv, bv = pair
        v = pv + (LIMB_MASK + 1) - bv - borrow
        return 1 - (v >> LIMB_BITS), v & LIMB_MASK

    borrow0 = jnp.zeros(b.shape[:-1], dtype=jnp.uint32)
    pairs = (
        jnp.moveaxis(jnp.broadcast_to(pp, b.shape), -1, 0),
        jnp.moveaxis(b, -1, 0),
    )
    _, pb = jax.lax.scan(step, borrow0, pairs)
    p_minus_b = jnp.moveaxis(pb, 0, -1)
    return add_mod_p(a, p_minus_b)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Limb-wise select: cond [...] bool, a/b [..., 16]."""
    return jnp.where(cond[..., None], a, b)
