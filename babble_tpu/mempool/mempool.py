"""Bounded dedup transaction pool with admission control and shedding.

Every client transaction passes through ``Mempool.submit`` and receives
an explicit verdict (the admission state machine, docs/mempool.md):

    oversized → duplicate / already_committed → throttled → full → accepted

Dedup is checked before the token bucket so retries of known
transactions cost no tokens and get a precise answer; capacity is
checked last so an evict-oldest pool never evicts to make room for a
transaction the dedup layer would have refused anyway.

Lifecycle of an accepted transaction:

    pending ──drain──▶ in-flight ──commit──▶ committed-hash LRU
       ▲                  │
       └─────requeue──────┘   (event creation failed)

``pending`` holds the bytes (FIFO, capped in count and bytes);
``in-flight`` holds only hashes of drained-but-uncommitted transactions
(their bytes live in the self-event) so a client retry during the
commit window is still a ``duplicate``; the committed LRU turns a retry
of a committed transaction into ``already_committed`` instead of a
second commit. All state transitions happen under ONE internal lock —
never the node's core lock — so admission stays race-clean and cheap
while consensus holds the core lock for inserts.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ..common.lru import LRU
from ..common.timed_lock import named_lock
from ..crypto.hashing import sha256
from .ratelimit import TokenBucket

# Admission verdicts (wire values: SubmitTx returns these strings).
ACCEPTED = "accepted"
DUPLICATE = "duplicate"
ALREADY_COMMITTED = "already_committed"
FULL = "full"
THROTTLED = "throttled"
OVERSIZED = "oversized"
VERDICTS = frozenset(
    {ACCEPTED, DUPLICATE, ALREADY_COMMITTED, FULL, THROTTLED, OVERSIZED}
)

# Overflow policies.
POLICY_REJECT = "reject"
POLICY_EVICT_OLDEST = "evict-oldest"
_POLICIES = (POLICY_REJECT, POLICY_EVICT_OLDEST)


class Mempool:
    """Bounded dedup pool between app submission and self-event creation."""

    def __init__(
        self,
        max_txs: int = 20000,
        max_bytes: int = 32 * 1024 * 1024,
        overflow: str = POLICY_REJECT,
        event_max_txs: int = 1024,
        event_max_bytes: int = 1024 * 1024,
        committed_lru: int = 65536,
        rate_tx_s: float = 0.0,
        burst: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_txs <= 0 or max_bytes <= 0:
            raise ValueError("mempool caps must be positive")
        if event_max_txs <= 0 or event_max_bytes <= 0:
            raise ValueError("mempool event caps must be positive")
        if overflow not in _POLICIES:
            raise ValueError(
                f"unknown mempool overflow policy {overflow!r}; "
                f"expected one of {_POLICIES}"
            )
        self.max_txs = max_txs
        self.max_bytes = max_bytes
        self.overflow = overflow
        self.event_max_txs = event_max_txs
        self.event_max_bytes = event_max_bytes
        self._clock = clock
        # Named for the BABBLE_LOCKCHECK acquisition-order recorder
        # (common/lockcheck.py): Core drains/requeues under the core
        # lock, so the core->mempool edge is part of the audited model;
        # a raw C lock when the recorder is off (hot admission path).
        self._lock = named_lock("mempool")
        # Commit-latency telemetry (attach_telemetry): per-hash admit and
        # drain timestamps feed commit_latency_seconds and the
        # tx_stage_seconds{mempool_wait,consensus} histograms. The dicts
        # are bounded by construction — keys are a subset of
        # pending ∪ in-flight, both capped — and stay EMPTY (zero
        # overhead) until telemetry is attached.
        self._lat_commit = None
        self._lat_wait = None
        self._lat_consensus = None
        # Commit-provenance table (attach_provenance): admit/drain
        # stamps for the cross-node trace merge; None until attached.
        self._prov = None
        self._admit_ts: Dict[bytes, float] = {}
        self._drain_ts: Dict[bytes, float] = {}
        self._pending: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._pending_bytes = 0
        # Drained-but-uncommitted hashes (bytes already live in the
        # self-event). Bounded: consensus normally retires entries at
        # commit, but a stalled cluster must not grow this without limit
        # — the oldest hashes age out (narrowing the dedup window, never
        # growing memory).
        self._inflight: "OrderedDict[bytes, int]" = OrderedDict()
        self._inflight_cap = max(4 * max_txs, 4096)
        self._committed = LRU(committed_lru) if committed_lru > 0 else None
        self._bucket = (
            TokenBucket(rate_tx_s, burst, clock) if rate_tx_s > 0 else None
        )
        # Counters (surfaced as mempool_* via Node.get_stats and /mempool).
        self.submitted = 0
        self.accepted = 0
        self.rejected_full = 0
        self.rejected_dup = 0
        self.rejected_oversized = 0
        self.rejected_throttled = 0
        self.committed_dedup_hits = 0
        self.evictions = 0
        self.requeued = 0
        self.commit_drops = 0
        self.committed_total = 0
        # In-flight hashes aged out past the cap (each narrows the dedup
        # window for one drained-but-uncommitted tx; nonzero only when
        # consensus lags drains by > _inflight_cap transactions).
        self.inflight_aged = 0

    @classmethod
    def from_config(cls, conf) -> "Mempool":
        """Build from a ``Config`` (mempool_* knobs + the node clock, so
        simulated nodes rate-limit and stamp latencies in virtual time)."""
        return cls(
            clock=conf.clock.monotonic,
            max_txs=conf.mempool_max_txs,
            max_bytes=conf.mempool_max_bytes,
            overflow=conf.mempool_overflow,
            event_max_txs=conf.mempool_event_max_txs,
            event_max_bytes=conf.mempool_event_max_bytes,
            committed_lru=conf.mempool_committed_lru,
            rate_tx_s=conf.mempool_rate,
            burst=conf.mempool_burst,
        )

    def attach_telemetry(self, commit_latency, tx_wait, tx_consensus) -> None:
        """Arm the latency histograms (obs.telemetry wiring): from here
        on accepted transactions are timestamped at admit and drain, and
        ``mark_committed`` observes admit→commit into ``commit_latency``
        plus the mempool_wait / consensus stage splits."""
        self._lat_commit = commit_latency
        self._lat_wait = tx_wait
        self._lat_consensus = tx_consensus

    def attach_provenance(self, prov) -> None:
        """Arm per-transaction commit provenance (obs/provenance.py):
        sampled admissions and first drains get origin-side stamps. The
        table applies its own sampling and no-ops when disabled."""
        self._prov = prov

    # -- admission ----------------------------------------------------------

    def submit(self, tx: bytes) -> str:
        """Admit one transaction; returns a verdict string (VERDICTS)."""
        tx = bytes(tx)
        size = len(tx)
        if size > self.event_max_bytes or size > self.max_bytes:
            # could never fit a self-event (or the pool): permanent reject
            with self._lock:
                self.submitted += 1
                self.rejected_oversized += 1
            return OVERSIZED
        h = sha256(tx)
        with self._lock:
            self.submitted += 1
            if h in self._pending or h in self._inflight:
                self.rejected_dup += 1
                return DUPLICATE
            if self._committed is not None and self._committed.peek(h)[1]:
                self.committed_dedup_hits += 1
                return ALREADY_COMMITTED
            if self._bucket is not None and not self._bucket.try_acquire():
                self.rejected_throttled += 1
                return THROTTLED
            while (
                len(self._pending) >= self.max_txs
                or self._pending_bytes + size > self.max_bytes
            ):
                if self.overflow != POLICY_EVICT_OLDEST or not self._pending:
                    self.rejected_full += 1
                    return FULL
                old_h, old = self._pending.popitem(last=False)
                self._pending_bytes -= len(old)
                self._admit_ts.pop(old_h, None)
                # a requeued tx back in pending can carry a drain stamp
                self._drain_ts.pop(old_h, None)
                self.evictions += 1
            self._pending[h] = tx
            self._pending_bytes += size
            self.accepted += 1
            if self._lat_commit is not None:
                self._admit_ts[h] = self._clock()
            if self._prov is not None:
                self._prov.admit(tx)
            return ACCEPTED

    def submit_many(self, txs) -> List[str]:
        return [self.submit(tx) for tx in txs]

    # -- drain / requeue ----------------------------------------------------

    def drain(self) -> List[bytes]:
        """Pop up to ``event_max_txs`` / ``event_max_bytes`` of pending
        transactions in FIFO order for one self-event. Drained hashes
        move to the in-flight set until committed (or requeued)."""
        out: List[bytes] = []
        nbytes = 0
        with self._lock:
            now = self._clock() if self._lat_commit is not None else 0.0
            while self._pending and len(out) < self.event_max_txs:
                h, tx = next(iter(self._pending.items()))
                if out and nbytes + len(tx) > self.event_max_bytes:
                    break
                del self._pending[h]
                self._pending_bytes -= len(tx)
                out.append(tx)
                nbytes += len(tx)
                self._inflight[h] = len(tx)
                ts = self._admit_ts.get(h)
                if ts is not None and h not in self._drain_ts:
                    # first drain only: a requeued tx keeps its original
                    # drain stamp, so mempool_wait gets exactly ONE
                    # sample per tx (admit → first drain) and its count
                    # matches commit_latency_seconds
                    self._drain_ts[h] = now
                    self._lat_wait.observe(now - ts)
                if self._prov is not None:
                    # provenance drain stamp (the table keeps the first)
                    self._prov.drain(tx)
            while len(self._inflight) > self._inflight_cap:
                aged_h, _ = self._inflight.popitem(last=False)
                self._admit_ts.pop(aged_h, None)
                self._drain_ts.pop(aged_h, None)
                self.inflight_aged += 1
        return out

    def requeue(self, txs: List[bytes]) -> None:
        """Put a drained batch back at the FRONT of the pool (FIFO order
        preserved) after a failed event creation. Entries committed in
        the meantime (the tx arrived via another peer's event) are
        skipped. Accepted transactions are never dropped here, so a
        requeue may transiently push pending above the admission cap."""
        with self._lock:
            for tx in reversed(txs):
                h = sha256(tx)
                self._inflight.pop(h, None)
                # back to pending: BOTH timestamps survive — the client
                # has been waiting the whole time, and keeping the
                # first-drain stamp makes the consensus stage cover
                # first drain → commit (requeue interludes included)
                # without re-observing mempool_wait on the next drain
                if self._committed is not None and self._committed.peek(h)[1]:
                    self._admit_ts.pop(h, None)
                    self._drain_ts.pop(h, None)
                    continue
                if h in self._pending:
                    continue
                self._pending[h] = tx
                self._pending.move_to_end(h, last=False)
                self._pending_bytes += len(tx)
                self.requeued += 1

    # -- commit feed --------------------------------------------------------

    def mark_committed(self, txs) -> None:
        """Record committed transaction hashes (called from the node's
        commit path, under THIS lock — atomically with the pending/
        in-flight cleanup — so a client retry racing the commit can
        never be admitted a second time). Pending copies of a now-
        committed transaction (submitted to several nodes, committed via
        another's event) are dropped before they can double-commit."""
        with self._lock:
            now = self._clock() if self._lat_commit is not None else 0.0
            for tx in txs:
                h = sha256(bytes(tx))
                self.committed_total += 1
                if self._committed is not None:
                    self._committed.add(h, True)
                self._inflight.pop(h, None)
                old = self._pending.pop(h, None)
                if old is not None:
                    self._pending_bytes -= len(old)
                    self.commit_drops += 1
                ts = self._admit_ts.pop(h, None)
                dts = self._drain_ts.pop(h, None)
                if ts is not None:
                    # end-to-end north-star latency: admit → block commit
                    # (only for txs THIS node admitted; gossip-received
                    # txs have no local admit time)
                    self._lat_commit.observe(now - ts)
                    if dts is not None:
                        self._lat_consensus.observe(now - dts)

    # -- views --------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def pending_count(self) -> int:
        return len(self._pending)

    @property
    def pending_bytes(self) -> int:
        return self._pending_bytes

    def pending_txs(self) -> List[bytes]:
        """Snapshot of pending transaction bytes in FIFO order."""
        with self._lock:
            return list(self._pending.values())

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "pending_bytes": self._pending_bytes,
                "in_flight": len(self._inflight),
                "submitted": self.submitted,
                "accepted": self.accepted,
                "rejected_full": self.rejected_full,
                "rejected_dup": self.rejected_dup,
                "rejected_oversized": self.rejected_oversized,
                "rejected_throttled": self.rejected_throttled,
                "committed_dedup_hits": self.committed_dedup_hits,
                "evictions": self.evictions,
                "requeued": self.requeued,
                "commit_drops": self.commit_drops,
                "committed_total": self.committed_total,
                "inflight_aged": self.inflight_aged,
            }

    def config(self) -> Dict[str, object]:
        return {
            "max_txs": self.max_txs,
            "max_bytes": self.max_bytes,
            "overflow": self.overflow,
            "event_max_txs": self.event_max_txs,
            "event_max_bytes": self.event_max_bytes,
            "committed_lru": (
                self._committed.size if self._committed is not None else 0
            ),
            "rate_tx_s": self._bucket.rate if self._bucket is not None else 0.0,
            "burst": self._bucket.burst if self._bucket is not None else 0.0,
        }
