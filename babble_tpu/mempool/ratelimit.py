"""Token-bucket rate limiter for mempool admission.

Same idiom as ``common/backoff.py``: one small shared primitive with its
nondeterminism injected (there the RNG, here the clock), so tests drive
it with a seeded/fake clock and get byte-identical verdict sequences.
"""

from __future__ import annotations

import time
from typing import Callable


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``.

    NOT internally locked — the owning ``Mempool`` already serializes
    admission under its own lock, and a second lock here would only add
    contention on the submit hot path.
    """

    def __init__(
        self,
        rate: float,
        burst: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate <= 0:
            raise ValueError("TokenBucket rate must be positive")
        self.rate = float(rate)
        # default burst of one second's worth of tokens (at least 1): a
        # client that paces exactly at the rate never sees `throttled`,
        # only a sustained overshoot does
        self.burst = float(burst) if burst > 0 else max(self.rate, 1.0)
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()

    def try_acquire(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; never blocks."""
        now = self._clock()
        if now > self._last:
            self._tokens = min(
                self.burst, self._tokens + (now - self._last) * self.rate
            )
        self._last = now
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False
