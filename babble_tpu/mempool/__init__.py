"""Mempool subsystem: the bounded, deduplicating transaction pool that
owns every client transaction between app submission and self-event
creation (docs/mempool.md).

Hashgraph itself has no admission story — the reference drains an
unbounded submit channel into an unbounded slice. This package supplies
the missing layer: capacity caps in count and bytes, duplicate
suppression against both pending entries and recently-committed hashes,
FIFO batch drain with per-self-event caps, a token-bucket rate limiter,
and an explicit admission verdict plumbed end-to-end.
"""

from .mempool import (
    ACCEPTED,
    ALREADY_COMMITTED,
    DUPLICATE,
    FULL,
    Mempool,
    OVERSIZED,
    THROTTLED,
    VERDICTS,
)
from .ratelimit import TokenBucket

__all__ = [
    "Mempool",
    "TokenBucket",
    "ACCEPTED",
    "DUPLICATE",
    "ALREADY_COMMITTED",
    "FULL",
    "THROTTLED",
    "OVERSIZED",
    "VERDICTS",
]
