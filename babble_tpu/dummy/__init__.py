"""Reference "chat" application used by tests and the demo
(reference: src/dummy/)."""

from .state import State

__all__ = ["State"]
