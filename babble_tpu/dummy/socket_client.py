"""Dummy socket client: the chat-app state served over the socket proxy
pair, for running the app in a separate process from the node
(reference: /root/reference/src/dummy/socket_dummy.go:13-60)."""

from __future__ import annotations

from ..proxy.socket_proxy import SocketBabbleProxy
from .state import State


class DummySocketClient:
    """App process: dummy State behind a SocketBabbleProxy."""

    def __init__(self, bind_addr: str, babble_addr: str):
        self.state = State()
        self.proxy = SocketBabbleProxy(bind_addr, babble_addr, self.state)
        self.addr = self.proxy.addr

    def submit_tx(self, tx: bytes) -> str:
        """Submit a transaction; returns the node's admission verdict
        ("accepted" | "duplicate" | "already_committed" | "full" |
        "throttled" | "oversized" — docs/mempool.md) so clients like
        demo/bombard.py can back off and report shed rates."""
        return self.proxy.submit_tx(tx)

    def close(self) -> None:
        self.proxy.close()
