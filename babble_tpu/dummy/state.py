"""Dummy application state: a chat app that chains transaction hashes.

Reference semantics: src/dummy/state.go:19-126 — the state hash is the
iterated two-hash combination of all committed transactions; snapshots are
the state hash recorded per block index; all internal transactions are
accepted.
"""

from __future__ import annotations

from typing import Dict, List

from ..crypto.hashing import sha256, simple_hash_from_two_hashes
from ..hashgraph.block import Block
from ..proxy.proxy import CommitResponse


class State:
    """ProxyHandler implementation (reference: dummy/state.go:19-34)."""

    def __init__(self) -> None:
        self.committed_txs: List[bytes] = []
        self.state_hash: bytes = b""
        self.snapshots: Dict[int, bytes] = {}
        self.babble_state = None

    def commit_handler(self, block: Block) -> CommitResponse:
        """Apply the block: append txs, chain the state hash, snapshot, and
        accept all internal transactions (reference: dummy/state.go:49-98)."""
        txs = block.transactions()
        self.committed_txs.extend(txs)

        h = self.state_hash
        for tx in txs:
            h = simple_hash_from_two_hashes(h, sha256(tx))
        self.state_hash = h

        self.snapshots[block.index()] = h

        receipts = [it.as_accepted() for it in block.internal_transactions()]
        return CommitResponse(state_hash=self.state_hash, receipts=receipts)

    def snapshot_handler(self, block_index: int) -> bytes:
        """reference: dummy/state.go:101-112."""
        if block_index not in self.snapshots:
            raise KeyError(f"snapshot {block_index} not found")
        return self.snapshots[block_index]

    def restore_handler(self, snapshot: bytes) -> bytes:
        """reference: dummy/state.go:115-121."""
        self.state_hash = snapshot
        return self.state_hash

    def state_change_handler(self, state) -> None:
        """reference: dummy/state.go:124-127."""
        self.babble_state = state
