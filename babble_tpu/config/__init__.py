"""Node configuration (reference: src/config/)."""

from .config import Config

__all__ = ["Config"]
