"""Configuration for a node, with the reference's defaults.

Reference semantics: src/config/config.go:34-56 (defaults),
config/config.go:58-197 (fields), config/config.go:287-308 (datadir
conventions). Durations are seconds (float) rather than Go durations.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field


DEFAULT_KEYFILE = "priv_key"
DEFAULT_BADGER_DIR = "badger_db"
DEFAULT_PEERS_FILE = "peers.json"
DEFAULT_GENESIS_PEERS_FILE = "peers.genesis.json"

# Sentry defaults — the single source of truth, shared by the Config
# fields below and Sentry.__init__ (node/sentry.py) so a Core built
# without an injected sentry can't drift from the configured tuning.
DEFAULT_SENTRY_THRESHOLD = 8.0
DEFAULT_SENTRY_QUARANTINE_S = 30.0
DEFAULT_SENTRY_DECAY_HALFLIFE_S = 30.0

# Causal-tracing / flight-recorder defaults — single source of truth,
# shared by the Config fields below, ProvenanceTable (obs/provenance.py)
# and StallWatchdog (obs/flight.py) so standalone cores and bare
# watchdogs can't drift from the configured tuning.
DEFAULT_TRACE_SAMPLE = 1.0 / 64.0
DEFAULT_TRACE_TABLE_CAP = 4096
DEFAULT_WATCHDOG_STALL_S = 10.0
DEFAULT_WATCHDOG_INTERVAL_S = 1.0

# Always-on sampling profiler (obs/profile.py): thread-stack samples per
# second, bucketed into the stage taxonomy and served as collapsed
# stacks at GET /profile. 0 disables; BABBLE_PROFILE_HZ overrides a
# whole cluster; BABBLE_OBS=0 disables regardless.
DEFAULT_PROFILE_HZ = 50.0

# Lifecycle tier defaults (docs/lifecycle.md) — single source of truth,
# shared by the Config fields below and CheckpointPruner so a pruner
# built outside a node can't drift from the configured cadence.
DEFAULT_PRUNE_EVERY_ROUNDS = 0  # 0 = compaction off (append-only store)
DEFAULT_PRUNE_KEEP_ROUNDS = 2
DEFAULT_PRUNE_VACUUM = True


def default_data_dir() -> str:
    """~/.babble equivalent (reference: config/config.go:287-297)."""
    return os.path.join(os.path.expanduser("~"), ".babble_tpu")


@dataclass
class Config:
    """Node configuration (reference: config/config.go:58-197)."""

    data_dir: str = field(default_factory=default_data_dir)
    log_level: str = "info"
    # Structured JSON log lines (obs/log.py): one object per line with
    # ts/level/logger/msg + node correlation fields. Plain text when off.
    log_json: bool = False

    bind_addr: str = "127.0.0.1:1337"
    advertise_addr: str = ""
    service_addr: str = "127.0.0.1:8000"
    no_service: bool = False

    heartbeat_timeout: float = 0.010  # 10 ms busy gossip cadence
    slow_heartbeat_timeout: float = 1.0  # idle gossip cadence
    tcp_timeout: float = 1.0
    join_timeout: float = 10.0

    max_pool: int = 2
    cache_size: int = 10000
    sync_limit: int = 1000
    suspend_limit: int = 100

    # Async gossip engine (docs/gossip.md): "async" builds the
    # event-driven selector transport (net/atcp.py — multiplexed
    # connections, binary framed codec, per-connection version
    # negotiation so JSON peers interoperate); "tcp" keeps the
    # thread-per-connection fallback (net/tcp.py).
    transport: str = "tcp"
    # Inbound-sync pipeline (node/pipeline.py): concurrent decode +
    # batch-verify stages feeding one serialized inserter through a
    # bounded queue (depth = backpressure threshold). Auto-disabled
    # under an injected sim clock (determinism). With the pipeline on,
    # the gossip PULL leg stages through the same queue, so a slow
    # insert never blocks the next pull round-trip.
    gossip_pipeline: bool = True
    gossip_pipeline_depth: int = 64

    # Adaptive gossip scheduler (node/adaptive.py, docs/gossip.md
    # §Adaptive scheduling): sync frequency, fan-out, and pipeline soft
    # depth driven by live load signals (mempool pressure, per-peer lag,
    # pipeline congestion), clamped to [heartbeat_timeout,
    # slow_heartbeat_timeout] x [1, gossip_max_fanout]. BABBLE_ADAPT=0
    # (env, cluster-wide) or adaptive_gossip=false falls back to the
    # reference's fixed two-speed timer, bit for bit. selfevent_burst
    # caps the extra self-events coalesced per tick while the mempool
    # still holds a full event's worth of transactions (0 = reference's
    # one-event-per-tick shape).
    adaptive_gossip: bool = True
    # Fan-out ceiling: 2 measured best on both the in-process 4-node
    # cluster (one GIL: 3 partners/tick thrashes the scheduler) and
    # within noise of 3 on the 8-node multi-process A/B; raise it on
    # hosts with real per-node parallelism.
    gossip_max_fanout: int = 2
    selfevent_burst: int = 4

    # Resilience knobs (docs/robustness.md): total budget for the
    # catching-up node's fast-forward poll loop (each pass polls every
    # peer; transient failures retry with exponential backoff until the
    # deadline), and the cap on the joining node's retry backoff.
    fast_forward_deadline: float = 5.0
    join_backoff_cap: float = 2.0

    # Signal/relay mode (the reference's WebRTC+WAMP analogue,
    # config/config.go:163-187): nodes keep one outbound connection to a
    # rendezvous server and are addressed by public key, so NAT-ed nodes
    # can participate without accepting inbound connections.
    signal: bool = False
    signal_addr: str = "127.0.0.1:2443"
    # Direct-connection upgrade listen address for signal mode (e.g.
    # "0.0.0.0:0"); empty = gossip stays relayed (pre-upgrade behavior).
    signal_direct: str = ""
    # Pinned relay TLS certificate (PEM). Defaults to datadir/cert.pem when
    # present (the reference's cert convention, config/config.go:19-32);
    # empty = plaintext relay link.
    signal_ca: str = ""

    # Mempool (docs/mempool.md): the bounded dedup transaction pool
    # between app submission and self-event creation. Caps are admission
    # bounds in count and bytes; the overflow policy is "reject" (client
    # sees `full`) or "evict-oldest" (oldest pending tx shed, client
    # accepted); event caps bound each self-event so gossip payloads stay
    # small under load; the committed LRU turns retries of committed
    # transactions into `already_committed`; rate>0 arms a token-bucket
    # limiter (`throttled` under sustained overload; burst 0 = 1 s worth).
    mempool_max_txs: int = 20000
    mempool_max_bytes: int = 33554432  # 32 MiB
    mempool_overflow: str = "reject"  # or "evict-oldest"
    mempool_event_max_txs: int = 1024
    mempool_event_max_bytes: int = 1048576  # 1 MiB per self-event
    mempool_committed_lru: int = 65536
    mempool_rate: float = 0.0  # tx/s; 0 disables the limiter
    mempool_burst: float = 0.0  # 0 = one second's worth of tokens
    # Submit-queue drain batch per background pass: bounded so a flood of
    # submissions can't starve transport RPC handling in the same loop.
    submit_batch: int = 256

    # Sentry (docs/robustness.md §Byzantine fault model): classified
    # ingest rejections add per-cause weights to the sender's misbehavior
    # score; crossing `threshold` triggers a `quarantine_s` time-box
    # (selector skips the peer, inbound syncs refused), after which the
    # peer is re-admitted with a clean score. Scores decay with half-life
    # `decay_halflife_s`, so only sustained abuse accumulates.
    sentry_threshold: float = DEFAULT_SENTRY_THRESHOLD
    sentry_quarantine_s: float = DEFAULT_SENTRY_QUARANTINE_S
    sentry_decay_halflife_s: float = DEFAULT_SENTRY_DECAY_HALFLIFE_S

    # Causal tracing + stall flight recorder (docs/observability.md
    # §Causal tracing): trace_sample is the deterministic per-transaction
    # sampling rate for the commit-provenance table (every node traces
    # the SAME transactions; 1.0 = trace everything, 0 = off; env
    # BABBLE_TRACE_SAMPLE overrides for a whole cluster at once);
    # trace_table_cap bounds records per node. watchdog_stall_s is the
    # no-progress-while-busy threshold that trips the flight recorder
    # (0 disables); artifacts land in flight_dir (default:
    # <tmpdir>/babble_tpu_flight). BABBLE_OBS=0 disables all of it.
    trace_sample: float = DEFAULT_TRACE_SAMPLE
    trace_table_cap: int = DEFAULT_TRACE_TABLE_CAP
    watchdog_stall_s: float = DEFAULT_WATCHDOG_STALL_S
    watchdog_interval_s: float = DEFAULT_WATCHDOG_INTERVAL_S
    flight_dir: str = ""
    # Sampling-profiler rate (obs/profile.py; docs/observability.md
    # §Sampling profiler). One process-wide sampler serves co-located
    # nodes; 0 disables, env BABBLE_PROFILE_HZ overrides cluster-wide.
    profile_hz: float = DEFAULT_PROFILE_HZ

    # Light-client gateway tier (docs/clients.md): client_listen binds
    # the SubscriptionHub (streaming commit subscriptions over one
    # selector loop; empty = off). Per-subscriber frame queues are
    # bounded (sub_queue_frames); a subscriber that stalls with queued
    # data for sub_stall_timeout_s, or whose delivery deficit grows past
    # sub_shed_lag blocks, is shed. txindex_cap bounds the txid→block
    # proof index behind GET /proof/<txid>.
    client_listen: str = ""
    sub_queue_frames: int = 256
    sub_stall_timeout_s: float = 10.0
    sub_shed_lag: int = 1024
    # kernel send-buffer cap per subscriber socket (0 = OS default);
    # small values make slow-consumer shedding prompt and deterministic
    sub_sndbuf: int = 0
    # proof-index bound: ~64-byte hex key + coords per entry; 256k
    # entries ≈ tens of MB. Indexing runs only when the node has a read
    # surface (service or client_listen).
    txindex_cap: int = 1 << 18

    # Lifecycle tier (docs/lifecycle.md): checkpoint-prune compaction.
    # Every prune_every_rounds of anchor advance, the node seals its
    # anchor checkpoint and compacts events/rounds/frames below
    # (anchor - prune_keep_rounds) out of the store; prune_vacuum hands
    # the freed SQLite pages back to the OS after each prune. 0 keeps
    # the store append-only (the reference's behavior).
    prune_every_rounds: int = DEFAULT_PRUNE_EVERY_ROUNDS
    prune_keep_rounds: int = DEFAULT_PRUNE_KEEP_ROUNDS
    prune_vacuum: bool = DEFAULT_PRUNE_VACUUM

    enable_fast_sync: bool = False
    store: bool = False  # persistent store (SQLite-backed) vs in-memory
    database_dir: str = ""
    bootstrap: bool = False
    maintenance_mode: bool = False
    moniker: str = ""

    # Time source (common/clock.py): every node-side deadline, sleep,
    # duration measurement, and event timestamp reads through this
    # object. None -> the process wall clock. The deterministic
    # simulation engine (babble_tpu.sim, docs/simulation.md) injects a
    # SimClock here so whole fault scenarios run in virtual time.
    # lint: allow(knobs: runtime injection point, not an operator knob)
    clock: object = None
    # Seed for the node's internal RNG streams (peer-selector pick
    # weighting). None -> OS entropy (production). The sim harness sets
    # it so gossip partner choice is a pure function of the master seed.
    # lint: allow(knobs: runtime injection point, not an operator knob)
    sim_seed: object = None

    # TPU acceleration: route batch verification and the DAG consensus
    # sweeps through the JAX kernels in babble_tpu.ops.
    accelerator: bool = False
    # Multi-chip consensus: shard the voting sweeps over this many devices
    # (jax.sharding.Mesh; 0 = single device). Only meaningful with
    # --accelerator; resolved after the device probe in Node.init.
    accelerator_mesh: int = 0

    def __post_init__(self) -> None:
        if self.clock is None:
            from ..common.clock import WALL

            self.clock = WALL
        # Cluster-wide sampling override without touching every node's
        # flags/toml — sampling must agree across nodes for hop merges.
        env_sample = os.environ.get("BABBLE_TRACE_SAMPLE")
        if env_sample:
            try:
                self.trace_sample = float(env_sample)
            except ValueError:
                pass
        # Adaptive-scheduler kill switch: one env var flips a whole
        # cluster back to the fixed two-speed timer (A/B benches, and
        # the operator escape hatch if the control law misbehaves).
        env_adapt = os.environ.get("BABBLE_ADAPT")
        if env_adapt:
            self.adaptive_gossip = env_adapt.lower() not in (
                "0", "false", "off", "no",
            )
        if self.gossip_max_fanout < 1:
            raise ValueError(
                f"gossip_max_fanout must be >= 1, got {self.gossip_max_fanout}"
            )
        if not self.database_dir:
            self.database_dir = os.path.join(self.data_dir, DEFAULT_BADGER_DIR)
        # Option forcing (reference: babble/babble.go:133-143):
        # maintenance-mode implies bootstrap, bootstrap implies store.
        if self.maintenance_mode:
            self.bootstrap = True
        if self.bootstrap:
            self.store = True
        if self.transport not in ("tcp", "async"):
            raise ValueError(
                f"transport must be 'tcp' or 'async', got {self.transport!r}"
            )
        if self.mempool_overflow not in ("reject", "evict-oldest"):
            raise ValueError(
                f"mempool_overflow must be 'reject' or 'evict-oldest', "
                f"got {self.mempool_overflow!r}"
            )
        if self.prune_every_rounds < 0 or self.prune_keep_rounds < 0:
            raise ValueError(
                "prune_every_rounds and prune_keep_rounds must be >= 0"
            )

    def seeded_rng(self, stream: str, ident) -> object:
        """Per-actor, per-stream ``random.Random`` derived from the master
        sim seed, or None in production (``sim_seed`` unset) — call sites
        fall back to the process-global random module. The seed string
        ``"{sim_seed}|{stream}|{ident}"`` is a determinism contract: every
        actor (honest node.py, adversary byzantine.py) must derive a given
        stream through THIS helper so same-seed replays stay reproducible."""
        if self.sim_seed is None:
            return None
        import random

        return random.Random(f"{self.sim_seed}|{stream}|{ident}")

    def keyfile_path(self) -> str:
        return os.path.join(self.data_dir, DEFAULT_KEYFILE)

    def peers_path(self) -> str:
        return os.path.join(self.data_dir, DEFAULT_PEERS_FILE)

    def genesis_peers_path(self) -> str:
        return os.path.join(self.data_dir, DEFAULT_GENESIS_PEERS_FILE)

    def logger(self, name: str = "babble_tpu") -> logging.Logger:
        """Per-component logger. Handlers/formatting are centralized in
        obs/log.py (``obs.log.configure_from(conf)`` — the CLI entry
        points call it); this only scopes the name and level."""
        if not name.startswith("babble_tpu"):
            # scope every component under the framework root so the one
            # obs/log handler (and level) covers them all
            name = f"babble_tpu.{name}"
        logger = logging.getLogger(f"{name}.{self.moniker or 'node'}")
        logger.setLevel(getattr(logging, self.log_level.upper(), logging.INFO))
        return logger
