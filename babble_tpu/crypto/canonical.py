"""Canonical, byte-stable serialization for consensus objects.

The reference hashes canonical JSON of event/round/frame bodies (ugorji codec
with Canonical=true, reference: roundInfo.go:127-149, event.go:57-64). We use
our own deterministic convention — sorted keys, no whitespace, bytes as
base64 — which is stable across nodes (what consensus actually requires), not
wire-compatible with Go.
"""

from __future__ import annotations

import base64
import json
from typing import Any


class CacheStats:
    """Hit/miss tally for a serialization memo. Process-wide (co-located
    nodes share it); increments race benignly under the GIL — a stats
    counter may drop an update, never corrupt."""

    __slots__ = ("hits", "misses")

    def __init__(self) -> None:
        self.hits = 0
        self.misses = 0

    def snapshot(self) -> dict:
        return {"hits": self.hits, "misses": self.misses}


#: memo_normalized() effectiveness — how often an event body / wire event
#: re-serialization was avoided (gossip replies, frame re-encodes).
NORM_CACHE = CacheStats()


class PreNormalized:
    """Wrapper marking a value as ALREADY normalized (b64 applied, plain
    str/int/dict/list all the way down). _normalize passes it through
    untouched — the hook that lets hot senders (event push paths) memoize
    an object's normalized form instead of re-walking it per send."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value


def memo_normalized(holder: Any, build) -> Any:
    """Shared memo for normalized() encoders (wire events, event bodies):
    compute _normalize(build()) once and cache it on ``holder._norm``.
    Callers must invalidate by setting ``holder._norm = None`` when the
    underlying object mutates."""
    n = getattr(holder, "_norm", None)
    if n is None:
        NORM_CACHE.misses += 1
        n = _normalize(build())
        holder._norm = n
    else:
        NORM_CACHE.hits += 1
    return n


def _normalize(obj: Any) -> Any:
    # exact-type fast path ordered by frequency (leaves dominate): this
    # walk runs for every event hash on the insert hot path. Subclasses
    # (IntEnum, OrderedDict, namedtuple, ...) miss the fast path and fall
    # through to the original isinstance chain below, keeping their old
    # semantics.
    t = type(obj)
    if t is str or t is int:
        return obj
    if t is PreNormalized:
        return obj.value
    if t is bytes or t is bytearray:
        return base64.b64encode(bytes(obj)).decode("ascii")
    if t is dict:
        return {str(k): _normalize(v) for k, v in obj.items()}
    if t is list or t is tuple:
        return [_normalize(v) for v in obj]
    if t is bool or obj is None:
        return obj
    if isinstance(obj, (bytes, bytearray)):
        return base64.b64encode(bytes(obj)).decode("ascii")
    if isinstance(obj, dict):
        return {str(k): _normalize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_normalize(v) for v in obj]
    if isinstance(obj, (str, int, bool)):
        return obj
    raise TypeError(f"non-canonical type {type(obj)!r} in consensus object")


def canonical_dumps(obj: Any) -> bytes:
    """Deterministic JSON bytes: sorted keys, compact separators, base64 bytes.

    Floats are rejected (consensus must not contain floats — SURVEY.md §7
    hard part 4)."""
    return json.dumps(
        _normalize(obj), sort_keys=True, separators=(",", ":"), allow_nan=False
    ).encode("utf-8")


def canonical_loads(data: bytes) -> Any:
    return json.loads(data.decode("utf-8"))


def jsonable(obj: Any) -> Any:
    """Canonical-normalize (bytes → b64, sorted keys) into plain JSON
    types — the one helper behind every HTTP payload and evidence record
    that must round-trip through json.dumps."""
    return json.loads(canonical_dumps(obj))


def b64(data: bytes) -> str:
    return base64.b64encode(data).decode("ascii")


def unb64(s: str) -> bytes:
    return base64.b64decode(s)
