"""Key objects and consensus-visible encodings (reference: src/crypto/keys/).

- Signature string format: ``r.Text(36) + "|" + s.Text(36)`` — base-36,
  lowercase, no padding (reference: keys/signature.go:25-38). This format is
  consensus-visible: it rides in events/blocks and its decoded R value is the
  ordering tiebreak (event.go:503-511), so it must be exact.
- Validator ID: 32-bit FNV-1a over the uncompressed public key
  (reference: keys/public_key.go:32-46), collision risk acknowledged there.

Verification prefers the OpenSSL backend (``cryptography``) when importable,
falling back to pure Python. Batched verification for the TPU path lives in
``babble_tpu.ops.verify``.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass
from typing import Tuple

from babble_tpu.crypto import secp256k1 as curve

_B36_ALPHABET = "0123456789abcdefghijklmnopqrstuvwxyz"
_B36_INDEX = {c: i for i, c in enumerate(_B36_ALPHABET)}

# FNV-1a 32-bit parameters.
_FNV_OFFSET = 0x811C9DC5
_FNV_PRIME = 0x01000193

try:  # OpenSSL fast path
    from cryptography.hazmat.primitives.asymmetric import ec as _ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature as _decode_dss,
        encode_dss_signature as _encode_dss,
    )
    from cryptography.hazmat.primitives import hashes as _hashes
    from cryptography.hazmat.primitives.asymmetric.utils import Prehashed as _Prehashed
    from cryptography.exceptions import InvalidSignature as _InvalidSignature
    from functools import lru_cache as _lru_cache

    _HAVE_OPENSSL = True

    @_lru_cache(maxsize=1024)
    def _openssl_pub(x: int, y: int):
        return _ec.EllipticCurvePublicNumbers(x, y, _ec.SECP256K1()).public_key()

    @_lru_cache(maxsize=64)
    def _openssl_priv(d: int):
        pub_x, pub_y = curve.pubkey_from_scalar(d)
        return _ec.EllipticCurvePrivateNumbers(
            d, _ec.EllipticCurvePublicNumbers(pub_x, pub_y, _ec.SECP256K1())
        ).private_key()

except Exception:  # pragma: no cover - cryptography is in the base image
    _HAVE_OPENSSL = False


def _int_to_b36(x: int) -> str:
    if x == 0:
        return "0"
    neg = x < 0
    x = abs(x)
    out = []
    while x:
        x, rem = divmod(x, 36)
        out.append(_B36_ALPHABET[rem])
    if neg:
        out.append("-")
    return "".join(reversed(out))


def _b36_to_int(s: str) -> int:
    s = s.strip().lower()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    if not s:
        raise ValueError("empty base36 string")
    x = 0
    for c in s:
        if c not in _B36_INDEX:
            raise ValueError(f"invalid base36 digit {c!r}")
        x = x * 36 + _B36_INDEX[c]
    return -x if neg else x


def encode_signature(r: int, s: int) -> str:
    """reference: keys/signature.go:25-30."""
    return f"{_int_to_b36(r)}|{_int_to_b36(s)}"


def decode_signature(sig: str) -> Tuple[int, int]:
    """reference: keys/signature.go:33-38."""
    parts = sig.split("|")
    if len(parts) != 2:
        raise ValueError(f"invalid signature (expected 2 values, got {len(parts)})")
    return _b36_to_int(parts[0]), _b36_to_int(parts[1])


def public_key_id(pub_bytes: bytes) -> int:
    """32-bit FNV-1a of the uncompressed pubkey (reference: keys/public_key.go:36)."""
    h = _FNV_OFFSET
    for b in pub_bytes:
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFF
    return h


@dataclass(frozen=True)
class PublicKey:
    x: int
    y: int

    def bytes(self) -> bytes:
        return curve.marshal_pubkey((self.x, self.y))

    def hex(self) -> str:
        """Uppercase 0X-prefixed hex, as rendered by the reference
        (keys/public_key.go, fmt %X convention used in peers.json)."""
        return "0X" + self.bytes().hex().upper()

    def id(self) -> int:
        return public_key_id(self.bytes())

    def verify(self, msg_hash: bytes, sig: str) -> bool:
        try:
            r, s = decode_signature(sig)
        except ValueError:
            return False
        return self.verify_rs(msg_hash, r, s)

    def verify_rs(self, msg_hash: bytes, r: int, s: int) -> bool:
        from babble_tpu import native_crypto

        res = native_crypto.verify_one(
            self.x.to_bytes(32, "big") + self.y.to_bytes(32, "big"),
            msg_hash,
            r,
            s,
        )
        if res is not None:
            return res
        if _HAVE_OPENSSL:
            try:
                pub = _openssl_pub(self.x, self.y)
                pub.verify(
                    _encode_dss(r, s), msg_hash, _ec.ECDSA(_Prehashed(_hashes.SHA256()))
                )
                return True
            except _InvalidSignature:
                return False
            except Exception:
                pass  # fall through to pure python on backend errors
        return curve.verify((self.x, self.y), msg_hash, r, s)

    @staticmethod
    def from_bytes(data: bytes) -> "PublicKey":
        x, y = curve.unmarshal_pubkey(data)
        return PublicKey(x, y)

    @staticmethod
    def from_hex(s: str) -> "PublicKey":
        t = s[2:] if s[:2].upper() == "0X" else s
        return PublicKey.from_bytes(bytes.fromhex(t))


from functools import lru_cache as _pk_lru_cache


@_pk_lru_cache(maxsize=1024)
def _pubkey_of_scalar(d: int) -> "PublicKey":
    """One scalar multiplication per key per process — Block.sign and
    friends access .public_key on every signature."""
    from babble_tpu import native_crypto

    try:
        xy = native_crypto.pubkey(d.to_bytes(32, "big"))
    except Exception:
        xy = None
    if xy is None:
        xy = curve.pubkey_from_scalar(d)
    return PublicKey(*xy)


@dataclass(frozen=True)
class PrivateKey:
    d: int

    @property
    def public_key(self) -> PublicKey:
        return _pubkey_of_scalar(self.d)

    def sign(self, msg_hash: bytes) -> str:
        r, s = self.sign_rs(msg_hash)
        return encode_signature(r, s)

    def sign_rs(self, msg_hash: bytes) -> Tuple[int, int]:
        # Signing touches the private key, so constant-time OpenSSL stays
        # preferred; the variable-time native signer is only a fallback
        # (verification is secret-free and uses native first).
        # EXCEPT under deterministic mode (the sim engine): OpenSSL draws
        # a random ECDSA nonce, and the consensus total order breaks
        # Lamport-timestamp ties on the signature's r value — so random
        # nonces would make two same-seed sim runs commit in different
        # orders. The native and pure-Python signers are RFC 6979.
        if _HAVE_OPENSSL and not _DETERMINISTIC_SIGNING:
            try:
                der = _openssl_priv(self.d).sign(
                    msg_hash, _ec.ECDSA(_Prehashed(_hashes.SHA256()))
                )
                return _decode_dss(der)
            except Exception:
                pass  # fall through on backend errors
        from babble_tpu import native_crypto

        try:
            rs = native_crypto.sign(self.d.to_bytes(32, "big"), msg_hash)
        except ValueError:
            rs = None
        if rs is not None:
            return rs
        return curve.sign(self.d, msg_hash)

    def bytes(self) -> bytes:
        return self.d.to_bytes(32, "big")

    def hex(self) -> str:
        return self.bytes().hex()

    @staticmethod
    def from_bytes(data: bytes) -> "PrivateKey":
        d = int.from_bytes(data, "big")
        if not (1 <= d < curve.N):
            raise ValueError("private scalar out of range")
        return PrivateKey(d)

    @staticmethod
    def from_hex(s: str) -> "PrivateKey":
        return PrivateKey.from_bytes(bytes.fromhex(s.strip()))


# Process-wide switch: when True, sign_rs skips the randomized-nonce
# OpenSSL path and uses the RFC 6979 deterministic signers (native C++,
# else pure Python). The sim engine flips this on so signatures — and
# therefore the signature-r consensus tie-break — are pure functions of
# (key, message), which byte-identical replay requires.
_DETERMINISTIC_SIGNING = False


def set_deterministic_signing(on: bool) -> bool:
    """Toggle RFC 6979-only signing; returns the previous setting."""
    global _DETERMINISTIC_SIGNING
    prev = _DETERMINISTIC_SIGNING
    _DETERMINISTIC_SIGNING = bool(on)
    return prev


def generate_key() -> PrivateKey:
    """reference: keys/private_key.go:21 (GenerateECDSAKey)."""
    while True:
        d = secrets.randbelow(curve.N)
        if d >= 1:
            return PrivateKey(d)
