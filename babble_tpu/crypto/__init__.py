"""Crypto (reference: src/crypto/, src/crypto/keys/).

SHA-256 hashing, canonical serialization, secp256k1 ECDSA keys with the
reference's consensus-visible formats:

- signature encoding is ``r.Text(36) + "|" + s.Text(36)`` (base-36)
  (reference: keys/signature.go:25-38);
- the validator ID is the 32-bit FNV-1a hash of the uncompressed public key
  (reference: keys/public_key.go:32-46).

Signing is deterministic (RFC 6979), so events are reproducible; verification
has three tiers: pure-Python (always available), OpenSSL via ``cryptography``
(fast host path), and the batched JAX kernel in ``babble_tpu.ops.verify``
(TPU path).
"""

from babble_tpu.crypto.hashing import sha256, simple_hash_from_two_hashes
from babble_tpu.crypto.keys import (
    PrivateKey,
    PublicKey,
    decode_signature,
    encode_signature,
    generate_key,
    public_key_id,
)
from babble_tpu.crypto.keyfile import SimpleKeyfile

__all__ = [
    "PrivateKey",
    "PublicKey",
    "SimpleKeyfile",
    "decode_signature",
    "encode_signature",
    "generate_key",
    "public_key_id",
    "sha256",
    "simple_hash_from_two_hashes",
]
