"""Hashing (reference: src/crypto/hash.go)."""

from __future__ import annotations

import hashlib


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def simple_hash_from_two_hashes(left: bytes, right: bytes) -> bytes:
    """SHA256(left || right) — used to chain-hash peer sets
    (reference: crypto/hash.go:17, peers/peer_set.go:104-115)."""
    h = hashlib.sha256()
    h.update(left)
    h.update(right)
    return h.digest()
