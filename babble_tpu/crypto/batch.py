"""Host-side batch signature prevalidation via the native C++ library.

The gossip sync path hands every decoded chunk of incoming events here;
one foreign call verifies all creator + internal-transaction signatures
and caches verdicts on the events, making the per-event ``Event.verify()``
in the insert path a cache hit. This mirrors the accelerator-side
``babble_tpu.ops.verify.prevalidate_events`` (which shares the collector
below) but runs on the host CPU — the default fast path when no TPU batch
kernel is configured.

Reference hot loop being replaced: per-event secp256k1 verification at
insert (src/hashgraph/hashgraph.go:672-687 -> src/crypto/keys/signature.go:20).
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from babble_tpu import native_crypto
from babble_tpu.common.lru import LRU
from babble_tpu.crypto import secp256k1 as ref
from babble_tpu.crypto.canonical import CacheStats
from babble_tpu.crypto.keys import decode_signature

# ((x, y), msg_hash, r, s)
SigItem = Tuple[Tuple[int, int], bytes, int, int]
# (event, first_item_index, item_count, statically_ok)
SigSpan = Tuple[object, int, int, bool]

# Process-wide verdict cache: a signature's validity is a pure function
# of (pubkey, msg_hash, r, s), so events that arrive again — pushed by a
# second peer, replayed by an adversary, re-decoded after a chaos retry
# — skip the native verify entirely. Per-Event verdict caching
# (Event._sig_ok) cannot catch these: every wire decode builds a fresh
# Event object. Bounded LRU; the lock covers concurrent gossip threads.
VERIFY_CACHE = CacheStats()
_VERDICTS = LRU(32768)
_VERDICTS_LOCK = threading.Lock()


def available() -> bool:
    return native_crypto.available()


def collect_signature_items(events) -> Tuple[List[SigItem], List[SigSpan]]:
    """Gather every verifiable signature of a list of Events: the creator
    signature plus one per internal transaction. Structurally invalid
    items (undecodable signature / malformed key) mark the whole event
    statically failed, same as the scalar verify path. Shared by the host
    (native C++) and accelerator (JAX) batch verifiers so what counts as a
    consensus-relevant signature can never diverge between them."""
    items: List[SigItem] = []
    spans: List[SigSpan] = []
    for ev in events:
        start = len(items)
        ok_static = True
        try:
            pub = ref.unmarshal_pubkey(ev.body.creator)
            r, s = decode_signature(ev.signature)
            items.append((pub, ev.hash(), r, s))
        except Exception:
            ok_static = False
        if ok_static:
            for itx in ev.body.internal_transactions:
                try:
                    ipub = ref.unmarshal_pubkey(
                        itx.body.peer.public_key().bytes()
                    )
                    ir, is_ = decode_signature(itx.signature)
                    items.append((ipub, itx.body.hash(), ir, is_))
                except Exception:
                    ok_static = False
                    break
        spans.append((ev, start, len(items) - start, ok_static))
    return items, spans


def prevalidate_events_host(events) -> bool:
    """Batch-verify signatures for a list of Events in one native call.

    Returns False (leaving events untouched, so the scalar path runs)
    when the native library is unavailable.
    """
    items, spans = collect_signature_items(events)
    verdicts: List[Optional[bool]] = []
    fresh: List[int] = []
    with _VERDICTS_LOCK:
        for it in items:
            v, ok = _VERDICTS.get(it)
            if ok:
                VERIFY_CACHE.hits += 1
                verdicts.append(v)
            else:
                VERIFY_CACHE.misses += 1
                verdicts.append(None)
                fresh.append(len(verdicts) - 1)
    if fresh:
        pubs = []
        msgs = []
        rss = []
        for i in fresh:
            (x, y), m, r, s = items[i]
            pubs.append(x.to_bytes(32, "big") + y.to_bytes(32, "big"))
            msgs.append(m)
            rss.append((r, s))
        results = native_crypto.verify_batch(pubs, msgs, rss)
        if results is None:
            return False
        with _VERDICTS_LOCK:
            for i, ok in zip(fresh, results):
                verdicts[i] = bool(ok)
                _VERDICTS.add(items[i], bool(ok))
    for ev, start, count, ok_static in spans:
        ok = ok_static and all(verdicts[start : start + count])
        ev.prevalidate(ok)
    return True
