"""Host-side batch signature prevalidation via the native C++ library.

The gossip sync path hands every decoded chunk of incoming events here;
one foreign call verifies all creator + internal-transaction signatures
and caches verdicts on the events, making the per-event ``Event.verify()``
in the insert path a cache hit. This mirrors the accelerator-side
``babble_tpu.ops.verify.prevalidate_events`` (which shares the collector
below) but runs on the host CPU — the default fast path when no TPU batch
kernel is configured.

Reference hot loop being replaced: per-event secp256k1 verification at
insert (src/hashgraph/hashgraph.go:672-687 -> src/crypto/keys/signature.go:20).
"""

from __future__ import annotations

from typing import List, Tuple

from babble_tpu import native_crypto
from babble_tpu.crypto import secp256k1 as ref
from babble_tpu.crypto.keys import decode_signature

# ((x, y), msg_hash, r, s)
SigItem = Tuple[Tuple[int, int], bytes, int, int]
# (event, first_item_index, item_count, statically_ok)
SigSpan = Tuple[object, int, int, bool]


def available() -> bool:
    return native_crypto.available()


def collect_signature_items(events) -> Tuple[List[SigItem], List[SigSpan]]:
    """Gather every verifiable signature of a list of Events: the creator
    signature plus one per internal transaction. Structurally invalid
    items (undecodable signature / malformed key) mark the whole event
    statically failed, same as the scalar verify path. Shared by the host
    (native C++) and accelerator (JAX) batch verifiers so what counts as a
    consensus-relevant signature can never diverge between them."""
    items: List[SigItem] = []
    spans: List[SigSpan] = []
    for ev in events:
        start = len(items)
        ok_static = True
        try:
            pub = ref.unmarshal_pubkey(ev.body.creator)
            r, s = decode_signature(ev.signature)
            items.append((pub, ev.hash(), r, s))
        except Exception:
            ok_static = False
        if ok_static:
            for itx in ev.body.internal_transactions:
                try:
                    ipub = ref.unmarshal_pubkey(
                        itx.body.peer.public_key().bytes()
                    )
                    ir, is_ = decode_signature(itx.signature)
                    items.append((ipub, itx.body.hash(), ir, is_))
                except Exception:
                    ok_static = False
                    break
        spans.append((ev, start, len(items) - start, ok_static))
    return items, spans


def prevalidate_events_host(events) -> bool:
    """Batch-verify signatures for a list of Events in one native call.

    Returns False (leaving events untouched, so the scalar path runs)
    when the native library is unavailable.
    """
    items, spans = collect_signature_items(events)
    pubs = [
        x.to_bytes(32, "big") + y.to_bytes(32, "big") for (x, y), _, _, _ in items
    ]
    msgs = [m for _, m, _, _ in items]
    rss = [(r, s) for _, _, r, s in items]

    results = native_crypto.verify_batch(pubs, msgs, rss)
    if results is None:
        return False
    for ev, start, count, ok_static in spans:
        ok = ok_static and all(results[start : start + count])
        ev.prevalidate(ok)
    return True
