"""Binary Merkle tree over a block's transaction list.

The light-client gateway (docs/clients.md) serves *inclusion proofs*:
a stateless client holding only the validator set can check that one
transaction is inside a committed block without downloading the block.
That requires validators to sign something that commits to the
transactions through a Merkle root instead of the raw list — see
``BlockBody.tx_root`` (hashgraph/block.py) and the parity note in
docs/parity.md.

Construction is RFC 6962-style (Certificate Transparency):

- leaf  = sha256(0x00 || tx)
- inner = sha256(0x01 || left || right)
- an odd node at the end of a level is *promoted* unchanged (never
  duplicated — duplication lets two different leaf lists share a root,
  the classic CVE-2012-2459 mutation), and the leaf count is part of
  the signed header anyway (``TxCount``) so tree shape is pinned.
- the empty tree hashes to sha256(b"") — a constant that can never
  collide with a leaf or inner node, both of which hash prefixed input.

An audit path is the sibling hash at each level from the leaf to the
root, each tagged with which side the sibling sits on.
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence, Tuple

_LEAF_PREFIX = b"\x00"
_NODE_PREFIX = b"\x01"

#: root of the empty tree (no transactions in the block)
EMPTY_ROOT = hashlib.sha256(b"").digest()


def leaf_hash(tx: bytes) -> bytes:
    return hashlib.sha256(_LEAF_PREFIX + tx).digest()


def node_hash(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(_NODE_PREFIX + left + right).digest()


def merkle_root(txs: Sequence[bytes]) -> bytes:
    """Root over the transaction list (order-sensitive)."""
    if not txs:
        return EMPTY_ROOT
    level = [leaf_hash(t) for t in txs]
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(node_hash(level[i], level[i + 1]))
        if len(level) % 2:  # odd tail promotes unchanged
            nxt.append(level[-1])
        level = nxt
    return level[0]


def merkle_path(txs: Sequence[bytes], index: int) -> List[Tuple[bytes, bool]]:
    """Audit path for ``txs[index]``: [(sibling_hash, sibling_is_right),
    ...] from leaf level to just below the root."""
    if not 0 <= index < len(txs):
        raise IndexError(f"leaf index {index} out of range 0..{len(txs) - 1}")
    level = [leaf_hash(t) for t in txs]
    pos = index
    path: List[Tuple[bytes, bool]] = []
    while len(level) > 1:
        sib = pos ^ 1
        if sib < len(level):
            path.append((level[sib], sib > pos))
        # else: odd tail promoted — no sibling at this level
        nxt = []
        for i in range(0, len(level) - 1, 2):
            nxt.append(node_hash(level[i], level[i + 1]))
        if len(level) % 2:
            nxt.append(level[-1])
        level = nxt
        pos //= 2
    return path


def verify_path(
    tx: bytes, index: int, count: int, path: Sequence[Tuple[bytes, bool]],
    root: bytes,
) -> bool:
    """Recompute the root from one transaction and its audit path.

    ``count`` is the signed leaf count (``TxCount``): it bounds the path
    length and pins the position walk, so a path valid for one (index,
    count) cannot be replayed for another tree shape."""
    if count <= 0 or not 0 <= index < count:
        return False
    # expected path length: one sibling per level where we have one
    expect = 0
    pos, n = index, count
    while n > 1:
        if (pos ^ 1) < n:
            expect += 1
        pos //= 2
        n = (n + 1) // 2
    if len(path) != expect:
        return False
    h = leaf_hash(tx)
    pos, n = index, count
    i = 0
    while n > 1:
        if (pos ^ 1) < n:
            sib, right = path[i]
            i += 1
            if not isinstance(sib, (bytes, bytearray)) or len(sib) != 32:
                return False
            # the sibling's side is DERIVED from the position walk, never
            # trusted from the path — a flag that contradicts the claimed
            # index is a forgery attempt (a left/right swap can re-root a
            # path onto a different leaf position)
            if bool(right) != (pos % 2 == 0):
                return False
            h = node_hash(h, bytes(sib)) if right else node_hash(bytes(sib), h)
        pos //= 2
        n = (n + 1) // 2
    return h == root
