"""SimpleKeyfile — hex private key on disk (reference: keys/key_reader_writer.go:21)."""

from __future__ import annotations

import os

from babble_tpu.crypto.keys import PrivateKey


class SimpleKeyfile:
    def __init__(self, path: str):
        self.path = path

    def read_key(self) -> PrivateKey:
        with open(self.path, "r", encoding="utf-8") as f:
            return PrivateKey.from_hex(f.read())

    def write_key(self, key: PrivateKey) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(key.hex())
