"""Pure-Python secp256k1 ECDSA (reference curve: src/crypto/keys/curve.go:20).

This is the portable reference implementation and the oracle for the batched
JAX verifier (babble_tpu/ops/verify.py). Affine arithmetic with modular
inversion via pow(x, -1, p) (extended Euclid in CPython, fast enough for the
host path); deterministic nonces per RFC 6979 so signing is reproducible.

Hot-path verification should go through babble_tpu.crypto.keys, which prefers
the OpenSSL backend when available and the TPU batch verifier for bulk work.
"""

from __future__ import annotations

import hashlib
import hmac
from typing import Optional, Tuple

# Curve parameters: y^2 = x^3 + 7 over F_p.
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
A = 0
B = 7
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

Point = Optional[Tuple[int, int]]  # None is the point at infinity

G: Point = (GX, GY)


def is_on_curve(pt: Point) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B) % P == 0


def point_add(p1: Point, p2: Point) -> Point:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        # doubling
        lam = (3 * x1 * x1) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def point_mul(k: int, pt: Point) -> Point:
    """Double-and-add scalar multiplication (not constant-time; fine for a
    consensus testbed — the secret-key path uses RFC6979 nonces and short
    lived processes; production signing should use the OpenSSL backend)."""
    k %= N
    result: Point = None
    addend = pt
    while k:
        if k & 1:
            result = point_add(result, addend)
        addend = point_add(addend, addend)
        k >>= 1
    return result


def scalar_base_mult(k: int) -> Point:
    return point_mul(k, G)


def pubkey_from_scalar(d: int) -> Tuple[int, int]:
    pt = scalar_base_mult(d)
    assert pt is not None
    return pt


def _bits2int(data: bytes) -> int:
    """Leftmost min(len*8, 256) bits as integer (RFC 6979 / ECDSA hash truncation)."""
    x = int.from_bytes(data, "big")
    excess = len(data) * 8 - 256
    if excess > 0:
        x >>= excess
    return x


def rfc6979_k(priv: int, msg_hash: bytes) -> int:
    """Deterministic nonce per RFC 6979 with HMAC-SHA256."""
    qlen = 32
    h1 = _bits2int(msg_hash) % N
    x_b = priv.to_bytes(qlen, "big")
    h1_b = h1.to_bytes(qlen, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + x_b + h1_b, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x_b + h1_b, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = _bits2int(v)
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(priv: int, msg_hash: bytes) -> Tuple[int, int]:
    """ECDSA sign; returns (r, s). Low-s normalization is NOT applied, matching
    Go's crypto/ecdsa which the reference uses (keys/signature.go:13-18)."""
    e = _bits2int(msg_hash)
    while True:
        k = rfc6979_k(priv, msg_hash)
        pt = scalar_base_mult(k)
        assert pt is not None
        r = pt[0] % N
        if r == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        s = (pow(k, -1, N) * (e + r * priv)) % N
        if s == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        return r, s


def verify(pub: Tuple[int, int], msg_hash: bytes, r: int, s: int) -> bool:
    """ECDSA verify against an affine public key."""
    if not (1 <= r < N and 1 <= s < N):
        return False
    if not is_on_curve(pub):
        return False
    e = _bits2int(msg_hash)
    w = pow(s, -1, N)
    u1 = (e * w) % N
    u2 = (r * w) % N
    pt = point_add(point_mul(u1, G), point_mul(u2, pub))
    if pt is None:
        return False
    return pt[0] % N == r % N


# --- SEC1 encodings -------------------------------------------------------

def marshal_pubkey(pub: Tuple[int, int]) -> bytes:
    """Uncompressed SEC1: 0x04 || X || Y (matches Go elliptic.Marshal, which
    the reference feeds to FNV for validator IDs — keys/public_key.go:32-46)."""
    x, y = pub
    return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")


def unmarshal_pubkey(data: bytes) -> Tuple[int, int]:
    if len(data) != 65 or data[0] != 0x04:
        raise ValueError("bad uncompressed secp256k1 public key")
    x = int.from_bytes(data[1:33], "big")
    y = int.from_bytes(data[33:65], "big")
    pt = (x, y)
    if not is_on_curve(pt):
        raise ValueError("public key not on curve")
    return pt
