"""Engine errors (reference: src/hashgraph/errors.go:1-32).

Every rejection on the sync/ingest path raises a typed error carrying a
``cause`` slug so the node's sentry (node/sentry.py) can classify
misbehavior without string-matching messages. The slugs are stable — they
become per-cause counters in ``get_stats`` and keys in the sentry's
scoring table (docs/robustness.md §Byzantine fault model).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Optional

if TYPE_CHECKING:  # pragma: no cover - typing only
    from babble_tpu.hashgraph.event import Event


class HashgraphError(Exception):
    """Base for classified ingest rejections. ``cause`` is the stable
    classification slug consumed by the sentry."""

    cause = "hashgraph"


class SelfParentError(HashgraphError):
    """Raised when an event's self-parent is not the creator's last known
    event. ``normal=True`` marks the benign concurrent-duplicate-insert race
    that must be tolerated, not reported (reference: errors.go:3-32)."""

    cause = "self_parent"

    def __init__(self, msg: str, normal: bool):
        super().__init__(msg)
        self.normal = normal


class InvalidSignatureError(HashgraphError, ValueError):
    """The event's creator signature (or an internal transaction's
    signature) does not verify — a forged or wrong-key event. Replaces the
    bare ValueError the insert path used to raise (still a ValueError for
    callers predating the typed hierarchy), so the sentry can score
    wrong-key floods without parsing messages.

    Carries the rejected event when the raiser has it: a signature
    failure is ambiguous after an observed fork (an honest event whose
    parent hash resolves to the OTHER branch on this node re-hashes
    differently and fails verification through no fault of the sender),
    and the sentry uses the event's parent creator-ids to recognize that
    case before scoring."""

    cause = "invalid_signature"

    def __init__(self, msg: str, event: Optional["Event"] = None):
        super().__init__(msg)
        self.event = event


class UnknownParticipantError(HashgraphError, ValueError):
    """A wire event references a creator id absent from the repertoire —
    either garbage or a peer lying about membership. Subclasses ValueError
    for compatibility with callers that predate the typed hierarchy."""

    cause = "unknown_creator"


class UnknownParentError(HashgraphError, ValueError):
    """The event's other-parent is not in the store (an out-of-order or
    fabricated reference)."""

    cause = "unknown_parent"


class ForkError(HashgraphError):
    """Equivocation: a *signed* event arrived at an already-occupied
    (creator, index) slot with a different hash. Both branches are
    cryptographically attributable to the creator — the pair IS the
    evidence (Baird 2016 §forks; the accountability line of work à la
    BFT forensics records exactly such signed conflict pairs).

    Carries both events so the sentry can mint a durable
    :class:`~babble_tpu.node.sentry.EquivocationProof` before the insert
    is refused. ``existing`` is the locally stored branch, ``incoming``
    the rejected one; ``incoming``'s signature was verified before this
    was raised (insert_event checks signatures first)."""

    cause = "fork"

    def __init__(
        self,
        creator: str,
        index: int,
        existing: Optional["Event"],
        incoming: "Event",
    ):
        super().__init__(
            f"fork detected: creator {creator[:16]}… already has a "
            f"different event at index {index}"
        )
        self.creator = creator
        self.index = index
        self.existing = existing
        self.incoming = incoming


def is_normal_self_parent_error(err: object) -> bool:
    return isinstance(err, SelfParentError) and err.normal


def classify_rejection(err: object) -> Optional[str]:
    """Map an exception from the sync/ingest path to its misbehavior
    cause slug, or None when the failure is not attributable to the peer
    (local store trouble, benign duplicate races, transport errors).

    SelfParentError is never attributed: normal=True is the benign
    concurrent-duplicate race, and normal=False wraps a LOCAL store
    error from last_event_from — blaming the sender for the receiver's
    own store trouble would let a DB fault quarantine honest peers."""
    if isinstance(err, SelfParentError):
        return None
    if isinstance(err, HashgraphError):
        return err.cause
    return None
