"""Engine errors (reference: src/hashgraph/errors.go:1-32)."""

from __future__ import annotations


class SelfParentError(Exception):
    """Raised when an event's self-parent is not the creator's last known
    event. ``normal=True`` marks the benign concurrent-duplicate-insert race
    that must be tolerated, not reported (reference: errors.go:3-32)."""

    def __init__(self, msg: str, normal: bool):
        super().__init__(msg)
        self.normal = normal


def is_normal_self_parent_error(err: object) -> bool:
    return isinstance(err, SelfParentError) and err.normal
