"""Consensus-layer caches (reference: src/hashgraph/caches.go:30-345)."""

from __future__ import annotations

from typing import Dict, List, Optional

from babble_tpu.common.errors import StoreError, StoreErrorKind
from babble_tpu.common.rolling_index_map import RollingIndexMap
from babble_tpu.hashgraph.event import BlockSignature
from babble_tpu.peers.peer import Peer
from babble_tpu.peers.peer_set import PeerSet

INT32_MAX = 2**31 - 1


class ParticipantEventsCache:
    """Per-peer rolling index of event hashes (reference: caches.go:32-123)."""

    def __init__(self, size: int):
        self.participants = PeerSet([])
        self.rim = RollingIndexMap("ParticipantEvents", size)

    def add_peer(self, peer: Peer) -> None:
        self.participants = self.participants.with_new_peer(peer)
        self.rim.add_key(peer.id)

    def _participant_id(self, participant: str) -> int:
        """Participant keys are case-insensitive pubkey hex
        (reference: caches.go:54-62)."""
        p = self.participants.by_pub_key.get(participant.upper())
        if p is None:
            raise StoreError(
                "ParticipantEvents",
                StoreErrorKind.UNKNOWN_PARTICIPANT,
                participant.upper(),
            )
        return p.id

    def get(self, participant: str, skip_index: int) -> List[str]:
        return list(self.rim.get(self._participant_id(participant), skip_index))

    def get_item(self, participant: str, index: int) -> str:
        return self.rim.get_item(self._participant_id(participant), index)

    def get_last(self, participant: str) -> str:
        return self.rim.get_last(self._participant_id(participant))

    def set(self, participant: str, hash_: str, index: int) -> None:
        self.rim.set(self._participant_id(participant), hash_, index)

    def known(self) -> Dict[int, int]:
        """participant id => last known index."""
        return self.rim.known()


class PeerSetCache:
    """Round-interval lookup of peer-sets + the repertoire of all peers ever
    seen (reference: caches.go:126-222)."""

    def __init__(self) -> None:
        self.rounds: List[int] = []
        self.peer_sets: Dict[int, PeerSet] = {}
        self.repertoire_by_pub_key: Dict[str, Peer] = {}
        self.repertoire_by_id: Dict[int, Peer] = {}
        self.first_rounds: Dict[int, int] = {}

    def set(self, round: int, peer_set: PeerSet) -> None:
        if round in self.peer_sets:
            raise StoreError(
                "PeerSetCache", StoreErrorKind.KEY_ALREADY_EXISTS, str(round)
            )
        self.peer_sets[round] = peer_set
        self.rounds.append(round)
        self.rounds.sort()
        for p in peer_set.peers:
            self.repertoire_by_pub_key[p.pub_key_hex] = p
            self.repertoire_by_id[p.id] = p
            fr = self.first_rounds.get(p.id)
            if fr is None or fr > round:
                self.first_rounds[p.id] = round

    def get(self, round: int) -> PeerSet:
        """The peer-set effective at `round`: the entry at the largest
        recorded round <= `round` (reference: caches.go:169-193)."""
        ps = self.peer_sets.get(round)
        if ps is not None:
            return ps
        if not self.rounds:
            raise StoreError("PeerSetCache", StoreErrorKind.KEY_NOT_FOUND, str(round))
        if round < self.rounds[0]:
            return self.peer_sets[self.rounds[0]]
        for i in range(len(self.rounds) - 1):
            if self.rounds[i] <= round < self.rounds[i + 1]:
                return self.peer_sets[self.rounds[i]]
        return self.peer_sets[self.rounds[-1]]

    def get_all(self) -> Dict[int, List[Peer]]:
        return {r: self.peer_sets[r].peers for r in self.rounds}

    def first_round(self, id_: int) -> tuple[int, bool]:
        fr = self.first_rounds.get(id_)
        if fr is not None:
            return fr, True
        return INT32_MAX, False


class PendingRound:
    """A round making its way through consensus (reference: caches.go:225-228)."""

    __slots__ = ("index", "decided")

    def __init__(self, index: int, decided: bool = False):
        self.index = index
        self.decided = decided


class PendingRoundsCache:
    """Ordered queue of undecided rounds (reference: caches.go:244-297)."""

    def __init__(self) -> None:
        self.items: Dict[int, PendingRound] = {}
        self.sorted_items: List[PendingRound] = []

    def queued(self, round: int) -> bool:
        return round in self.items

    def set(self, pending_round: PendingRound) -> None:
        self.items[pending_round.index] = pending_round
        self.sorted_items.append(pending_round)
        self.sorted_items.sort(key=lambda pr: pr.index)

    def get_ordered_pending_rounds(self) -> List[PendingRound]:
        return self.sorted_items

    def update(self, decided_rounds: List[int]) -> None:
        for drn in decided_rounds:
            pr = self.items.get(drn)
            if pr is not None:
                pr.decided = True

    def clean(self, processed_rounds: List[int]) -> None:
        for pr in processed_rounds:
            self.items.pop(pr, None)
        self.sorted_items = sorted(self.items.values(), key=lambda p: p.index)


class SigPool:
    """Pool of block signatures awaiting processing (reference: caches.go:300-345)."""

    def __init__(self) -> None:
        self.items: Dict[str, BlockSignature] = {}

    def add(self, bs: BlockSignature) -> None:
        self.items[bs.key()] = bs

    def remove(self, key: str) -> None:
        self.items.pop(key, None)

    def remove_slice(self, sigs: List[BlockSignature]) -> None:
        for s in sigs:
            self.items.pop(s.key(), None)

    def __len__(self) -> int:
        return len(self.items)

    def slice(self) -> List[BlockSignature]:
        return list(self.items.values())
