"""PersistentStore — write-through durable store over SQLite.

The tpu-native equivalent of the reference's BadgerStore
(/root/reference/src/hashgraph/badger_store.go:28-100): an InmemStore
LRU cache in front, with every event/round/block/frame/peer-set written
through to an embedded KV (SQLite, stdlib — this image ships no badger).
Reads fall back to the DB on cache miss or rolling-index eviction
(TooLate), mirroring badger_store.go:293-310.

Bootstrap (`--bootstrap`) replays the whole DB topologically through
consensus to rebuild in-memory state — "WE CAN ONLY BOOTSTRAP FROM 0"
(reference: hashgraph.go:1481-1536); Hashgraph.bootstrap drives it via
``topological_events`` and flips ``set_maintenance_mode`` so the replay
doesn't rewrite the DB.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Dict, List, Optional

from babble_tpu.common.errors import StoreError, StoreErrorKind
from babble_tpu.crypto.canonical import canonical_dumps, canonical_loads
from babble_tpu.hashgraph.block import Block
from babble_tpu.hashgraph.event import Event, EventBody
from babble_tpu.hashgraph.frame import Frame, Root
from babble_tpu.hashgraph.round_info import RoundInfo
from babble_tpu.hashgraph.store import InmemStore
from babble_tpu.peers.peer import Peer
from babble_tpu.peers.peer_set import PeerSet

_SCHEMA = """
CREATE TABLE IF NOT EXISTS events (
    key TEXT PRIMARY KEY, topo INTEGER NOT NULL, data TEXT NOT NULL);
CREATE INDEX IF NOT EXISTS events_topo ON events(topo);
CREATE TABLE IF NOT EXISTS participant_events (
    participant TEXT NOT NULL, idx INTEGER NOT NULL, hash TEXT NOT NULL,
    PRIMARY KEY (participant, idx));
CREATE TABLE IF NOT EXISTS rounds (idx INTEGER PRIMARY KEY, data TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS blocks (idx INTEGER PRIMARY KEY, data TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS frames (round INTEGER PRIMARY KEY, data TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS peer_sets (round INTEGER PRIMARY KEY, data TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS roots (participant TEXT PRIMARY KEY, data TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS evidence (key TEXT PRIMARY KEY, data TEXT NOT NULL);
"""


class PersistentStore:
    """Write-through store: InmemStore cache + SQLite persistence."""

    def __init__(self, cache_size: int = 10000, path: str = "babble.db"):
        self._path = path
        self._inmem = InmemStore(cache_size)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db_lock = threading.Lock()
        with self._db_lock:
            # Declared BEFORE the first table exists so a fresh DB gets
            # incremental vacuum (checkpoint-prune frees pages back to the
            # OS without a full rebuild). On a pre-existing DB this is a
            # no-op until a full VACUUM — vacuum(incremental=False) covers
            # that upgrade path.
            self._db.execute("PRAGMA auto_vacuum=INCREMENTAL")
            self._db.executescript(_SCHEMA)
            self._db.execute("PRAGMA journal_mode=WAL")
            self._db.execute("PRAGMA synchronous=NORMAL")
            row = self._db.execute("SELECT MAX(topo) FROM events").fetchone()
        self._next_topo = (row[0] + 1) if row and row[0] is not None else 0
        # maintenanceMode disables DB writes during bootstrap replay
        # (reference: badger_store.go:848-855)
        self._maintenance = False
        # NOTE: persisted peer-sets are deliberately NOT preloaded into the
        # interval cache. The reference's design comment
        # (badger_store.go:109-118) applies verbatim: membership state must
        # be reconstructed by replaying events through consensus
        # (Bootstrap), which re-registers each peer-set at its effective
        # round — preloading would make that replay collide with
        # KEY_ALREADY_EXISTS. db_peer_set() exposes the raw rows.

    # -- maintenance --------------------------------------------------------

    def set_maintenance_mode(self, on: bool) -> None:
        self._maintenance = on

    # -- passthroughs to the cache -----------------------------------------

    def cache_size(self) -> int:
        return self._inmem.cache_size()

    def get_all_peer_sets(self) -> Dict[int, List[Peer]]:
        return self._inmem.get_all_peer_sets()

    def first_round(self, participant_id: int):
        return self._inmem.first_round(participant_id)

    def repertoire_by_pub_key(self) -> Dict[str, Peer]:
        return self._inmem.repertoire_by_pub_key()

    def repertoire_by_id(self) -> Dict[int, Peer]:
        return self._inmem.repertoire_by_id()

    def known_events(self) -> Dict[int, int]:
        return self._inmem.known_events()

    def consensus_events(self) -> List[str]:
        return self._inmem.consensus_events()

    def consensus_events_count(self) -> int:
        return self._inmem.consensus_events_count()

    def add_consensus_event(self, event: Event) -> None:
        self._inmem.add_consensus_event(event)

    def last_event_from(self, participant: str) -> str:
        return self._inmem.last_event_from(participant)

    def last_consensus_event_from(self, participant: str) -> str:
        return self._inmem.last_consensus_event_from(participant)

    def last_round(self) -> int:
        return self._inmem.last_round()

    def last_block_index(self) -> int:
        return self._inmem.last_block_index()

    def round_witnesses(self, round_index: int) -> List[str]:
        try:
            return self.get_round(round_index).witnesses()
        except StoreError:
            return []

    def round_events(self, round_index: int) -> int:
        try:
            return len(self.get_round(round_index).created_events)
        except StoreError:
            return 0

    def get_root(self, participant: str) -> Root:
        try:
            return self._inmem.get_root(participant)
        except StoreError:
            row = self._fetch(
                "SELECT data FROM roots WHERE participant = ?", (participant,)
            )
            if row is None:
                raise
            return Root.from_dict(json.loads(row[0]))

    # -- peer sets (write-through) -----------------------------------------

    def get_peer_set(self, round: int) -> PeerSet:
        return self._inmem.get_peer_set(round)

    def set_peer_set(self, round: int, peer_set: PeerSet) -> None:
        self._inmem.set_peer_set(round, peer_set)
        self._write(
            "INSERT OR REPLACE INTO peer_sets (round, data) VALUES (?, ?)",
            (round, canonical_dumps([p.to_dict() for p in peer_set.peers]).decode()),
        )

    # -- events -------------------------------------------------------------

    def get_event(self, hash_: str) -> Event:
        try:
            return self._inmem.get_event(hash_)
        except StoreError:
            row = self._fetch("SELECT data FROM events WHERE key = ?", (hash_,))
            if row is None:
                raise
            return _event_from_json(row[0])

    def set_event(self, event: Event) -> None:
        # DB first, memory second: an event must be DURABLE before it can
        # become visible to gossip. A silently dropped disk write during the
        # shutdown race let a node gossip an event, lose it at close, then
        # re-sign a different event at the same index after bootstrap — a
        # cross-incarnation self-fork that wedges every peer still holding
        # the first incarnation's event (observed as the recycle tests'
        # "invalid event signature" livelock). Failing the insert instead
        # keeps the event out of this node's head chain entirely.
        if self._maintenance:
            self._inmem.set_event(event)
            return
        fresh = self._persist_event(event)
        try:
            self._inmem.set_event(event)
        except BaseException:
            if fresh:
                # the cache rejected an event the DB just gained (e.g. a
                # trusted frame-event insert hitting an index gap): roll
                # the fresh rows back so the next incarnation's bootstrap
                # never replays an event this one refused. Pre-existing
                # rows (annotation re-sets) are left untouched.
                self._unpersist_event(event)
            raise

    def _persist_event(self, event: Event) -> bool:
        """Write through to the DB; returns True when the rows are new
        (vs. a re-set of an already-durable event)."""
        key = event.hex()
        from babble_tpu.crypto.canonical import PreNormalized

        # memoized body form: byte-identical stored JSON, reusing the
        # normalization the insert-path hash already paid for
        d = {
            "Body": PreNormalized(event.body.normalized()),
            "Signature": event.signature,
        }
        # Consensus annotations (write-once once assigned) ride along so a
        # cache-evicted event reloads with its round/lamport intact —
        # after compaction the recursive recomputation may no longer have
        # the parents to rebuild them from. Bootstrap replay strips them
        # (topological_events) so the from-zero recompute stays pristine.
        if event.round is not None:
            d["Round"] = event.round
        if event.lamport_timestamp is not None:
            d["Lamport"] = event.lamport_timestamp
        if event.round_received is not None:
            d["RoundReceived"] = event.round_received
        with self._db_lock:
            if self._db is None:
                raise StoreError(
                    "PersistentStore", StoreErrorKind.CLOSED, key
                )
            cur = self._db.execute("SELECT topo FROM events WHERE key = ?", (key,))
            row = cur.fetchone()
            topo = row[0] if row else self._next_topo
            if row is None:
                self._next_topo += 1
                self._db.execute(
                    "INSERT OR REPLACE INTO participant_events "
                    "(participant, idx, hash) VALUES (?, ?, ?)",
                    (event.creator(), event.index(), key),
                )
            self._db.execute(
                "INSERT OR REPLACE INTO events (key, topo, data) VALUES (?, ?, ?)",
                (key, topo, canonical_dumps(d).decode()),
            )
            self._db.commit()
            return row is None

    def _unpersist_event(self, event: Event) -> None:
        key = event.hex()
        with self._db_lock:
            if self._db is None:
                return
            self._db.execute("DELETE FROM events WHERE key = ?", (key,))
            self._db.execute(
                "DELETE FROM participant_events WHERE participant = ? "
                "AND idx = ? AND hash = ?",
                (event.creator(), event.index(), key),
            )
            self._db.commit()

    def participant_events(self, participant: str, skip: int) -> List[str]:
        try:
            return self._inmem.participant_events(participant, skip)
        except StoreError as err:
            if err.kind != StoreErrorKind.TOO_LATE:
                raise
            with self._db_lock:
                if self._db is None:
                    raise err  # shutdown race: surface the original miss
                rows = self._db.execute(
                    "SELECT hash FROM participant_events "
                    "WHERE participant = ? AND idx > ? ORDER BY idx",
                    (participant, skip),
                ).fetchall()
            return [r[0] for r in rows]

    def participant_event(self, participant: str, index: int) -> str:
        """Cache first; DB fallback on eviction (badger_store.go:293-310)."""
        try:
            return self._inmem.participant_event(participant, index)
        except StoreError:
            row = self._fetch(
                "SELECT hash FROM participant_events "
                "WHERE participant = ? AND idx = ?",
                (participant, index),
            )
            if row is None:
                raise
            return row[0]

    # -- rounds -------------------------------------------------------------

    def get_round(self, round_index: int) -> RoundInfo:
        try:
            return self._inmem.get_round(round_index)
        except StoreError:
            row = self._fetch(
                "SELECT data FROM rounds WHERE idx = ?", (round_index,)
            )
            if row is None:
                raise
            return RoundInfo.from_dict(json.loads(row[0]))

    def set_round(self, round_index: int, round_info: RoundInfo) -> None:
        self._inmem.set_round(round_index, round_info)
        self._write(
            "INSERT OR REPLACE INTO rounds (idx, data) VALUES (?, ?)",
            (round_index, canonical_dumps(round_info.to_dict()).decode()),
        )

    # -- blocks -------------------------------------------------------------

    def get_block(self, index: int) -> Block:
        try:
            return self._inmem.get_block(index)
        except StoreError:
            row = self._fetch("SELECT data FROM blocks WHERE idx = ?", (index,))
            if row is None:
                raise
            return Block.from_dict(json.loads(row[0]))

    def set_block(self, block: Block) -> None:
        self._inmem.set_block(block)
        self._write(
            "INSERT OR REPLACE INTO blocks (idx, data) VALUES (?, ?)",
            (block.index(), canonical_dumps(block.to_dict()).decode()),
        )

    # -- frames -------------------------------------------------------------

    def get_frame(self, round_received: int) -> Frame:
        try:
            return self._inmem.get_frame(round_received)
        except StoreError:
            row = self._fetch(
                "SELECT data FROM frames WHERE round = ?", (round_received,)
            )
            if row is None:
                raise
            return Frame.from_dict(json.loads(row[0]))

    def set_frame(self, frame: Frame) -> None:
        self._inmem.set_frame(frame)
        self._write(
            "INSERT OR REPLACE INTO frames (round, data) VALUES (?, ?)",
            (frame.round, canonical_dumps(frame.to_dict()).decode()),
        )

    # -- bootstrap support ---------------------------------------------------

    def topological_events(self, skip: int, count: int) -> List[Event]:
        """Events in insert order, for bootstrap replay
        (reference: badger_store.go dbTopologicalEvents / hashgraph.go:1481)."""
        with self._db_lock:
            if self._db is None:
                return []  # shutdown race: nothing left to replay
            rows = self._db.execute(
                "SELECT data FROM events ORDER BY topo LIMIT ? OFFSET ?",
                (count, skip),
            ).fetchall()
        return [_event_from_json(r[0], annotated=False) for r in rows]

    def db_peer_set(self, round: int) -> PeerSet:
        """The persisted peer-set registered at EXACTLY this round (raw DB
        row, no interval semantics — reference: badger_store.go
        dbGetPeerSet). Bootstrap replay, not this accessor, rebuilds the
        live interval cache."""
        row = self._fetch(
            "SELECT data FROM peer_sets WHERE round = ?", (round,)
        )
        if row is None:
            raise StoreError(
                "PeerSetDB", StoreErrorKind.KEY_NOT_FOUND, str(round)
            )
        return PeerSet(
            [Peer.from_dict(d) for d in canonical_loads(row[0].encode())]
        )

    def db_last_block_index(self) -> int:
        row = self._fetch("SELECT MAX(idx) FROM blocks", ())
        return row[0] if row and row[0] is not None else -1

    # -- evidence ------------------------------------------------------------

    def set_evidence(self, key: str, data: dict) -> None:
        """Durable misbehavior evidence (equivocation proofs): written
        through even in maintenance mode — evidence is NOT derived state
        that a bootstrap replay rebuilds, so the replay's write gate
        (which protects events/rounds/blocks from being re-written) must
        not silently drop a proof recorded while it is open."""
        self._inmem.set_evidence(key, data)
        with self._db_lock:
            if self._db is None:
                raise StoreError(
                    "PersistentStore", StoreErrorKind.CLOSED, "evidence"
                )
            self._db.execute(
                "INSERT OR REPLACE INTO evidence (key, data) VALUES (?, ?)",
                (key, canonical_dumps(data).decode()),
            )
            self._db.commit()

    def all_evidence(self) -> Dict[str, dict]:
        with self._db_lock:
            if self._db is None:
                return self._inmem.all_evidence()
            rows = self._db.execute("SELECT key, data FROM evidence").fetchall()
        out = dict(self._inmem.all_evidence())
        for key, data in rows:
            out[key] = json.loads(data)
        return out

    # -- lifecycle -----------------------------------------------------------

    def reset(self, frame: Frame) -> None:
        """Reset the cache from a frame; the DB keeps accumulating (the
        reference's badger Reset also only clears the in-memory half)."""
        self._inmem.reset(frame)
        for participant, root in frame.roots.items():
            self._write(
                "INSERT OR REPLACE INTO roots (participant, data) VALUES (?, ?)",
                (participant, canonical_dumps(root.to_dict()).decode()),
            )
        self.set_frame(frame)

    # -- compaction ----------------------------------------------------------

    def prune_below(
        self,
        floor_round: int,
        drop_events: List[str],
        drop_rounds: List[int],
        participant_floors: Dict[str, int],
    ) -> None:
        """Durable half of checkpoint-prune: delete the compacted rows.
        Blocks, peer-sets, roots and evidence are never touched — evidence
        in particular is NOT replay-derived state (see set_evidence) and
        must survive compaction."""
        self._inmem.prune_below(
            floor_round, drop_events, drop_rounds, participant_floors
        )
        with self._db_lock:
            if self._db is None:
                raise StoreError(
                    "PersistentStore", StoreErrorKind.CLOSED, "prune"
                )
            self._db.executemany(
                "DELETE FROM events WHERE key = ?",
                [(h,) for h in drop_events],
            )
            self._db.executemany(
                "DELETE FROM rounds WHERE idx = ?",
                [(r,) for r in drop_rounds],
            )
            self._db.execute(
                "DELETE FROM frames WHERE round < ?", (floor_round,)
            )
            for participant, floor in participant_floors.items():
                self._db.execute(
                    "DELETE FROM participant_events "
                    "WHERE participant = ? AND idx < ?",
                    (participant, floor),
                )
            self._db.commit()

    def vacuum(self, incremental: bool = True) -> None:
        """Hand freed pages back to the OS. Incremental is cheap and the
        default (the DB is created with auto_vacuum=INCREMENTAL); a full
        VACUUM rebuild also upgrades DBs that predate that pragma."""
        with self._db_lock:
            if self._db is None:
                return
            if incremental:
                self._db.execute("PRAGMA incremental_vacuum")
            else:
                self._db.execute("VACUUM")
            self._db.commit()

    def size_stats(self) -> Dict[str, int]:
        stats = dict(self._inmem.size_stats())
        with self._db_lock:
            if self._db is None:
                return stats
            ev = self._db.execute("SELECT COUNT(*) FROM events").fetchone()[0]
            rd = self._db.execute("SELECT COUNT(*) FROM rounds").fetchone()[0]
            bl = self._db.execute("SELECT COUNT(*) FROM blocks").fetchone()[0]
            fr = self._db.execute("SELECT COUNT(*) FROM frames").fetchone()[0]
            page_count = self._db.execute("PRAGMA page_count").fetchone()[0]
            page_size = self._db.execute("PRAGMA page_size").fetchone()[0]
            freelist = self._db.execute("PRAGMA freelist_count").fetchone()[0]
        stats["events"] = ev
        stats["rounds"] = rd
        stats["blocks"] = bl
        stats["frames"] = fr
        stats["store_bytes"] = page_count * page_size
        stats["free_bytes"] = freelist * page_size
        return stats

    def close(self) -> None:
        with self._db_lock:
            if self._db is None:
                return
            self._db.commit()
            self._db.close()
            self._db = None

    def store_path(self) -> str:
        return self._path

    # -- helpers -------------------------------------------------------------

    def _fetch(self, sql: str, args: tuple) -> Optional[tuple]:
        with self._db_lock:
            if self._db is None:
                # a gossip thread outliving shutdown's bounded wait must
                # get a typed miss, not an AttributeError
                raise StoreError(
                    "PersistentStore", StoreErrorKind.KEY_NOT_FOUND, "closed"
                )
            return self._db.execute(sql, args).fetchone()

    def _write(self, sql: str, args: tuple) -> None:
        if self._maintenance:
            return
        with self._db_lock:
            if self._db is None:
                # Same fail-closed policy as events: a silently dropped
                # write leaves the durable history behind what this
                # incarnation advertised to the network. Derived objects
                # (rounds/blocks/frames) replay from events, but a loud
                # failure is strictly safer than a silent gap — the dying
                # caller handles it like any other store error.
                raise StoreError(
                    "PersistentStore", StoreErrorKind.CLOSED, sql.split()[2]
                )
            self._db.execute(sql, args)
            self._db.commit()


def _event_from_json(data: str, annotated: bool = True) -> Event:
    d = json.loads(data)
    ev = Event(EventBody.from_dict(d["Body"]), signature=d["Signature"])
    if annotated:
        if d.get("Round") is not None:
            ev.set_round(d["Round"])
        if d.get("Lamport") is not None:
            ev.set_lamport_timestamp(d["Lamport"])
        if d.get("RoundReceived") is not None:
            ev.set_round_received(d["RoundReceived"])
    return ev
