"""Hashgraph consensus core — data model, store, and engine.

Reference parity map: src/hashgraph/ (event.go, block.go, frame.go, root.go,
roundInfo.go, internal_transaction.go, caches.go, store.go, inmem_store.go,
hashgraph.go). The engine here is the CPU oracle; the tensorized pipeline
lives in babble_tpu.ops.dag.
"""

from babble_tpu.hashgraph.block import Block, BlockBody
from babble_tpu.hashgraph.caches import (
    ParticipantEventsCache,
    PeerSetCache,
    PendingRound,
    PendingRoundsCache,
    SigPool,
)
from babble_tpu.hashgraph.errors import (
    ForkError,
    HashgraphError,
    InvalidSignatureError,
    SelfParentError,
    UnknownParentError,
    UnknownParticipantError,
    classify_rejection,
    is_normal_self_parent_error,
)
from babble_tpu.hashgraph.event import (
    BlockSignature,
    Event,
    EventBody,
    EventCoordinates,
    FrameEvent,
    WireBlockSignature,
    WireBody,
    WireEvent,
    decode_hash,
    encode_hash,
    sort_frame_events,
    sort_topological,
)
from babble_tpu.hashgraph.frame import Frame, Root
from babble_tpu.hashgraph.hashgraph import (
    COIN_ROUND_FREQ,
    ROOT_DEPTH,
    Hashgraph,
    dummy_commit_callback,
    middle_bit,
)
from babble_tpu.hashgraph.internal_transaction import (
    InternalTransaction,
    InternalTransactionBody,
    InternalTransactionReceipt,
    TransactionType,
)
from babble_tpu.hashgraph.round_info import RoundEvent, RoundInfo
from babble_tpu.hashgraph.persistent_store import PersistentStore
from babble_tpu.hashgraph.store import InmemStore, Store

__all__ = [
    "Block",
    "BlockBody",
    "BlockSignature",
    "COIN_ROUND_FREQ",
    "Event",
    "EventBody",
    "EventCoordinates",
    "Frame",
    "FrameEvent",
    "Hashgraph",
    "InmemStore",
    "PersistentStore",
    "InternalTransaction",
    "InternalTransactionBody",
    "InternalTransactionReceipt",
    "ParticipantEventsCache",
    "PeerSetCache",
    "PendingRound",
    "PendingRoundsCache",
    "ROOT_DEPTH",
    "Root",
    "RoundEvent",
    "RoundInfo",
    "SelfParentError",
    "ForkError",
    "HashgraphError",
    "InvalidSignatureError",
    "UnknownParentError",
    "UnknownParticipantError",
    "classify_rejection",
    "SigPool",
    "Store",
    "TransactionType",
    "WireBlockSignature",
    "WireBody",
    "WireEvent",
    "decode_hash",
    "dummy_commit_callback",
    "encode_hash",
    "is_normal_self_parent_error",
    "middle_bit",
    "sort_frame_events",
    "sort_topological",
]
