"""InternalTransaction — signed peer-membership changes that go through
consensus (reference: src/hashgraph/internal_transaction.go:20-189)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from babble_tpu.crypto.canonical import canonical_dumps
from babble_tpu.crypto.hashing import sha256
from babble_tpu.crypto.keys import PrivateKey
from babble_tpu.peers.peer import Peer


class TransactionType(enum.IntEnum):
    """reference: internal_transaction.go:20-25."""

    PEER_ADD = 0
    PEER_REMOVE = 1

    def __str__(self) -> str:
        return self.name


@dataclass
class InternalTransactionBody:
    """reference: internal_transaction.go:40-43, plus a uniquifying nonce.

    The reference body is {Type, Peer} only, which makes a validator's
    join (or leave) itx BYTE-IDENTICAL every time the same peer rejoins —
    and membership promises are keyed by the itx hash. A rejoining
    node that fast-forwards and replays a block carrying its own
    PREVIOUS leave/join then pops the NEW promise with the stale
    receipt: leave() returns before the new itx was ever published, the
    node shuts down, and the cluster keeps a ghost validator forever
    (found by the looped rejoin hunt, tests/test_node_rejoin_loop.py —
    the reference has the same latent hash collision). The nonce makes
    every membership request a distinct consensus object."""

    type: TransactionType
    peer: Peer
    nonce: int = 0

    def to_dict(self) -> dict:
        d = {"Type": int(self.type), "Peer": self.peer.to_dict()}
        if self.nonce:
            d["Nonce"] = self.nonce
        return d

    def hash(self) -> bytes:
        return sha256(canonical_dumps(self.to_dict()))

    @staticmethod
    def from_dict(d: dict) -> "InternalTransactionBody":
        return InternalTransactionBody(
            type=TransactionType(d["Type"]),
            peer=Peer.from_dict(d["Peer"]),
            nonce=d.get("Nonce", 0),
        )


@dataclass
class InternalTransaction:
    """reference: internal_transaction.go:72-75."""

    body: InternalTransactionBody
    signature: str = ""

    @staticmethod
    def join(peer: Peer) -> "InternalTransaction":
        import secrets

        return InternalTransaction(
            InternalTransactionBody(
                TransactionType.PEER_ADD, peer, nonce=secrets.randbits(63)
            )
        )

    @staticmethod
    def leave(peer: Peer) -> "InternalTransaction":
        import secrets

        return InternalTransaction(
            InternalTransactionBody(
                TransactionType.PEER_REMOVE, peer, nonce=secrets.randbits(63)
            )
        )

    def sign(self, key: PrivateKey) -> None:
        """The *target peer's* key signs the body — joins are self-requested
        (reference: internal_transaction.go:122-136)."""
        self.signature = key.sign(self.body.hash())

    def verify(self) -> bool:
        """reference: internal_transaction.go:139-154."""
        try:
            pub = self.body.peer.public_key()
        except Exception:
            return False
        return pub.verify(self.body.hash(), self.signature)

    def hash_string(self) -> str:
        """Key for tracking itxs through consensus
        (reference: internal_transaction.go:159-162)."""
        return self.body.hash().hex()

    def as_accepted(self) -> "InternalTransactionReceipt":
        return InternalTransactionReceipt(self, True)

    def as_refused(self) -> "InternalTransactionReceipt":
        return InternalTransactionReceipt(self, False)

    def to_dict(self) -> dict:
        return {"Body": self.body.to_dict(), "Signature": self.signature}

    @staticmethod
    def from_dict(d: dict) -> "InternalTransaction":
        return InternalTransaction(
            body=InternalTransactionBody.from_dict(d["Body"]),
            signature=d.get("Signature", ""),
        )


@dataclass
class InternalTransactionReceipt:
    """App's accept/refuse decision (reference: internal_transaction.go:186-189)."""

    internal_transaction: InternalTransaction
    accepted: bool

    def to_dict(self) -> dict:
        return {
            "InternalTransaction": self.internal_transaction.to_dict(),
            "Accepted": self.accepted,
        }

    @staticmethod
    def from_dict(d: dict) -> "InternalTransactionReceipt":
        return InternalTransactionReceipt(
            internal_transaction=InternalTransaction.from_dict(
                d["InternalTransaction"]
            ),
            accepted=d["Accepted"],
        )
