"""SweepBatcher — ONE device dispatch for all co-located nodes' sweeps.

Multi-validator hosts (the 16-node threaded topology, tests, any
in-process cluster) run many consensus engines against ONE device. The
per-node admission control in :mod:`babble_tpu.hashgraph.accel` keeps
their sweeps from convoying, but it is still one dispatch+readback PER
NODE — n nodes pay n tunnel readbacks per flush cycle, and the losers
ride the host oracle.

The batcher replaces that with data parallelism over the node axis: flush
requests arriving within a short coalesce window are grouped by window
shape bucket, stacked along a leading batch axis, and dispatched as ONE
vmapped program (``ops.voting._batched_sweep_jit``) with ONE readback for
the whole host. This is the architecture BASELINE.md's config 3 calls
for — one chip batching consensus for many co-located validators — and
it is the tpu-native answer to the reference's per-process nodes (each Go
node owns its consensus loop, node.go; here the device amortizes them).

Semantics: vmap adds a batch dimension and never mixes rows, so each
window's [fame | round_received] vector is bit-identical to its
single-dispatch result (pinned by tests/test_sweep_batcher.py). Failures
set the ticket error and the owning node falls back to its oracle —
exactly the degradation contract of TensorConsensus.

Enabled per-node via ``BABBLE_ACCEL_BATCH=1`` (TensorConsensus resolves
it at first flush). The batcher is in-process by design: cross-process
coalescing would need shared device buffers; separate processes keep the
flock admission slots instead.
"""

from __future__ import annotations

import logging
import threading
import time

from ..common.timed_lock import named_lock
from typing import Dict, List, Optional

logger = logging.getLogger("babble_tpu.hashgraph.sweep_batcher")


class Ticket:
    """One node's submitted window; the batcher delivers (fame, rr) or an
    error. ``done`` is set exactly once."""

    __slots__ = ("win", "result", "error", "done", "batch_size", "mesh",
                 "owner")

    def __init__(self, win, mesh=None, owner: Optional[str] = None):
        self.win = win
        self.result = None  # (fame, rr) numpy arrays
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.batch_size = 0  # how many windows shared the dispatch
        # Coprocessor lane: a configured jax Mesh routes this window to
        # the sharded program shared by every co-located validator on the
        # same mesh; owner is the submitting validator's identity (for
        # the copro_validators multiplexing stat).
        self.mesh = mesh
        self.owner = owner


class SweepBatcher:
    """Process-wide coalescing dispatcher (one daemon thread)."""

    _instance: Optional["SweepBatcher"] = None
    _instance_lock = threading.Lock()

    #: how long the dispatcher waits after the first submission for
    #: co-located nodes' flushes to land. Gossip heartbeats are >= 10 ms,
    #: so a few ms captures one whole flush wave without adding visible
    #: decision latency (the pipelined mode hides it behind gossip anyway).
    COALESCE_S = 0.004
    MAX_BATCH = 16
    #: consecutive waves strictly below the target bucket before it decays
    #: back toward the observed per-wave max — one oversized window (a
    #: rejoin backlog, a churn spike) must not permanently inflate the
    #: padded shapes every later batch pays to compute.
    DECAY_WAVES = 24

    def __init__(self) -> None:
        # Named for the BABBLE_LOCKCHECK order recorder (lockcheck.py).
        self._lock = named_lock("batcher")
        self._pending: List[Ticket] = []
        self._work = threading.Event()
        self._compiling: set = set()
        # Shape-space discipline: every batched dispatch pads to B =
        # MAX_BATCH and to a MONOTONE target bucket (elementwise max of
        # everything seen, seeded by the prewarmed ``floor_key``) — without
        # this, drifting per-wave buckets spray one-off (B, shape) compiles
        # and batches never meet a warm program (measured: 9 distinct
        # compile kicks in one 20 s run, zero warm batches).
        self.floor_key: Optional[tuple] = None
        self._target: Optional[tuple] = None
        # decay bookkeeping (see _update_target)
        self._below_waves = 0
        self._decay_max: Optional[tuple] = None
        # stats
        self.batches = 0  # batched dispatches (>= 2 windows)
        self.singles = 0  # lone or unwarmed windows dispatched singly
        self.windows = 0  # total windows served
        self.max_batch_seen = 0
        self.compile_kicks = 0
        self.refused = 0  # submissions bounced by backpressure
        self.target_decays = 0  # times the monotone bucket shrank back
        # Coprocessor (mesh) lane: per-mesh monotone target buckets (the
        # wave pads every validator's window to ONE aligned shape so the
        # whole cluster shares each mesh's compile cache) and the distinct
        # validators multiplexed so far.
        self._mesh_targets: Dict[tuple, tuple] = {}
        self.copro_waves = 0  # mesh waves dispatched
        self.copro_windows = 0  # windows served through a mesh wave
        self._owners: set = set()  # validators seen on any mesh lane
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="sweep-batcher"
        )
        self._thread.start()

    @classmethod
    def instance(cls) -> "SweepBatcher":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    #: refuse submissions past this backlog: the caller's oracle is cheaper
    #: than queueing behind a convoy (the admission-slot economics, kept).
    MAX_QUEUE = 32

    def submit(self, win, mesh=None,
               owner: Optional[str] = None) -> Optional[Ticket]:
        """Queue a window for the next coalesced dispatch, or return None
        when the batcher is backlogged — the caller must run its oracle,
        exactly like losing an admission slot. With ``mesh`` the window
        rides the coprocessor lane: one wave of overlapped SHARDED
        dispatches padded to a shared per-mesh bucket."""
        with self._lock:
            if len(self._pending) >= self.MAX_QUEUE:
                self.refused += 1
                return None
            t = Ticket(win, mesh=mesh, owner=owner)
            self._pending.append(t)
        self._work.set()
        return t

    def stats(self) -> dict:
        return {
            "batch_batches": self.batches,
            "batch_singles": self.singles,
            "batch_windows": self.windows,
            "batch_max": self.max_batch_seen,
            "batch_compile_kicks": self.compile_kicks,
            "batch_refused": self.refused,
            "batch_target_decays": self.target_decays,
            # coprocessor lane: mesh waves, windows multiplexed through
            # them, and distinct validators sharing the mesh(es)
            "copro_waves": self.copro_waves,
            "copro_windows": self.copro_windows,
            "copro_validators": len(self._owners),
        }

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            self._work.wait()
            # Let the rest of the flush wave land before grouping: nodes
            # flush on the same gossip cadence, so the first submission
            # predicts more within a few ms.
            time.sleep(self.COALESCE_S)
            with self._lock:
                batch, self._pending = self._pending, []
                self._work.clear()
            if batch:
                try:
                    self._dispatch(batch)
                except BaseException as err:  # never kill the daemon
                    for t in batch:
                        if not t.done.is_set():
                            t.error = err
                            t.done.set()
                    logger.warning("sweep batch dispatch failed",
                                   exc_info=True)

    def _dispatch(self, tickets: List[Ticket]) -> None:
        # Partition the wave into lanes: one per configured mesh (the
        # coprocessor path — every co-located validator on the same mesh
        # shares its compile cache and padded bucket) plus the
        # single-device lane. Lanes dispatch independently; a wave can
        # carry both without cross-contamination.
        lanes: Dict[Optional[tuple], List[Ticket]] = {}
        meshes: Dict[tuple, object] = {}
        for t in tickets:
            if t.mesh is not None:
                from babble_tpu.parallel import voting_shard

                mk = voting_shard._mesh_key(t.mesh)
                meshes[mk] = t.mesh
                lanes.setdefault(mk, []).append(t)
            else:
                lanes.setdefault(None, []).append(t)
        for mk, lane in lanes.items():
            group = lane
            while len(group) > self.MAX_BATCH:
                head, group = group[: self.MAX_BATCH], group[self.MAX_BATCH:]
                self._dispatch_lane(meshes.get(mk), head)
            self._dispatch_lane(meshes.get(mk), group)

    def _dispatch_lane(self, mesh, group: List[Ticket]) -> None:
        if mesh is not None:
            self._dispatch_mesh_group(mesh, group)
        else:
            self._dispatch_group(group)

    def _gate_stale(self, group: List[Ticket]) -> List[Ticket]:
        # Resident-state generation gate: windows snapshotted from a
        # persistent WindowState carry (state, generation). If the state
        # mutated between submit and dispatch (a rebuild, an invalidate),
        # the window's row maps are stale — computing it would hand the
        # owner results it must discard anyway, so fail the ticket now and
        # let that node's oracle carry the flush. This is what keys a
        # batched wave to the resident-state generation — and what keeps
        # one validator's reset from ever corrupting a co-multiplexed
        # neighbour: stale generations never ride a dispatch.
        fresh: List[Ticket] = []
        for t in group:
            state = getattr(t.win, "state", None)
            if state is not None and state.generation != t.win.generation:
                from babble_tpu.ops.window_state import StaleWindowError

                t.error = StaleWindowError(
                    f"window generation {t.win.generation} != state "
                    f"generation {state.generation}"
                )
                t.done.set()
                continue
            fresh.append(t)
        return fresh

    def _dispatch_mesh_group(self, mesh, group: List[Ticket]) -> None:
        """Coprocessor wave: every validator's window re-pads to ONE
        mesh-aligned monotone bucket and launches through the shared
        per-mesh sharded program — launch all, read all, so the device
        overlaps the windows' work and the wave pays ~one readback. The
        padding rule is the batcher's (elementwise-max bucket, neutral
        fills) with the witness axis grown until the mesh size divides
        it; the compile cache is voting_shard's per-mesh jit, shared by
        every validator on this mesh."""
        from babble_tpu.ops import voting
        from babble_tpu.parallel import voting_shard

        group = self._gate_stale(group)
        if not group:
            return
        for t in group:
            if t.owner is not None:
                self._owners.add(t.owner)
        keys = [voting.bucket_key(t.win) for t in group]
        wave = tuple(max(k[d] for k in keys) for d in range(5))
        n = int(mesh.devices.size)
        W_m = wave[0]
        while W_m % n and W_m <= wave[0] * n:
            # doubling a power-of-two W can never reach a multiple of a
            # mesh with an odd factor; cap the climb and launch unaligned
            # (the per-ticket try/except below converts the shard error
            # into a ticket failure -> the owner's oracle path)
            W_m *= 2
        if W_m % n == 0:
            wave = (W_m,) + wave[1:]
        mk = voting_shard._mesh_key(mesh)
        prev = self._mesh_targets.get(mk)
        if prev is not None:
            wave = tuple(max(a, b) for a, b in zip(wave, prev))
        self._mesh_targets[mk] = wave
        launched = []
        for t in group:
            try:
                padded = voting.repad_window(t.win, wave)
                launched.append((
                    t, padded,
                    voting_shard._jitted(mesh)(
                        *voting_shard.place_window(mesh, padded)
                    ),
                ))
            except BaseException as err:
                t.error = err
                t.done.set()
        import numpy as np

        served = 0
        for t, padded, out in launched:
            try:
                host = np.asarray(out)
                # real rows keep their indexes under repad: slice back to
                # the ORIGINAL window's row spaces
                t.result = (
                    host[: t.win.n_witnesses],
                    host[padded.n_witnesses:
                         padded.n_witnesses + t.win.n_events],
                )
                t.batch_size = len(launched)
                served += 1
            except BaseException as err:
                t.error = err
            t.done.set()
        if served:
            self.copro_waves += 1
            self.copro_windows += served
            self.windows += served
            self.max_batch_seen = max(self.max_batch_seen, served)

    def _dispatch_group(self, group: List[Ticket]) -> None:
        from babble_tpu.ops import voting

        group = self._gate_stale(group)
        if not group:
            return

        # Co-located nodes at slightly different DAG progress land in
        # DIFFERENT shape buckets; grouping by exact bucket would leave
        # every wave as singles. Instead the whole wave re-pads to the
        # monotone target bucket (repad_window: same neutral fills as the
        # builder, bit-identical decisions) and rides one dispatch.
        keys = [voting.bucket_key(t.win) for t in group]
        if self.floor_key is not None:
            keys.append(self.floor_key)
        wave = tuple(max(k[d] for k in keys) for d in range(5))
        target = self._update_target(wave)
        B = self.MAX_BATCH
        if len(group) > 1 and voting.batched_ready(target, B):
            padded = [voting.repad_window(t.win, target) for t in group]
            try:
                out = voting.launch_batched(padded, B)
                results = voting.read_batched(out, padded)
            except BaseException as err:
                for t in group:
                    t.error = err
                    t.done.set()
                return
            self.batches += 1
            self.windows += len(group)
            self.max_batch_seen = max(self.max_batch_seen, len(group))
            for t, (fame, rr) in zip(group, results):
                # slice the padded vectors back to the ORIGINAL window's
                # row spaces (real rows keep their indexes under repad)
                t.batch_size = len(group)
                t.result = (
                    fame[: t.win.n_witnesses],
                    rr[: t.win.n_events],
                )
                t.done.set()
            return
        if len(group) > 1:
            self._kick_compile(target, B)
        # Unwarmed batch shape (or a lone window): serve through the warm
        # single-window program so decisions keep flowing. Launch ALL
        # buffers first, read back after — launch_sweep returns unread
        # device buffers, so the device overlaps the windows' work and the
        # wave pays ~one readback latency instead of a serial convoy.
        launched = []
        for t in group:
            try:
                launched.append((t, voting.launch_sweep(t.win)))
            except BaseException as err:
                t.error = err
                self.singles += 1
                self.windows += 1
                t.done.set()
        for t, out in launched:
            try:
                t.result = voting.read_sweep(out, t.win)
                t.batch_size = 1
            except BaseException as err:
                t.error = err
            self.singles += 1
            self.windows += 1
            t.done.set()

    def _update_target(self, wave: tuple) -> tuple:
        """Monotone-with-decay shape bucket. The target grows to cover
        every wave (keeping dispatches on one warm program), but after
        DECAY_WAVES consecutive waves strictly below it, it shrinks back
        to the elementwise max actually observed in that window — so one
        oversized window stops permanently inflating padded shapes. The
        floor_key rides inside ``wave`` (the caller folds it in), so
        decay never drops below the prewarmed floor."""
        t = self._target
        if t is None:
            self._target = wave
            return wave
        grown = tuple(max(w, d) for w, d in zip(wave, t))
        if grown != t or wave == t:
            # at or above the target in some dimension: (re)grow and
            # restart the decay observation window
            self._target = grown
            self._below_waves = 0
            self._decay_max = None
            return grown
        # strictly below the target in >= 1 dim, nowhere above
        dm = self._decay_max
        self._decay_max = (
            wave if dm is None else tuple(max(a, b) for a, b in zip(dm, wave))
        )
        self._below_waves += 1
        if self._below_waves >= self.DECAY_WAVES:
            self._target = self._decay_max
            self.target_decays += 1
            self._below_waves = 0
            self._decay_max = None
        return self._target

    def _kick_compile(self, key: tuple, batch: int) -> None:
        gate = (batch, key)
        with self._lock:
            if gate in self._compiling:
                return
            self._compiling.add(gate)
        self.compile_kicks += 1

        def work() -> None:
            from babble_tpu.ops import voting

            try:
                t0 = time.perf_counter()
                voting.precompile_batched(batch, *key)
                logger.info(
                    "batched sweep ready for B=%d bucket %s in %.1fs",
                    batch, key, time.perf_counter() - t0,
                )
            except Exception:
                logger.warning(
                    "batched precompile failed for B=%d %s", batch, key,
                    exc_info=True,
                )
            finally:
                with self._lock:
                    self._compiling.discard(gate)

        threading.Thread(target=work, daemon=True,
                         name="sweep-batch-compile").start()
