"""Store — the persistence boundary of the consensus engine
(reference: src/hashgraph/store.go:6-73, inmem_store.go:14-321).

The engine only ever touches state through this interface, which is what
lets the TPU kernels swap in dense tensor snapshots behind the same
boundary (SURVEY.md §7)."""

from __future__ import annotations

from typing import Dict, List, Optional, Protocol

from babble_tpu.common.errors import StoreError, StoreErrorKind, is_store_err
from babble_tpu.common.lru import LRU
from babble_tpu.common.rolling_index import RollingIndex
from babble_tpu.hashgraph.block import Block
from babble_tpu.hashgraph.caches import ParticipantEventsCache, PeerSetCache
from babble_tpu.hashgraph.event import Event
from babble_tpu.hashgraph.frame import Frame, Root
from babble_tpu.hashgraph.round_info import RoundInfo
from babble_tpu.peers.peer import Peer
from babble_tpu.peers.peer_set import PeerSet


class Store(Protocol):
    """reference: store.go:6-73."""

    def cache_size(self) -> int: ...
    def get_peer_set(self, round: int) -> PeerSet: ...
    def set_peer_set(self, round: int, peer_set: PeerSet) -> None: ...
    def get_all_peer_sets(self) -> Dict[int, List[Peer]]: ...
    def first_round(self, participant_id: int) -> tuple[int, bool]: ...
    def repertoire_by_pub_key(self) -> Dict[str, Peer]: ...
    def repertoire_by_id(self) -> Dict[int, Peer]: ...
    def get_event(self, hash_: str) -> Event: ...
    def set_event(self, event: Event) -> None: ...
    def participant_events(self, participant: str, skip: int) -> List[str]: ...
    def participant_event(self, participant: str, index: int) -> str: ...
    def last_event_from(self, participant: str) -> str: ...
    def last_consensus_event_from(self, participant: str) -> str: ...
    def known_events(self) -> Dict[int, int]: ...
    def consensus_events(self) -> List[str]: ...
    def consensus_events_count(self) -> int: ...
    def add_consensus_event(self, event: Event) -> None: ...
    def get_round(self, round_index: int) -> RoundInfo: ...
    def set_round(self, round_index: int, round_info: RoundInfo) -> None: ...
    def last_round(self) -> int: ...
    def round_witnesses(self, round_index: int) -> List[str]: ...
    def round_events(self, round_index: int) -> int: ...
    def get_root(self, participant: str) -> Root: ...
    def get_block(self, index: int) -> Block: ...
    def set_block(self, block: Block) -> None: ...
    def last_block_index(self) -> int: ...
    def get_frame(self, round_received: int) -> Frame: ...
    def set_frame(self, frame: Frame) -> None: ...
    def reset(self, frame: Frame) -> None: ...
    def close(self) -> None: ...
    def store_path(self) -> str: ...
    # Compaction (lifecycle tier — babble_tpu/lifecycle): the hashgraph
    # computes WHAT is safe to drop (Hashgraph.prune_below); the store
    # only deletes it and reports its footprint.
    def prune_below(
        self,
        floor_round: int,
        drop_events: List[str],
        drop_rounds: List[int],
        participant_floors: Dict[str, int],
    ) -> None: ...
    def size_stats(self) -> Dict[str, int]: ...
    # Misbehavior evidence (equivocation proofs — node/sentry.py): a flat
    # key -> jsonable-dict ledger, durable on persistent stores.
    def set_evidence(self, key: str, data: dict) -> None: ...
    def all_evidence(self) -> Dict[str, dict]: ...


class InmemStore:
    """All-LRU store; evicts old items, so not suitable for joiners that
    need full history (reference: inmem_store.go:14-48)."""

    def __init__(self, cache_size: int = 10000):
        self._cache_size = cache_size
        self._event_cache = LRU(cache_size)
        self._round_cache = LRU(cache_size)
        self._block_cache = LRU(cache_size)
        self._frame_cache = LRU(cache_size)
        self._consensus_cache = RollingIndex("ConsensusCache", cache_size)
        self._tot_consensus_events = 0
        self._peer_set_cache = PeerSetCache()
        self._participant_events_cache = ParticipantEventsCache(cache_size)
        self._roots: Dict[str, Root] = {}
        self._last_round = -1
        self._last_consensus_events: Dict[str, str] = {}
        self._last_block = -1
        # Equivocation evidence (node/sentry.py) — in-memory only here;
        # deliberately NOT an LRU: proofs are tiny, rare, and must never
        # be evicted while the process lives.
        self._evidence: Dict[str, dict] = {}

    def cache_size(self) -> int:
        return self._cache_size

    # -- peer sets ---------------------------------------------------------

    def get_peer_set(self, round: int) -> PeerSet:
        return self._peer_set_cache.get(round)

    def set_peer_set(self, round: int, peer_set: PeerSet) -> None:
        """reference: inmem_store.go:63-89 — also registers participants and
        creates their Roots."""
        self._peer_set_cache.set(round, peer_set)
        for p in peer_set.peers:
            self._add_participant(p)

    def _add_participant(self, p: Peer) -> None:
        if p.id not in self._participant_events_cache.participants.by_id:
            self._participant_events_cache.add_peer(p)
        if p.pub_key_hex not in self._roots:
            self._roots[p.pub_key_hex] = Root()

    def get_all_peer_sets(self) -> Dict[int, List[Peer]]:
        return self._peer_set_cache.get_all()

    def first_round(self, participant_id: int) -> tuple[int, bool]:
        return self._peer_set_cache.first_round(participant_id)

    def repertoire_by_pub_key(self) -> Dict[str, Peer]:
        return self._peer_set_cache.repertoire_by_pub_key

    def repertoire_by_id(self) -> Dict[int, Peer]:
        return self._peer_set_cache.repertoire_by_id

    # -- events ------------------------------------------------------------

    def get_event(self, hash_: str) -> Event:
        ev, ok = self._event_cache.get(hash_)
        if not ok:
            raise StoreError("EventCache", StoreErrorKind.KEY_NOT_FOUND, hash_)
        return ev

    def set_event(self, event: Event) -> None:
        """First insert also appends to the creator's participant index
        (reference: inmem_store.go:122-135)."""
        key = event.hex()
        if key not in self._event_cache:
            self._participant_events_cache.set(event.creator(), key, event.index())
        self._event_cache.add(key, event)

    def participant_events(self, participant: str, skip: int) -> List[str]:
        return self._participant_events_cache.get(participant, skip)

    def participant_event(self, participant: str, index: int) -> str:
        return self._participant_events_cache.get_item(participant, index)

    def last_event_from(self, participant: str) -> str:
        return self._participant_events_cache.get_last(participant)

    def last_consensus_event_from(self, participant: str) -> str:
        """Returns '' when the participant has no consensus events yet
        (reference: inmem_store.go:154-157 — the Go version swallows the
        missing-key case the same way)."""
        return self._last_consensus_events.get(participant, "")

    def known_events(self) -> Dict[int, int]:
        return self._participant_events_cache.known()

    def consensus_events(self) -> List[str]:
        # get_last_window already returns a fresh copy
        window, _ = self._consensus_cache.get_last_window()
        return window

    def consensus_events_count(self) -> int:
        return self._tot_consensus_events

    def add_consensus_event(self, event: Event) -> None:
        self._consensus_cache.set(event.hex(), self._tot_consensus_events)
        self._tot_consensus_events += 1
        self._last_consensus_events[event.creator()] = event.hex()

    # -- rounds ------------------------------------------------------------

    def get_round(self, round_index: int) -> RoundInfo:
        ri, ok = self._round_cache.get(round_index)
        if not ok:
            raise StoreError(
                "RoundCache", StoreErrorKind.KEY_NOT_FOUND, str(round_index)
            )
        return ri

    def set_round(self, round_index: int, round_info: RoundInfo) -> None:
        self._round_cache.add(round_index, round_info)
        if round_index > self._last_round:
            self._last_round = round_index

    def last_round(self) -> int:
        return self._last_round

    def round_witnesses(self, round_index: int) -> List[str]:
        try:
            return self.get_round(round_index).witnesses()
        except StoreError:
            return []

    def round_events(self, round_index: int) -> int:
        try:
            return len(self.get_round(round_index).created_events)
        except StoreError:
            return 0

    # -- roots -------------------------------------------------------------

    def get_root(self, participant: str) -> Root:
        root = self._roots.get(participant)
        if root is None:
            raise StoreError("RootCache", StoreErrorKind.KEY_NOT_FOUND, participant)
        return root

    # -- blocks ------------------------------------------------------------

    def get_block(self, index: int) -> Block:
        b, ok = self._block_cache.get(index)
        if not ok:
            raise StoreError("BlockCache", StoreErrorKind.KEY_NOT_FOUND, str(index))
        return b

    def set_block(self, block: Block) -> None:
        self._block_cache.add(block.index(), block)
        if block.index() > self._last_block:
            self._last_block = block.index()

    def last_block_index(self) -> int:
        return self._last_block

    # -- frames ------------------------------------------------------------

    def get_frame(self, round_received: int) -> Frame:
        f, ok = self._frame_cache.get(round_received)
        if not ok:
            raise StoreError(
                "FrameCache", StoreErrorKind.KEY_NOT_FOUND, str(round_received)
            )
        return f

    def set_frame(self, frame: Frame) -> None:
        self._frame_cache.add(frame.round, frame)

    # -- lifecycle ---------------------------------------------------------

    def reset(self, frame: Frame) -> None:
        """Clear everything, then rebuild roots/peer-sets from the frame
        (reference: inmem_store.go:286-311)."""
        cs = self._cache_size
        self._peer_set_cache = PeerSetCache()
        self._event_cache = LRU(cs)
        self._round_cache = LRU(cs)
        self._block_cache = LRU(cs)
        self._frame_cache = LRU(cs)
        self._participant_events_cache = ParticipantEventsCache(cs)
        self._last_round = -1
        self._last_block = -1
        self._consensus_cache = RollingIndex("ConsensusCache", cs)
        self._last_consensus_events = {}
        # NOTE: _tot_consensus_events deliberately survives the reset — the
        # reference keeps counting across resets (inmem_store.go:286-311 never
        # touches totConsensusEvents) so consensus indexes stay monotonic.

        self._roots = dict(frame.roots)
        for round, ps in frame.peer_sets.items():
            self.set_peer_set(round, PeerSet(ps))
        self.set_frame(frame)
        # evidence survives resets: a fast-forward must not amnesty an
        # equivocator

    # -- compaction --------------------------------------------------------

    def prune_below(
        self,
        floor_round: int,
        drop_events: List[str],
        drop_rounds: List[int],
        participant_floors: Dict[str, int],
    ) -> None:
        """Drop compacted history (lifecycle tier). Blocks, peer-sets,
        roots, evidence and the consensus counters always survive — only
        the listed events/rounds and frames below the floor go. The
        participant index is already a bounded rolling window, so
        ``participant_floors`` only matters to durable stores."""
        for h in drop_events:
            self._event_cache.remove(h)
        for r in drop_rounds:
            self._round_cache.remove(r)
        for fr in [k for k in self._frame_cache.keys() if k < floor_round]:
            self._frame_cache.remove(fr)

    def size_stats(self) -> Dict[str, int]:
        """Retained-object counts + byte footprint (0 for a pure in-memory
        store) — the lifecycle_* gauges and healthview columns read this."""
        return {
            "events": len(self._event_cache),
            "rounds": len(self._round_cache),
            "blocks": len(self._block_cache),
            "frames": len(self._frame_cache),
            "store_bytes": 0,
            "free_bytes": 0,
        }

    # -- evidence ----------------------------------------------------------

    def set_evidence(self, key: str, data: dict) -> None:
        self._evidence[key] = data

    def all_evidence(self) -> Dict[str, dict]:
        return dict(self._evidence)

    def close(self) -> None:
        pass

    def store_path(self) -> str:
        return ""
