"""Event — the fundamental unit of the hashgraph DAG.

Semantics from the reference (cited for parity checks, not copied):
- EventBody fields and hashing: /root/reference/src/hashgraph/event.go:21-64
- coordinates maps (lastAncestors / firstDescendants): event.go:70-120
- sign/verify incl. internal-transaction signatures: event.go:201-247
- wire format replacing parent hashes with (creatorID, index): event.go:411-449
- FrameEvent wrapper and the two sort orders (topological vs
  Lamport+signature-R consensus order): event.go:457-511

TPU-first notes: the string-keyed coordinate maps here are the *oracle*
representation. The JAX kernels in ``babble_tpu.ops.dag`` consume dense
``[n_events, n_peers] int32`` snapshots of the same data; ``peer_index`` in
:class:`babble_tpu.peers.PeerSet` fixes the tensor coordinate of each peer.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from babble_tpu.crypto.canonical import CacheStats, canonical_dumps
from babble_tpu.crypto.hashing import sha256
from babble_tpu.crypto.keys import PrivateKey, PublicKey, decode_signature
from babble_tpu.hashgraph.internal_transaction import InternalTransaction


#: Event.to_wire() memo effectiveness: a hit means a gossip push/reply
#: reused the cached WireEvent instead of rebuilding it (process-wide;
#: surfaced per node via get_stats as wire_cache_hits/misses).
WIRE_CACHE = CacheStats()


def encode_hash(hash_bytes: bytes) -> str:
    """'0X' + uppercase hex (reference: common/hex.go:10-12)."""
    return "0X" + hash_bytes.hex().upper()


def decode_hash(s: str) -> bytes:
    return bytes.fromhex(s[2:])


@dataclass
class EventCoordinates:
    """(hash, index) of an event, used by the stronglySee predicate
    (reference: event.go:70-74)."""

    hash: str
    index: int


@dataclass
class EventBody:
    """Consensus-visible payload of an Event (reference: event.go:21-35).

    The wire-only fields (creator_id, parent indexes) are kept outside the
    canonical encoding, exactly as the reference excludes its private fields
    from JSON marshalling.
    """

    transactions: List[bytes] = field(default_factory=list)
    internal_transactions: List[InternalTransaction] = field(default_factory=list)
    parents: List[str] = field(default_factory=lambda: ["", ""])  # [self, other]
    creator: bytes = b""
    index: int = -1
    block_signatures: List["BlockSignature"] = field(default_factory=list)
    timestamp: int = 0

    # wire info — not part of the canonical encoding (event.go:30-35)
    creator_id: int = 0
    other_parent_creator_id: int = 0
    self_parent_index: int = -1
    other_parent_index: int = -1

    def normalized(self) -> dict:
        """Canonically normalized to_dict (bytes already base64), memoized.
        Frames re-encode every contained event body per decided round
        (frame.hash, Block.from_frame); the consensus-visible body is
        immutable after creation, so each body pays the b64 walk once per
        process instead of once per frame it appears in."""
        from babble_tpu.crypto.canonical import memo_normalized

        return memo_normalized(self, self.to_dict)

    def invalidate_normalized(self) -> None:
        self._norm = None

    def to_dict(self) -> dict:
        return {
            "Transactions": list(self.transactions),
            "InternalTransactions": [t.to_dict() for t in self.internal_transactions],
            "Parents": list(self.parents),
            "Creator": self.creator,
            "Index": self.index,
            "BlockSignatures": [bs.to_dict() for bs in self.block_signatures],
            "Timestamp": self.timestamp,
        }

    def hash(self) -> bytes:
        """SHA256 of the canonical encoding (reference: event.go:57-64).
        Shares the normalized memo with the frame/wire encoders, so the
        b64 walk happens once per body however it is consumed."""
        from babble_tpu.crypto.canonical import PreNormalized

        return sha256(canonical_dumps(PreNormalized(self.normalized())))

    @staticmethod
    def from_dict(d: dict) -> "EventBody":
        from babble_tpu.crypto.canonical import unb64

        def as_bytes(v):
            return unb64(v) if isinstance(v, str) else bytes(v)

        return EventBody(
            transactions=[as_bytes(t) for t in d.get("Transactions") or []],
            internal_transactions=[
                InternalTransaction.from_dict(t)
                for t in d.get("InternalTransactions") or []
            ],
            parents=list(d.get("Parents") or ["", ""]),
            creator=as_bytes(d.get("Creator", b"")),
            index=d.get("Index", -1),
            block_signatures=[
                BlockSignature.from_dict(b) for b in d.get("BlockSignatures") or []
            ],
            timestamp=d.get("Timestamp", 0),
        )


@dataclass
class BlockSignature:
    """A validator's signature over a block body (reference: block.go:59-66)."""

    validator: bytes  # signer's public key
    index: int  # block index
    signature: str  # base-36 "r|s" encoding

    def validator_hex(self) -> str:
        return encode_hash(self.validator)

    def key(self) -> str:
        """Storage key '<index>-<validator hex>' (reference: block.go:104-106)."""
        return f"{self.index}-{self.validator_hex()}"

    def to_wire(self) -> "WireBlockSignature":
        return WireBlockSignature(index=self.index, signature=self.signature)

    def to_dict(self) -> dict:
        return {
            "Validator": self.validator,
            "Index": self.index,
            "Signature": self.signature,
        }

    @staticmethod
    def from_dict(d: dict) -> "BlockSignature":
        from babble_tpu.crypto.canonical import unb64

        v = d["Validator"]
        return BlockSignature(
            validator=unb64(v) if isinstance(v, str) else bytes(v),
            index=d["Index"],
            signature=d["Signature"],
        )


@dataclass
class WireBlockSignature:
    """Signature as it travels in a WireEvent (reference: block.go:110-113)."""

    index: int
    signature: str

    def to_dict(self) -> dict:
        return {"Index": self.index, "Signature": self.signature}

    @staticmethod
    def from_dict(d: dict) -> "WireBlockSignature":
        return WireBlockSignature(index=d["Index"], signature=d["Signature"])


class Event:
    """EventBody + creator signature + local-only consensus annotations
    (reference: event.go:102-142)."""

    __slots__ = (
        "body",
        "signature",
        "topological_index",
        "round",
        "lamport_timestamp",
        "round_received",
        "last_ancestors",
        "first_descendants",
        "_creator",
        "_hash",
        "_hex",
        "_sig_ok",
        "_wire",
    )

    def __init__(self, body: EventBody, signature: str = ""):
        self.body = body
        self.signature = signature
        self.topological_index: int = -1
        self.round: Optional[int] = None
        self.lamport_timestamp: Optional[int] = None
        self.round_received: Optional[int] = None
        self.last_ancestors: Dict[str, EventCoordinates] = {}
        self.first_descendants: Dict[str, EventCoordinates] = {}
        self._creator: str = ""
        self._hash: bytes = b""
        self._hex: str = ""
        self._sig_ok: Optional[bool] = None
        self._wire: Optional["WireEvent"] = None

    @staticmethod
    def new(
        transactions: List[bytes],
        internal_transactions: List[InternalTransaction],
        block_signatures: List[BlockSignature],
        parents: List[str],
        creator: bytes,
        index: int,
        timestamp: int = 0,
    ) -> "Event":
        """reference: event.go:123-142 (timestamp is explicit, not wall-clock,
        so DAG fixtures are deterministic)."""
        return Event(
            EventBody(
                transactions=list(transactions),
                internal_transactions=list(internal_transactions),
                block_signatures=list(block_signatures),
                parents=list(parents),
                creator=creator,
                index=index,
                timestamp=timestamp,
            )
        )

    # -- identity ----------------------------------------------------------

    def creator(self) -> str:
        if not self._creator:
            self._creator = encode_hash(self.body.creator)
        return self._creator

    def self_parent(self) -> str:
        return self.body.parents[0]

    def other_parent(self) -> str:
        return self.body.parents[1]

    def index(self) -> int:
        return self.body.index

    def timestamp(self) -> int:
        return self.body.timestamp

    def transactions(self) -> List[bytes]:
        return self.body.transactions

    def internal_transactions(self) -> List[InternalTransaction]:
        return self.body.internal_transactions

    def block_signatures(self) -> List[BlockSignature]:
        return self.body.block_signatures

    def is_loaded(self) -> bool:
        """True if the event carries a payload or is its creator's first event
        (reference: event.go:189-198)."""
        if self.body.index == 0:
            return True
        return bool(self.body.transactions) or bool(self.body.internal_transactions)

    def hash(self) -> bytes:
        if not self._hash:
            self._hash = self.body.hash()
        return self._hash

    def hex(self) -> str:
        if not self._hex:
            self._hex = encode_hash(self.hash())
        return self._hex

    def invalidate_hash(self) -> None:
        """Drop cached identity after mutating the body (test fixtures only)."""
        self._hash = b""
        self._hex = ""
        self._creator = ""
        self._sig_ok = None
        self._wire = None
        self.body.invalidate_normalized()

    # -- signatures --------------------------------------------------------

    def sign(self, key: PrivateKey) -> None:
        """reference: event.go:201-215."""
        self.signature = key.sign(self.hash())
        self._wire = None  # wire form carries the signature

    def verify(self) -> bool:
        """Verify the creator's signature AND every internal transaction's
        signature (reference: event.go:219-247).

        If the event was prevalidated through the accelerator batch
        verifier (babble_tpu.ops.verify.prevalidate_events), the cached
        verdict is returned without re-doing host-side ECDSA."""
        if self._sig_ok is not None:
            return self._sig_ok
        for itx in self.body.internal_transactions:
            if not itx.verify():
                return False
        try:
            pub = PublicKey.from_bytes(self.body.creator)
        except Exception:
            return False
        return pub.verify(self.hash(), self.signature)

    def prevalidate(self, ok: bool) -> None:
        """Cache a signature verdict computed out-of-band (batch path)."""
        self._sig_ok = bool(ok)

    def prevalidated(self) -> Optional[bool]:
        """The cached batch verdict, or None if never batch-verified."""
        return self._sig_ok

    def clear_prevalidation(self) -> None:
        """Drop the cached verdict so verify() re-runs the scalar path —
        the batch-failure fallback uses this to pinpoint offenders."""
        self._sig_ok = None

    # -- consensus annotations --------------------------------------------

    def set_round(self, r: int) -> None:
        self.round = r

    def set_lamport_timestamp(self, t: int) -> None:
        self.lamport_timestamp = t

    def set_round_received(self, rr: int) -> None:
        self.round_received = rr

    def set_wire_info(
        self,
        self_parent_index: int,
        other_parent_creator_id: int,
        other_parent_index: int,
        creator_id: int,
    ) -> None:
        """reference: event.go:363-371."""
        self.body.self_parent_index = self_parent_index
        self.body.other_parent_creator_id = other_parent_creator_id
        self.body.other_parent_index = other_parent_index
        self.body.creator_id = creator_id
        self._wire = None  # wire form depends on the ids set here

    # -- wire --------------------------------------------------------------

    def wire_block_signatures(self) -> List[WireBlockSignature]:
        return [bs.to_wire() for bs in self.body.block_signatures]

    def to_wire(self) -> "WireEvent":
        """reference: event.go:390-405.

        Cached: the same immutable event is pushed to many peers, and the
        shared WireEvent also memoizes its normalized (base64-applied)
        encoding, so per-transaction b64 work happens once per event
        instead of once per send (set_wire_info invalidates)."""
        if self._wire is not None:
            WIRE_CACHE.hits += 1
            return self._wire
        WIRE_CACHE.misses += 1
        self._wire = WireEvent(
            body=WireBody(
                transactions=list(self.body.transactions),
                internal_transactions=list(self.body.internal_transactions),
                block_signatures=self.wire_block_signatures(),
                creator_id=self.body.creator_id,
                other_parent_creator_id=self.body.other_parent_creator_id,
                index=self.body.index,
                self_parent_index=self.body.self_parent_index,
                other_parent_index=self.body.other_parent_index,
                timestamp=self.body.timestamp,
            ),
            signature=self.signature,
        )
        return self._wire

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Event({self.creator()[:10]}:{self.index()} {self.hex()[:10]})"


@dataclass
class WireBody:
    """Light-weight event body: parent hashes replaced by
    (creatorID, index) pairs (reference: event.go:413-423)."""

    transactions: List[bytes] = field(default_factory=list)
    internal_transactions: List[InternalTransaction] = field(default_factory=list)
    block_signatures: List[WireBlockSignature] = field(default_factory=list)
    creator_id: int = 0
    other_parent_creator_id: int = 0
    index: int = -1
    self_parent_index: int = -1
    other_parent_index: int = -1
    timestamp: int = 0

    def to_dict(self) -> dict:
        return {
            "Transactions": list(self.transactions),
            "InternalTransactions": [t.to_dict() for t in self.internal_transactions],
            "BlockSignatures": [b.to_dict() for b in self.block_signatures],
            "CreatorID": self.creator_id,
            "OtherParentCreatorID": self.other_parent_creator_id,
            "Index": self.index,
            "SelfParentIndex": self.self_parent_index,
            "OtherParentIndex": self.other_parent_index,
            "Timestamp": self.timestamp,
        }

    @staticmethod
    def from_dict(d: dict) -> "WireBody":
        from babble_tpu.crypto.canonical import unb64

        def as_bytes(v):
            return unb64(v) if isinstance(v, str) else bytes(v)

        return WireBody(
            transactions=[as_bytes(t) for t in d.get("Transactions") or []],
            internal_transactions=[
                InternalTransaction.from_dict(t)
                for t in d.get("InternalTransactions") or []
            ],
            block_signatures=[
                WireBlockSignature.from_dict(b) for b in d.get("BlockSignatures") or []
            ],
            creator_id=d.get("CreatorID", 0),
            other_parent_creator_id=d.get("OtherParentCreatorID", 0),
            index=d.get("Index", -1),
            self_parent_index=d.get("SelfParentIndex", -1),
            other_parent_index=d.get("OtherParentIndex", -1),
            timestamp=d.get("Timestamp", 0),
        )


@dataclass
class WireEvent:
    """reference: event.go:427-430."""

    body: WireBody
    signature: str = ""

    def block_signatures(self, validator: bytes) -> List[BlockSignature]:
        """Unpack wire signatures, attributing them to the event's creator
        (reference: event.go:433-449)."""
        return [
            BlockSignature(validator=validator, index=bs.index, signature=bs.signature)
            for bs in self.body.block_signatures
        ]

    def to_dict(self) -> dict:
        return {"Body": self.body.to_dict(), "Signature": self.signature}

    def normalized(self) -> dict:
        """Canonically normalized to_dict (bytes already base64), memoized:
        Event.to_wire shares one WireEvent per event, so each event's
        transactions are b64-encoded once total rather than once per peer
        it is pushed to."""
        from babble_tpu.crypto.canonical import memo_normalized

        return memo_normalized(self, self.to_dict)

    @staticmethod
    def from_dict(d: dict) -> "WireEvent":
        return WireEvent(
            body=WireBody.from_dict(d["Body"]), signature=d.get("Signature", "")
        )


@dataclass
class FrameEvent:
    """Event + its consensus annotations, as shipped in Frames
    (reference: event.go:457-462)."""

    core: Event
    round: int = 0
    lamport_timestamp: int = 0
    witness: bool = False

    def to_dict(self) -> dict:
        from babble_tpu.crypto.canonical import PreNormalized

        return {
            # memoized normalized body: frames re-encode the same immutable
            # event bodies per decided round (see EventBody.normalized)
            "Core": {
                "Body": PreNormalized(self.core.body.normalized()),
                "Signature": self.core.signature,
            },
            "Round": self.round,
            "LamportTimestamp": self.lamport_timestamp,
            "Witness": self.witness,
        }

    @staticmethod
    def from_dict(d: dict) -> "FrameEvent":
        from babble_tpu.crypto.canonical import PreNormalized

        body = d["Core"]["Body"]
        if isinstance(body, PreNormalized):
            # in-process round trip of a to_dict (no codec in between)
            body = body.value
        core = Event(
            EventBody.from_dict(body),
            signature=d["Core"].get("Signature", ""),
        )
        return FrameEvent(
            core=core,
            round=d["Round"],
            lamport_timestamp=d["LamportTimestamp"],
            witness=d["Witness"],
        )


def sort_topological(events: List[Event]) -> List[Event]:
    """Local (per-node) insertion order (reference: event.go:479-490)."""
    return sorted(events, key=lambda e: e.topological_index)


def _signature_r(e: Event) -> int:
    try:
        r, _ = decode_signature(e.signature)
        return r
    except ValueError:
        return 0


def sort_frame_events(events: List[FrameEvent]) -> List[FrameEvent]:
    """Consensus total order: Lamport timestamp, ties broken by the
    signature's R value (reference: event.go:494-511)."""
    return sorted(
        events, key=lambda fe: (fe.lamport_timestamp, _signature_r(fe.core))
    )
