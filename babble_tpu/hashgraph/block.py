"""Block — a section of the hashgraph that reached consensus
(reference: src/hashgraph/block.go:16-357)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from babble_tpu.crypto.canonical import canonical_dumps
from babble_tpu.crypto.hashing import sha256
from babble_tpu.crypto.keys import PrivateKey, PublicKey
from babble_tpu.crypto.merkle import merkle_root
from babble_tpu.hashgraph.event import BlockSignature, decode_hash, encode_hash
from babble_tpu.hashgraph.internal_transaction import (
    InternalTransaction,
    InternalTransactionReceipt,
)
from babble_tpu.peers.peer_set import PeerSet


@dataclass
class BlockBody:
    """reference: block.go:16-26."""

    index: int = -1
    round_received: int = -1
    timestamp: int = 0
    state_hash: bytes = b""
    frame_hash: bytes = b""
    peers_hash: bytes = b""
    transactions: List[bytes] = field(default_factory=list)
    internal_transactions: List[InternalTransaction] = field(default_factory=list)
    internal_transaction_receipts: List[InternalTransactionReceipt] = field(
        default_factory=list
    )

    def to_dict(self) -> dict:
        return {
            "Index": self.index,
            "RoundReceived": self.round_received,
            "Timestamp": self.timestamp,
            "StateHash": self.state_hash,
            "FrameHash": self.frame_hash,
            "PeersHash": self.peers_hash,
            "TxRoot": self.tx_root(),
            "Transactions": list(self.transactions),
            "InternalTransactions": [
                t.to_dict() for t in self.internal_transactions
            ],
            "InternalTransactionReceipts": [
                r.to_dict() for r in self.internal_transaction_receipts
            ],
        }

    def header_dict(self) -> dict:
        """The SIGNED form of the body (docs/clients.md §Proof format,
        docs/parity.md): every field of to_dict except the raw
        transaction list, which is committed through its Merkle root +
        leaf count. Validators sign the hash of THIS dict, so an
        inclusion proof only has to carry the header, never the block's
        other transactions. The reference signs the full body
        (block.go:49-55) — deliberate divergence."""
        return {
            "Index": self.index,
            "RoundReceived": self.round_received,
            "Timestamp": self.timestamp,
            "StateHash": self.state_hash,
            "FrameHash": self.frame_hash,
            "PeersHash": self.peers_hash,
            "TxRoot": self.tx_root(),
            "TxCount": len(self.transactions),
            "InternalTransactions": [
                t.to_dict() for t in self.internal_transactions
            ],
            "InternalTransactionReceipts": [
                r.to_dict() for r in self.internal_transaction_receipts
            ],
        }

    def __setattr__(self, name, value):
        # Any body mutation (commit fills state_hash/receipts) invalidates
        # the cached canonical hash — by bumping a version, not clearing a
        # flag: a concurrent hash() writing its result AFTER this
        # invalidation must not resurrect the pre-mutation digest (the
        # lost-invalidation race a reader thread hits while commit fills
        # the body).
        object.__setattr__(self, name, value)
        if name not in ("_hash_cache", "_hash_version", "_tx_root_cache"):
            object.__setattr__(
                self, "_hash_version", getattr(self, "_hash_version", 0) + 1
            )

    def tx_root(self) -> bytes:
        """Merkle root over the transaction list (crypto/merkle.py),
        cached with the same versioning discipline as hash()."""
        ver = getattr(self, "_hash_version", 0)
        cached = getattr(self, "_tx_root_cache", None)
        if cached is not None and cached[0] == ver:
            return cached[1]
        root = merkle_root(self.transactions)
        object.__setattr__(self, "_tx_root_cache", (ver, root))
        return root

    def hash(self) -> bytes:
        """SHA256 of the canonical HEADER encoding — what validators sign
        (header_dict: transactions committed via TxRoot+TxCount; the
        reference hashes the full body, block.go:49-55 — divergence
        recorded in docs/parity.md). Cached until a field changes: the
        sig pool re-verifies against this hash once per gossiped
        signature. The cache entry is (version, digest); a digest
        computed against a body that mutated mid-walk carries a stale
        version and is simply recomputed on the next call."""
        ver = getattr(self, "_hash_version", 0)
        cached = getattr(self, "_hash_cache", None)
        if cached is not None and cached[0] == ver:
            return cached[1]
        digest = sha256(canonical_dumps(self.header_dict()))
        object.__setattr__(self, "_hash_cache", (ver, digest))
        return digest

    @staticmethod
    def from_dict(d: dict) -> "BlockBody":
        from babble_tpu.crypto.canonical import unb64

        def as_bytes(v):
            return unb64(v) if isinstance(v, str) else bytes(v)

        return BlockBody(
            index=d["Index"],
            round_received=d["RoundReceived"],
            timestamp=d["Timestamp"],
            state_hash=as_bytes(d.get("StateHash", b"")),
            frame_hash=as_bytes(d.get("FrameHash", b"")),
            peers_hash=as_bytes(d.get("PeersHash", b"")),
            transactions=[as_bytes(t) for t in d.get("Transactions") or []],
            internal_transactions=[
                InternalTransaction.from_dict(t)
                for t in d.get("InternalTransactions") or []
            ],
            internal_transaction_receipts=[
                InternalTransactionReceipt.from_dict(r)
                for r in d.get("InternalTransactionReceipts") or []
            ],
        )


class Block:
    """BlockBody + accumulated validator signatures
    (reference: block.go:125-192)."""

    def __init__(self, body: BlockBody, peer_set: Optional[PeerSet] = None):
        self.body = body
        self.signatures: Dict[str, str] = {}  # validator hex => signature
        self.peer_set = peer_set
        self._hash: bytes = b""
        self._hex: str = ""

    @staticmethod
    def new(
        block_index: int,
        round_received: int,
        frame_hash: bytes,
        peer_set: PeerSet,
        txs: List[bytes],
        itxs: List[InternalTransaction],
        timestamp: int,
    ) -> "Block":
        """reference: block.go:161-192."""
        body = BlockBody(
            index=block_index,
            round_received=round_received,
            timestamp=timestamp,
            state_hash=b"",
            frame_hash=frame_hash,
            peers_hash=peer_set.hash(),
            transactions=list(txs),
            internal_transactions=list(itxs),
        )
        return Block(body, peer_set=peer_set)

    @staticmethod
    def from_frame(block_index: int, frame) -> "Block":
        """Assemble a block from a frame's events, concatenating their
        payloads in consensus order (reference: block.go:135-158)."""
        txs: List[bytes] = []
        itxs: List[InternalTransaction] = []
        for fe in frame.events:
            txs.extend(fe.core.transactions())
            itxs.extend(fe.core.internal_transactions())
        return Block.new(
            block_index,
            frame.round,
            frame.hash(),
            frame.peers,
            txs,
            itxs,
            frame.timestamp,
        )

    # -- accessors ---------------------------------------------------------

    def index(self) -> int:
        return self.body.index

    def round_received(self) -> int:
        return self.body.round_received

    def timestamp(self) -> int:
        return self.body.timestamp

    def transactions(self) -> List[bytes]:
        return self.body.transactions

    def internal_transactions(self) -> List[InternalTransaction]:
        return self.body.internal_transactions

    def internal_transaction_receipts(self) -> List[InternalTransactionReceipt]:
        return self.body.internal_transaction_receipts

    def state_hash(self) -> bytes:
        return self.body.state_hash

    def frame_hash(self) -> bytes:
        return self.body.frame_hash

    def peers_hash(self) -> bytes:
        return self.body.peers_hash

    def get_signatures(self) -> List[BlockSignature]:
        """reference: block.go:241-254."""
        return [
            BlockSignature(
                validator=decode_hash(v), index=self.index(), signature=sig
            )
            for v, sig in self.signatures.items()
        ]

    # -- hashing / signing -------------------------------------------------

    def to_dict(self) -> dict:
        return {"Body": self.body.to_dict(), "Signatures": dict(self.signatures)}

    def hash(self) -> bytes:
        """Hash of the whole block incl. signatures (reference: block.go:296-306)."""
        if not self._hash:
            self._hash = sha256(canonical_dumps(self.to_dict()))
        return self._hash

    def hex(self) -> str:
        if not self._hex:
            self._hex = encode_hash(self.hash())
        return self._hex

    def sign(self, key: PrivateKey) -> BlockSignature:
        """Sign the body hash; returns a BlockSignature, does NOT append it
        (reference: block.go:318-334)."""
        return BlockSignature(
            validator=key.public_key.bytes(),
            index=self.index(),
            signature=key.sign(self.body.hash()),
        )

    def set_signature(self, bs: BlockSignature) -> None:
        self.signatures[bs.validator_hex()] = bs.signature
        self._hash = b""
        self._hex = ""

    def verify_signature(self, bs: BlockSignature) -> bool:
        """reference: block.go:343-357."""
        try:
            pub = PublicKey.from_bytes(bs.validator)
        except Exception:
            return False
        return pub.verify(self.body.hash(), bs.signature)

    @staticmethod
    def from_dict(d: dict) -> "Block":
        b = Block(BlockBody.from_dict(d["Body"]))
        b.signatures = dict(d.get("Signatures") or {})
        return b

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"Block(index={self.index()}, rr={self.round_received()}, "
            f"txs={len(self.transactions())}, sigs={len(self.signatures)})"
        )
