"""TensorConsensus — drives the device voting kernels for a live Hashgraph.

Attached to a Hashgraph by the node's core when ``--accelerator`` is on.
``Hashgraph.insert_event_and_run_consensus`` then defers DecideFame /
DecideRoundReceived to batched device sweeps (the reference runs them per
insert, hashgraph.go:644-668; here a sweep covers a whole sync batch so
device dispatch amortizes across the gossip round — SURVEY.md hard-part 6).

Two modes, chosen by the measured economics of the device link:

- **Synchronous** (CPU-XLA fallback, tests): one fused device call per
  flush — snapshot the undecided window, run fame + decidedness +
  round-received in one compiled program, read back one buffer, apply.

- **Pipelined** (real accelerator): a device→host readback through the
  tunnel costs ~65-100 ms flat, so the flush path never waits for one.
  Each flush first applies the PREVIOUS sweep's results (read back by a
  background thread while gossip continued — the readback releases the
  GIL), then snapshots and launches the next sweep (sub-millisecond
  dispatch). Applying a snapshot's decisions after later inserts is exactly
  the hashgraph's incremental == batch property — the same property the
  reference's per-insert pipeline relies on — so consensus output is
  bit-identical; only decision latency shifts by one flush interval.

Any store eviction or snapshot failure falls back to the oracle sweep for
that round — consensus output is identical either way, and the node keeps
running; the ``fallbacks`` counter surfaces it in /stats.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from babble_tpu.common.breaker import CircuitBreaker
from babble_tpu.common.errors import StoreError

logger = logging.getLogger("babble_tpu.hashgraph.accel")


def _breaker_from_env(clock=None) -> CircuitBreaker:
    """Device-path circuit breaker with env-tunable parameters: open after
    BABBLE_ACCEL_BREAKER_N failures within BABBLE_ACCEL_BREAKER_WINDOW_S
    seconds, refuse the device for BABBLE_ACCEL_BREAKER_COOLDOWN_S, then
    probe one sweep to half-open/re-close. ``clock`` (a common.clock.Clock
    or bare monotonic callable) makes the trip window and cooldown run on
    the node's time source — virtual under the sim engine."""
    import os

    return CircuitBreaker(
        threshold=max(1, int(os.environ.get("BABBLE_ACCEL_BREAKER_N", "5"))),
        window_s=float(os.environ.get("BABBLE_ACCEL_BREAKER_WINDOW_S", "30")),
        cooldown_s=float(
            os.environ.get("BABBLE_ACCEL_BREAKER_COOLDOWN_S", "15")
        ),
        **({"clock": clock} if clock is not None else {}),
    )


class _Inflight:
    """A launched sweep whose output buffer a background thread is reading
    back while gossip continues."""

    __slots__ = ("win", "result", "error", "done", "generation", "t_launch",
                 "t_done", "topo", "snap", "readback_s", "_slots",
                 "_slot_lock", "_slot_held")

    def __init__(self, win, generation: int, topo: int, slots=None,
                 snap=None):
        self.win = win
        self.result = None  # (fame, rr) numpy arrays once read back
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.generation = generation
        self.t_launch = time.perf_counter()
        self.t_done = 0.0  # set by the reader when the readback lands
        self.topo = topo  # hashgraph topological index at snapshot time
        # Resident-window provenance: the WindowState snapshot this sweep
        # was launched from (None on the legacy full-build path). Its
        # generation gates apply — see TensorConsensus._apply.
        self.snap = snap
        self.readback_s = 0.0  # device→host wait measured by the reader
        # Admission-control slot ownership: released exactly once, by the
        # reader when the readback lands OR by the abandonment path when a
        # wedged readback times out — whichever gets there first.
        self._slots = slots
        self._slot_lock = threading.Lock()
        self._slot_held = slots is not None

    def release_slot(self) -> None:
        with self._slot_lock:
            held, self._slot_held = self._slot_held, False
        if held:
            self._slots.release()


# Sweep admission control. Co-located nodes (multi-validator hosts, the
# 16-node bench, tests) share ONE device and ONE tunnel; without a cap
# their redundant sweeps convoy on the readback path and per-sweep latency
# balloons from ~100 ms to 600+ ms. Capping in-flight sweeps keeps device
# latency flat; flushes that lose the race ride the oracle, which is
# exactly the small-window economics already encoded in min_window.
#
# Two scopes:
# - in-process (default): a plain semaphore covers threads in one
#   interpreter (threaded clusters, tests);
# - cross-process (BABBLE_ACCEL_SLOT_DIR): flock-guarded slot files, so
#   independent node PROCESSES on one host coordinate too — per-process
#   semaphores can't see each other, and 4 processes x 2 slots would put
#   8 sweeps in flight on one device.


class _FlockSlots:
    """Semaphore-shaped admission slots shared ACROSS processes via
    non-blocking flock on a fixed set of slot files. Locks die with the
    process, so a crashed node can never leak a slot."""

    def __init__(self, dir_path: str, n: int):
        import os

        os.makedirs(dir_path, exist_ok=True)
        self._paths = [
            os.path.join(dir_path, f"sweep-slot-{i}.lock") for i in range(n)
        ]
        self._lock = threading.Lock()
        self._held: list = []  # (path, fd) LIFO

    def acquire(self, blocking: bool = False) -> bool:
        import fcntl
        import os

        assert not blocking, "admission slots are try-acquire only"
        with self._lock:
            held_paths = {p for p, _ in self._held}
            for p in self._paths:
                if p in held_paths:
                    continue
                fd = os.open(p, os.O_CREAT | os.O_RDWR, 0o644)
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                except OSError:
                    os.close(fd)
                    continue
                self._held.append((p, fd))
                return True
            return False

    def release(self) -> None:
        import fcntl
        import os

        with self._lock:
            if not self._held:
                return
            _, fd = self._held.pop()
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


def _is_stale_window(err: BaseException) -> bool:
    """True for the batcher's stale-generation rejection — the window
    snapshot aged out before dispatch, which says nothing about device
    health (the breaker must not count it as a failure)."""
    try:
        from babble_tpu.ops.window_state import StaleWindowError
    except Exception:
        return False
    return isinstance(err, StaleWindowError)


_INFLIGHT_SLOTS = None
_slots_lock = threading.Lock()


def _inflight_slots():
    global _INFLIGHT_SLOTS
    if _INFLIGHT_SLOTS is None:
        with _slots_lock:
            if _INFLIGHT_SLOTS is None:
                import os

                n = max(1, int(os.environ.get("BABBLE_ACCEL_MAX_INFLIGHT", "2")))
                slot_dir = os.environ.get("BABBLE_ACCEL_SLOT_DIR")
                if slot_dir:
                    _INFLIGHT_SLOTS = _FlockSlots(slot_dir, n)
                else:
                    _INFLIGHT_SLOTS = threading.Semaphore(n)
    return _INFLIGHT_SLOTS


class TensorConsensus:
    def __init__(self, sweep_events: int = 256, async_compile: bool = True,
                 min_window: int | None = None,
                 pipeline: bool | None = None,
                 mesh=None,
                 batcher: bool | None = None,
                 resident: bool | None = None,
                 breaker: CircuitBreaker | None = None,
                 clock=None,
                 owner: str | None = None):
        # Force a sweep mid-batch once this many inserts accumulate, so the
        # window tensors stay inside one shape bucket even under huge syncs.
        # Normal cadence is one sweep per gossip round (core.sync flush).
        self.sweep_events = sweep_events
        # Crossover threshold: below this many undetermined events the
        # incremental oracle beats the sweep's fixed dispatch+readback cost,
        # so small windows stay on the host and the device takes over
        # exactly when the oracle's O(witnesses² · rounds) voting would
        # start to crawl. None = resolve on first use. 0 forces the device
        # path (tests).
        self.min_window = min_window
        # Pipelined (non-blocking) sweeps: None = resolve on first flush —
        # on a real accelerator the tunnel readback latency must be hidden;
        # on the CPU-XLA fallback readback is free and synchronous sweeps
        # keep decision latency minimal.
        self.pipeline = pipeline
        # Compile window-shape buckets off the consensus thread: the first
        # sweep of a new bucket would otherwise stall gossip for the XLA
        # compile (seconds on CPU, tens of seconds cold on TPU) while
        # holding the core lock. Until a bucket's kernels are ready the
        # oracle carries consensus — output is identical either way.
        self.async_compile = async_compile
        # Optional jax.sharding.Mesh: sweeps run witness-axis sharded over
        # the device mesh (parallel/voting_shard.py) instead of on one
        # device. Output is bit-identical; only placement differs.
        self.mesh = mesh
        # Validator identity for the coprocessor stats (the SweepBatcher
        # counts distinct owners multiplexed onto one mesh); falls back to
        # a per-engine token when the node doesn't name itself.
        self.owner = owner
        # Co-located batching: route sweeps through the process-wide
        # SweepBatcher so all nodes on this host share ONE device dispatch
        # per flush wave (BASELINE config-3 architecture). None = resolve
        # from BABBLE_ACCEL_BATCH at first flush. With a mesh the batcher
        # runs as a consensus coprocessor: co-located validators' windows
        # are padded to one aligned bucket and multiplexed onto the SAME
        # sharded program (shared per-mesh compile cache, one wave of
        # overlapped dispatches).
        self.batcher = batcher
        # Incremental device-resident windows (ops/window_state.py): the
        # snapshot is a persistent WindowState updated in O(ΔE) per sweep,
        # and the window tensors stay on the device between sweeps (the
        # resident program donates the previous buffers and applies a
        # compact delta). None = resolve from BABBLE_ACCEL_RESIDENT at
        # first flush (default ON). Under a mesh, residency is per-shard:
        # the delta scatters into the sharded buffers through the mesh
        # resident program (voting_shard.resident_jitted) and the
        # single-device rebuild stays the correctness oracle. With the
        # batcher, the host side stays incremental but windows are
        # submitted as copies (the batch wave cannot donate per-node
        # buffers).
        self.resident = resident
        self.window_state = None
        # Device-path circuit breaker: transient failures fall back to the
        # oracle per-flush as before, but a FLAPPING device (N failures in
        # a window) opens the breaker and the node stops paying for device
        # dispatch attempts for a cooldown; a probe sweep then re-enables
        # the path once the device answers again. This replaces any notion
        # of a sticky "disable forever" kill-switch: degradation is always
        # recoverable.
        self.breaker = (breaker if breaker is not None
                        else _breaker_from_env(clock))
        self.sweeps = 0
        self.fallbacks = 0
        self.compile_waits = 0
        self.small_windows = 0  # flushes routed to the oracle by min_window
        self.deferred = 0  # flushes that rode behind an in-flight readback
        self.contended = 0  # launches skipped: device at max in-flight sweeps
        self.stale_drops = 0  # readbacks discarded by the generation check
        self.rows_delta_total = 0  # delta rows uploaded across sweeps
        self.rows_reused_total = 0  # resident rows reused across sweeps
        # Mesh padding visibility (satellite: no more silent single-device
        # fallback when W doesn't divide the mesh): rows added to align
        # the witness axis, and windows that still dropped to the
        # single-device program because padding itself failed.
        self.mesh_pad_rows = 0
        self.mesh_fallbacks = 0
        self.generation = 0  # bumped by Hashgraph.reset/bootstrap
        # A sweep whose readback exceeds this is abandoned (tunnel wedge):
        # the oracle takes over so a dead device can stall only one sweep's
        # worth of decisions, never the node.
        self.readback_timeout_s = 30.0
        self._last_snapshot_topo = -1
        self.last_sweep_s = 0.0
        self.total_sweep_s = 0.0
        self.last_window_events = 0
        # Per-stage rolling sums (seconds) for /debug and bench breakdowns.
        # snapshot cost = build (full rebuilds) + delta_scan + pack
        # (incremental); dispatch/readback split the old "kernel" stage so
        # a transfer regression is distinguishable from a compute one.
        self.stage_s = {
            "build": 0.0, "delta_scan": 0.0, "pack": 0.0,
            "dispatch": 0.0, "readback": 0.0, "kernel": 0.0, "apply": 0.0,
        }
        # Optional per-sample stage observer (obs.telemetry wires the
        # accel_stage_seconds{stage=...} histogram here); stage_s keeps
        # the legacy rolling totals either way.
        self.stage_observer = None
        self._inflight: Optional[_Inflight] = None
        self._compiling = set()
        self._lock = threading.Lock()

    def _stage(self, stage: str, seconds: float) -> None:
        """One stage sample: legacy rolling total + histogram observer."""
        self.stage_s[stage] = self.stage_s.get(stage, 0.0) + seconds
        obs = self.stage_observer
        if obs is not None:
            obs(stage, seconds)

    # -- gates --------------------------------------------------------------

    def should_sweep(self, pending_inserts: int) -> bool:
        return pending_inserts >= self.sweep_events

    def use_device(self, undetermined: int) -> bool:
        """Window-size gate: route small windows to the oracle."""
        if self.min_window is None:
            import os

            from babble_tpu.ops.device import on_accelerator

            env = os.environ.get("BABBLE_ACCEL_MIN_WINDOW")
            if env is not None:
                self.min_window = int(env)
            else:
                self.min_window = 192 if on_accelerator() else 256
        if undetermined >= self.min_window:
            return True
        self.small_windows += 1
        return False

    def busy(self) -> bool:
        """True while decisions are pending on an in-flight sweep — keeps
        the node's fast heartbeat ticking so the next flush applies them."""
        return self._inflight is not None

    def invalidate(self) -> None:
        """Drop any in-flight sweep (hashgraph reset / fast-sync landing):
        its snapshot no longer describes this store. Reclaim its admission
        slot — dropping the reference would lose the timeout-reclaim path,
        and a wedged readback would then leak the slot forever. If the
        readback is merely slow, the device is briefly over-admitted by
        one sweep; the reader's own eventual release is a no-op."""
        self.generation += 1
        inf = self._inflight
        if inf is not None:
            inf.release_slot()
            # the dropped sweep never reports an outcome; if it was the
            # half-open probe, release the probe slot so the breaker can
            # admit another
            self.breaker.cancel()
        self._inflight = None
        self._last_snapshot_topo = -1
        if self.window_state is not None:
            # drop residency + force a rebuild: the mirrors describe a
            # store that no longer exists
            self.window_state.mark_dirty("invalidate")

    # -- compile management -------------------------------------------------

    def _use_mesh(self, win) -> bool:
        """True when _dispatch will take the sharded path for this window.
        With a mesh configured this is the normal case: windows whose
        witness axis the mesh size doesn't divide are PADDED to it by
        _mesh_align before they get here — the old silent single-device
        fallback is gone. A window that still arrives unaligned (padding
        failed; counted in mesh_fallbacks) rides the single program."""
        return (
            self.mesh is not None
            and win.n_witnesses % self.mesh.devices.size == 0
        )

    def _mesh_align(self, win):
        """Pad the witness axis so the mesh size divides it (repad_window:
        neutral fills, real rows keep their indexes, decisions identical).
        Counts the padding in mesh_pad_rows; a padding failure counts a
        mesh_fallback and returns the window unchanged (single-device)."""
        n = int(self.mesh.devices.size)
        if n <= 0 or win.n_witnesses % n == 0:
            return win
        from babble_tpu.ops import voting

        key = voting.bucket_key(win)
        W_m = key[0]
        while W_m % n:
            if W_m > key[0] * n:
                # doubling a power-of-two W can never reach a multiple of
                # a mesh with an odd factor — give up, ride single-device
                self.mesh_fallbacks += 1
                return win
            W_m *= 2
        try:
            padded = voting.repad_window(win, (W_m,) + key[1:])
        except Exception:
            logger.warning(
                "mesh witness-axis padding failed for bucket %s", key,
                exc_info=True,
            )
            self.mesh_fallbacks += 1
            return win
        self.mesh_pad_rows += W_m - key[0]
        return padded

    def _copro_owner(self) -> str:
        """Stable validator identity for coprocessor multiplexing stats."""
        return self.owner if self.owner else f"tc-{id(self):x}"

    def _bucket_ready(self, win) -> bool:
        """True when the window's shape bucket is compiled FOR THE PATH
        _dispatch will take (single-device and per-mesh jit caches are
        separate programs). Otherwise kicks a background compile (once)
        and returns False."""
        from babble_tpu.ops import voting

        if not self.async_compile:
            return True  # compile inline (tests, explicit opt-out)
        key = voting.bucket_key(win)
        use_mesh = self._use_mesh(win)
        if use_mesh:
            from babble_tpu.parallel import voting_shard

            ready = voting_shard.bucket_ready(self.mesh, key)
        else:
            ready = voting.bucket_ready(key)
        if ready:
            return True
        gate = (key, use_mesh)
        with self._lock:
            kick = gate not in self._compiling
            if kick:
                self._compiling.add(gate)
        if kick:
            threading.Thread(
                target=self._compile_bucket, args=(key, use_mesh),
                daemon=True,
            ).start()
        self.compile_waits += 1
        return False

    def _compile_bucket(self, key: tuple, use_mesh: bool = False) -> None:
        from babble_tpu.ops import voting

        try:
            t0 = time.perf_counter()
            if use_mesh:
                from babble_tpu.parallel import voting_shard

                voting_shard.precompile(self.mesh, *key)
            else:
                voting.precompile(*key)
            logger.info(
                "voting kernels ready for bucket %s (mesh=%s) in %.1fs",
                key,
                use_mesh,
                time.perf_counter() - t0,
            )
        except Exception:
            # Leave the bucket un-ready so a later sweep retries the
            # background compile instead of stalling inline on it.
            logger.warning("bucket %s precompile failed", key, exc_info=True)
        finally:
            with self._lock:
                self._compiling.discard((key, use_mesh))

    # -- flush entry point ---------------------------------------------------

    def flush(self, hg) -> bool:
        """Handle one consensus flush. Returns False when the caller must
        run the oracle voting stages instead — and marks the resident
        window state dirty in that case, because the oracle pass that
        follows mutates fame/round-received state the mirrors can't track
        in O(ΔE); the next engaged snapshot rebuilds from scratch."""
        handled = self._flush(hg)
        if not handled and self.window_state is not None:
            self.window_state.mark_dirty("oracle-pass")
            # Discard the hashgraph's delta channels too: the rebuild that
            # follows reads the store directly, and on a node whose
            # windows never clear the min_window gate NO snapshot ever
            # drains them — without this they'd grow one entry per
            # witness/fd-update forever.
            hg.drain_accel_delta()
        return handled

    def _flush(self, hg) -> bool:
        from babble_tpu.ops.device import jax_usable

        if not jax_usable():
            # Wedged device link: importing jax would hang the node.
            return False
        if self.pipeline is None:
            import os

            env = os.environ.get("BABBLE_ACCEL_PIPELINE")
            if env is not None:
                # test/bench override: exercise the pipelined (or sync)
                # path regardless of the resolved backend
                self.pipeline = env == "1"
            else:
                from babble_tpu.ops.device import on_accelerator

                self.pipeline = on_accelerator()
        if self.batcher is None:
            import os

            # Default: batch only on a REAL accelerator, where dispatch is
            # async and a vmapped batch costs ~one window's latency. On
            # host XLA a central dispatcher convoys sweeps that already
            # run at full host throughput (measured: 16-node threaded
            # accel dropped ~2.7x with the batcher forced on), so CPU
            # tests that force pipeline=True must not pick it up.
            # BABBLE_ACCEL_BATCH=1/0 overrides either way. With a mesh
            # the batcher multiplexes co-located validators onto the
            # sharded program (the coprocessor mode) instead of stacking
            # single-device ones.
            env = os.environ.get("BABBLE_ACCEL_BATCH")
            if env is not None:
                self.batcher = env == "1"
            else:
                from babble_tpu.ops.device import on_accelerator

                self.batcher = on_accelerator()
        if self.resident is None:
            self.resident = resident_default_on()
        if self.resident and self.window_state is None:
            from babble_tpu.ops.window_state import WindowState

            self.window_state = WindowState(mesh=self.mesh)
        # turn on the hashgraph's delta channels (new witnesses, fd
        # mutations) exactly when a WindowState consumes them
        hg._accel_track_delta = bool(self.resident)
        if not self.pipeline:
            if not self.use_device(len(hg.undetermined_events)):
                return False
            if not self.breaker.allow():
                # breaker open: the device is known-bad; don't pay for a
                # dispatch attempt, let the oracle carry the flush
                return False
            return self.sweep(hg)

        handled = False
        inf = self._inflight
        if inf is not None:
            if inf.generation != self.generation:
                inf.release_slot()  # same reclaim rationale as invalidate()
                self._inflight = None
            elif not inf.done.is_set():
                if (
                    time.perf_counter() - inf.t_launch
                    > self.readback_timeout_s
                ):
                    # Tunnel wedge: abandon the sweep and let the oracle
                    # take over so the node keeps deciding. Reclaim the
                    # admission slot here — the parked reader thread may
                    # never finish, and a leaked slot would silently
                    # disable the accelerator process-wide (its own
                    # eventual release is a no-op after this).
                    inf.release_slot()
                    self._inflight = None
                    self._note_fallback(
                        TimeoutError(
                            f"sweep readback exceeded "
                            f"{self.readback_timeout_s:.0f}s"
                        )
                    )
                    return False
                # Results still crossing the tunnel; decisions arrive next
                # flush. Skipping the oracle here is what hides the
                # readback latency.
                self.deferred += 1
                return True
            else:
                self._inflight = None
                if not self._apply(hg, inf):
                    return False  # oracle carries this flush
                handled = True
        # Relaunch only when the DAG grew since the last snapshot: a sweep
        # over an identical window returns identical decisions, so spinning
        # launch/apply on a quiescent backlog would burn a device sweep per
        # heartbeat for nothing and pin busy() high forever.
        if hg.topological_index != self._last_snapshot_topo and self.use_device(
            len(hg.undetermined_events)
        ):
            if not self.breaker.allow():
                return handled  # breaker open: oracle unless already applied
            launched = self._launch(hg)
            return handled or launched
        return handled

    # -- pipelined internals -------------------------------------------------

    def _dispatch(self, win):
        """Launch the fused sweep — single-device, or witness-axis sharded
        over the configured mesh (bit-identical output, different
        placement). Windows reach here already mesh-aligned (_mesh_align);
        one that didn't (padding failed) is counted and rides the
        single-device program."""
        from babble_tpu.ops import voting

        if self._use_mesh(win):
            from babble_tpu.parallel import voting_shard

            return voting_shard._jitted(self.mesh)(
                *voting_shard.place_window(self.mesh, win)
            )
        if self.mesh is not None:
            self.mesh_fallbacks += 1
        return voting.launch_sweep(win)

    def _snapshot(self, hg, for_batcher: bool = False):
        """This sweep's window: the legacy from-scratch build, or — in
        resident mode — an O(ΔE) WindowState snapshot (delta over the
        persistent mirrors, rebuilding only when a trigger fires).
        Returns (win, snap); win None ⇒ nothing to decide; snap None on
        the legacy path. ``for_batcher`` snapshots copied row arrays so
        the batcher's asynchronous dispatch never reads mirrors a later
        delta mutated in place."""
        from babble_tpu.ops import voting

        if not self.resident:
            t0 = time.perf_counter()
            win = voting.build_voting_window(hg)
            self._stage("build", time.perf_counter() - t0)
            return win, None
        timers: dict = {}
        try:
            snap = self.window_state.snapshot(
                hg, timers, copy_rows=for_batcher
            )
        finally:
            for k, v in timers.items():
                self._stage(k, v)
        if snap is None:
            return None, None
        self.rows_delta_total += snap.rows_delta
        self.rows_reused_total += snap.rows_reused
        return snap.win, snap

    def _dispatch_snap(self, win, snap):
        """Dispatch one sweep. With a WindowState snapshot, the window
        stays device-resident: the delta program (once warm) donates the
        previous buffers and uploads only the delta; until it is warm the
        full-upload path reseeds residency through the plain program while
        a background thread compiles the delta program. Under a mesh the
        same discipline runs sharded: the delta scatters into per-shard
        resident buffers via voting_shard.resident_jitted."""
        if snap is None or self.batcher:
            return self._dispatch(win)
        from babble_tpu.ops import window_state as ws

        state = self.window_state
        if state.mesh is not None:
            from babble_tpu.parallel import voting_shard

            ready = voting_shard.resident_bucket_ready(state.mesh, state.key)
        else:
            ready = ws.resident_ready(state.key)
        if (
            snap.delta is not None
            and state.device is not None
            and self.async_compile
            and not ready
        ):
            self._kick_resident(state.key)
        out, _used_delta = state.dispatch(
            snap, allow_inline_compile=not self.async_compile
        )
        return out

    def _kick_resident(self, key: tuple) -> None:
        from babble_tpu.ops import window_state as ws

        mesh = self.window_state.mesh if self.window_state else None
        gate = (key, "resident", mesh is not None)
        with self._lock:
            if gate in self._compiling:
                return
            self._compiling.add(gate)

        def work() -> None:
            try:
                t0 = time.perf_counter()
                if mesh is not None:
                    from babble_tpu.parallel import voting_shard

                    voting_shard.precompile_resident(mesh, *key)
                else:
                    ws.precompile_resident(*key)
                logger.info(
                    "resident delta program ready for bucket %s (mesh=%s)"
                    " in %.1fs",
                    key, mesh is not None, time.perf_counter() - t0,
                )
            except Exception:
                logger.warning(
                    "resident precompile failed for %s", key, exc_info=True
                )
            finally:
                with self._lock:
                    self._compiling.discard(gate)

        threading.Thread(target=work, daemon=True,
                         name="resident-compile").start()

    def _launch(self, hg) -> bool:
        from babble_tpu.ops import voting

        try:
            win, snap = self._snapshot(hg, for_batcher=bool(self.batcher))
            if win is None:
                self.breaker.cancel()  # no device attempt to judge
                return True  # nothing undecided
            if self.mesh is not None:
                # resident snapshots are already mesh-aligned (WindowState
                # aligns W at rebuild); this pads the legacy/batcher path
                win = self._mesh_align(win)
            if not self._bucket_ready(win):
                if snap is not None:
                    # the snapshot's delta is committed to the mirrors but
                    # never reached the device — reseed residency later
                    self.window_state.drop_residency()
                self.breaker.cancel()
                return False
        except Exception as err:
            self._note_fallback(err)
            return False

        if self.batcher:
            # Co-located batching: the process-wide batcher coalesces this
            # window with other nodes' into ONE device dispatch + readback;
            # its own backpressure replaces the admission slots.
            from babble_tpu.hashgraph.sweep_batcher import SweepBatcher

            ticket = SweepBatcher.instance().submit(
                win, mesh=self.mesh, owner=self._copro_owner()
            )
            if ticket is None:
                # backlogged: the oracle carries this flush (same
                # economics as losing an admission slot)
                self.contended += 1
                if snap is not None:
                    self.window_state.drop_residency()
                self.breaker.cancel()
                return False
            inf = _Inflight(win, self.generation, hg.topological_index,
                            None, snap)

            def batch_reader() -> None:
                try:
                    t_r = time.perf_counter()
                    ticket.done.wait()
                    # coalesce wait + dispatch + readback, from this
                    # node's perspective
                    inf.readback_s = time.perf_counter() - t_r
                    if ticket.error is not None:
                        inf.error = ticket.error
                    else:
                        inf.result = ticket.result
                finally:
                    inf.t_done = time.perf_counter()
                    inf.done.set()

            threading.Thread(target=batch_reader, daemon=True).start()
            self._inflight = inf
            self._last_snapshot_topo = hg.topological_index
            return True

        # Admission control covers only actual device occupancy — the
        # host-side window build above runs slot-free so co-located nodes
        # aren't starved during work that never touches the device.
        try:
            slots = _inflight_slots()
            acquired = slots.acquire(blocking=False)
        except OSError as err:
            # _FlockSlots.acquire opens slot files; a vanished slot dir or
            # fd exhaustion must degrade to the oracle like every other
            # failure in this module, never kill the gossip path.
            self._note_fallback(err)
            return False
        if not acquired:
            # Device already at max in-flight sweeps (co-located nodes
            # share it): let the oracle carry this flush instead of
            # joining a readback convoy.
            self.contended += 1
            if snap is not None:
                self.window_state.drop_residency()
            self.breaker.cancel()
            return False
        inf = _Inflight(win, self.generation, hg.topological_index, slots,
                        snap)
        try:
            t_d = time.perf_counter()
            out = self._dispatch_snap(win, snap)
            self._stage("dispatch", time.perf_counter() - t_d)

            def reader() -> None:
                try:
                    t_r = time.perf_counter()
                    inf.result = voting.read_sweep(out, inf.win)
                    inf.readback_s = time.perf_counter() - t_r
                except BaseException as e:  # device/tunnel failure
                    inf.error = e
                finally:
                    inf.release_slot()
                    inf.t_done = time.perf_counter()
                    inf.done.set()

            threading.Thread(target=reader, daemon=True).start()
        except BaseException as err:
            inf.release_slot()
            if not isinstance(err, Exception):
                raise  # KeyboardInterrupt & friends propagate
            self._note_fallback(err)
            return False
        self._inflight = inf
        self._last_snapshot_topo = hg.topological_index
        return True

    def _apply(self, hg, inf: _Inflight) -> bool:
        from babble_tpu.ops import voting

        t0 = time.perf_counter()
        if inf.error is not None:
            if _is_stale_window(inf.error):
                # batcher rejected an aged-out window: neutral outcome,
                # same handling as the snap-generation check below
                self.stale_drops += 1
                self.breaker.cancel()
                return False
            self._note_fallback(inf.error)
            return False
        state = self.window_state
        if inf.snap is not None and (
            state is None or inf.snap.generation != state.generation
        ):
            # Donation/generation safety: the resident state mutated after
            # this sweep launched (rebuild, invalidate, a newer snapshot),
            # so its row maps no longer describe these results. Discard
            # them — the oracle carries this flush and the dirty state
            # rebuilds at the next snapshot.
            self.stale_drops += 1
            self.breaker.cancel()  # not the device's fault: no verdict
            return False
        try:
            fame, rr = inf.result
            _decided, fame_applied = voting.apply_fame(hg, inf.win, fame)
            received = voting.apply_round_received(hg, inf.win, rr)
        except Exception as err:
            self._note_fallback(err)
            return False
        if inf.snap is not None and state is not None:
            state.note_applied(fame_applied, received)
        t_apply = time.perf_counter() - t0
        kernel_s = inf.t_done - inf.t_launch  # dispatch+kernel+readback
        self._stage("apply", t_apply)
        self._stage("kernel", kernel_s)
        self._stage("readback", inf.readback_s)
        self.breaker.record_success()
        self.sweeps += 1
        self.last_window_events = len(inf.win.hashes)
        # Sweep cost, not launch-to-apply wall time (the latter includes
        # the idle wait for this flush and would read as the flush
        # interval in /stats).
        self.last_sweep_s = kernel_s + t_apply
        self.total_sweep_s += self.last_sweep_s
        return True

    # -- synchronous sweep ---------------------------------------------------

    def sweep(self, hg) -> bool:
        """One blocking fused sweep. Returns False when the caller must
        fall back to the oracle pipeline."""
        from babble_tpu.ops import voting

        t0 = time.perf_counter()
        try:
            win, snap = self._snapshot(hg, for_batcher=bool(self.batcher))
            if win is None:
                self.breaker.cancel()  # no device attempt to judge
                return True  # nothing undecided
            if self.mesh is not None:
                win = self._mesh_align(win)
            if not self._bucket_ready(win):
                self.breaker.cancel()
                return False
            t1 = time.perf_counter()
            if self.batcher:
                # Synchronous mode still coalesces with concurrent nodes:
                # submit and wait — co-located threads flushing in the
                # same wave share the dispatch.
                from babble_tpu.hashgraph.sweep_batcher import SweepBatcher

                ticket = SweepBatcher.instance().submit(
                    win, mesh=self.mesh, owner=self._copro_owner()
                )
                if ticket is None:
                    self.contended += 1
                    self.breaker.cancel()
                    return False
                self._stage("dispatch", time.perf_counter() - t1)
                t_r = time.perf_counter()
                if not ticket.done.wait(self.readback_timeout_s):
                    raise TimeoutError(
                        f"batched sweep exceeded {self.readback_timeout_s:.0f}s"
                    )
                if ticket.error is not None:
                    raise ticket.error
                fame, rr = ticket.result
                self._stage("readback", time.perf_counter() - t_r)
            else:
                out = self._dispatch_snap(win, snap)
                t_r = time.perf_counter()
                self._stage("dispatch", t_r - t1)
                fame, rr = voting.read_sweep(out, win)
                self._stage("readback", time.perf_counter() - t_r)
            t2 = time.perf_counter()
            self._stage("kernel", t2 - t1)
            _decided, fame_applied = voting.apply_fame(hg, win, fame)
            received = voting.apply_round_received(hg, win, rr)
            if snap is not None and self.window_state is not None:
                self.window_state.note_applied(fame_applied, received)
            self._stage("apply", time.perf_counter() - t2)
        except Exception as err:
            if _is_stale_window(err):
                self.stale_drops += 1
                self.breaker.cancel()
                return False
            self._note_fallback(err)
            return False
        self.breaker.record_success()
        self.sweeps += 1
        self.last_window_events = len(win.hashes)
        self.last_sweep_s = time.perf_counter() - t0
        self.total_sweep_s += self.last_sweep_s
        return True

    def _note_fallback(self, err: BaseException) -> None:
        # Any failure — store eviction, a tunnel dropping mid-run, a device
        # OOM — must degrade to the oracle, not kill the sync. Writebacks
        # are ordered so no partial mutation precedes a fallible read (see
        # apply_round_received), making the oracle re-run safe.
        self.fallbacks += 1
        # feed the circuit breaker: N of these within its window open it,
        # and the node stops paying for device attempts until a cooldown
        # probe succeeds (state machine in common/breaker.py)
        self.breaker.record_failure()
        if self.window_state is not None:
            # the oracle pass that follows mutates state the mirrors can't
            # track; the next snapshot must rebuild
            self.window_state.mark_dirty("fallback")
        if isinstance(err, StoreError):
            logger.warning("accelerated sweep fell back to oracle: %s", err)
        else:
            logger.warning(
                "accelerated sweep fell back to oracle",
                exc_info=(type(err), err, err.__traceback__),
            )

    def stats(self) -> dict:
        from babble_tpu.ops.device import jax_usable

        if jax_usable():
            from babble_tpu.ops import voting as _voting

            pallas = _voting.pallas_mode()
        else:
            pallas = None  # DEAD link: importing voting would import jax
        avg_ms = (
            1000.0 * self.total_sweep_s / self.sweeps if self.sweeps else 0.0
        )
        out = {
            "consensus_engine": "device",
            # which strongly-see path the sweep kernels trace: "tpu" =
            # Pallas on hardware, "interpret" = Pallas interpreter
            # (tests), None = XLA einsum
            "accel_pallas": pallas,
            "accel_batcher": bool(self.batcher),
            "accel_sweeps": self.sweeps,
            "accel_fallbacks": self.fallbacks,
            "accel_compile_waits": self.compile_waits,
            "accel_small_windows": self.small_windows,
            "accel_deferred": self.deferred,
            "accel_contended": self.contended,
            "accel_min_window": self.min_window,
            "accel_pipeline": self.pipeline,
            "accel_mesh": (
                "x".join(str(d) for d in self.mesh.devices.shape)
                if self.mesh is not None
                else None
            ),
            "accel_last_sweep_ms": round(1000.0 * self.last_sweep_s, 3),
            "accel_avg_sweep_ms": round(avg_ms, 3),
            "accel_last_window_events": self.last_window_events,
            # Per-stage breakdown (ms totals): snapshot cost is build (full
            # rebuilds) + delta_scan + pack (incremental); dispatch and
            # readback split the device leg; kernel is the legacy combined
            # dispatch→readback wall time.
            "accel_stage_ms": {
                k: round(1000.0 * v, 1) for k, v in self.stage_s.items()
            },
            # Resident-window counters: delta rows uploaded vs rows served
            # from the device-resident buffers, and how often the
            # incremental state fell back to a from-scratch rebuild.
            "accel_resident": bool(self.resident),
            "accel_rows_delta": self.rows_delta_total,
            "accel_rows_reused": self.rows_reused_total,
            "accel_rebuilds": (
                self.window_state.rebuilds
                if self.window_state is not None
                else 0
            ),
            "accel_stale_drops": self.stale_drops,
            # Mesh padding visibility: witness rows added to align W to
            # the mesh, and windows that dropped to single-device anyway
            "accel_mesh_pad_rows": self.mesh_pad_rows,
            "accel_mesh_fallbacks": self.mesh_fallbacks,
        }
        # circuit-breaker surface: accel_breaker_state/open/probes/skips/
        # failures (open = count of closed→open transitions)
        out.update(self.breaker.stats(prefix="accel_breaker_"))
        if self.batcher:
            from babble_tpu.hashgraph.sweep_batcher import SweepBatcher

            out.update(SweepBatcher.instance().stats())
        return out


def resident_default_on() -> bool:
    """Whether TensorConsensus will resolve resident=True with default
    settings (BABBLE_ACCEL_RESIDENT unset or not \"0\"). Used by prewarm
    to decide whether the resident delta programs are worth compiling."""
    import os

    return os.environ.get("BABBLE_ACCEL_RESIDENT") != "0"


def batcher_default_on() -> bool:
    """Whether TensorConsensus will resolve batcher=True with default
    settings: forced by BABBLE_ACCEL_BATCH, else pipelined (accelerator)
    mode. Used by prewarm to decide whether the batched floor bucket is
    worth compiling."""
    import os

    env = os.environ.get("BABBLE_ACCEL_BATCH")
    if env is not None:
        return env == "1"
    from babble_tpu.ops.device import jax_usable, on_accelerator

    return jax_usable() and on_accelerator()


def prewarm_buckets(n_peers: int, background: bool = True, mesh=None):
    """Compile (or load from the persistent XLA cache) the window-shape
    buckets a freshly started node is most likely to hit, so the first
    real backlog meets warm kernels instead of a compile wait. Called from
    Node.init when --accelerator is on; runs in a daemon thread by default
    (compiles happen in XLA's C++ with the GIL released). With a mesh,
    the SHARDED kernels are warmed too (separate jit cache)."""
    from babble_tpu.ops import voting

    P = voting._bucket_mult(n_peers, 8)
    S = 1
    buckets = [
        (16, 32, P, S, 8),
        (16, 64, P, S, 8),
        (32, 128, P, S, 8),
        (64, 256, P, S, 8),
        (64, 256, P, S, 16),
        (64, 512, P, S, 16),
        (128, 512, P, S, 16),
        (128, 1024, P, S, 16),
    ]
    if n_peers >= 12:
        # sustained backlogs at 16+ validators accumulate rounds past the
        # R=16 bucket before decisions drain; compiling R=32 up front keeps
        # mid-run compiles (and their single-core steal) off the bench
        # path. Small clusters never hit these shapes — skipping them
        # keeps their prewarm cheap.
        buckets += [
            (128, 1024, P, S, 32),
            (256, 1024, P, S, 32),
        ]

    def work() -> None:
        if mesh is None and batcher_default_on():
            # Seed the co-located batcher: compile the B=MAX_BATCH floor
            # bucket and pin it as the batcher's target floor, so the
            # FIRST flush wave meets a warm batched program instead of a
            # compile kick (the monotone target then stays inside this
            # shape until windows genuinely outgrow it).
            from babble_tpu.hashgraph.sweep_batcher import SweepBatcher

            floor = (
                (128, 1024, P, 1, 32) if n_peers >= 12 else (64, 512, P, 1, 16)
            )
            svc = SweepBatcher.instance()
            if svc.floor_key is None or tuple(
                max(a, b) for a, b in zip(svc.floor_key, floor)
            ) != svc.floor_key:
                try:
                    voting.precompile_batched(SweepBatcher.MAX_BATCH, *floor)
                    svc.floor_key = floor
                except Exception:
                    logger.warning(
                        "batched floor prewarm failed for %s", floor,
                        exc_info=True,
                    )
        for key in buckets:
            if mesh is not None:
                # the sharded kernel is the only one _dispatch will ever
                # run for this bucket — don't burn compile time (and
                # device contention) on the unused single-device program.
                # Buckets whose W the mesh doesn't divide are warmed at
                # the shape _mesh_align pads them to.
                from babble_tpu.parallel import voting_shard

                n = int(mesh.devices.size)
                W_m = key[0]
                while W_m % n:
                    W_m *= 2
                key = (W_m,) + key[1:]
                if not voting_shard.bucket_ready(mesh, key):
                    try:
                        voting_shard.precompile(mesh, *key)
                    except Exception:
                        logger.warning(
                            "mesh prewarm failed for %s", key, exc_info=True
                        )
                if resident_default_on() and not batcher_default_on():
                    # the mesh resident delta program is a separate
                    # executable, same rationale as the single-device one
                    if not voting_shard.resident_bucket_ready(mesh, key):
                        try:
                            voting_shard.precompile_resident(mesh, *key)
                        except Exception:
                            logger.warning(
                                "mesh resident prewarm failed for %s", key,
                                exc_info=True,
                            )
            elif not voting.bucket_ready(key):
                try:
                    voting.precompile(*key)
                except Exception:
                    logger.warning(
                        "prewarm failed for %s", key, exc_info=True
                    )
            if mesh is None and resident_default_on() and not batcher_default_on():
                # resident delta program for the same bucket (a separate
                # executable): first delta sweeps then meet a warm
                # program instead of riding full uploads while a
                # background compile catches up. With the batcher on,
                # sweeps ride the vmapped program and the resident
                # executable would never run — don't burn compiles on it.
                from babble_tpu.ops import window_state as ws

                if not ws.resident_ready(key):
                    try:
                        ws.precompile_resident(*key)
                    except Exception:
                        logger.warning(
                            "resident prewarm failed for %s", key,
                            exc_info=True,
                        )

    if background:
        t = threading.Thread(target=work, daemon=True, name="voting-prewarm")
        t.start()
        return t
    work()
    return None
