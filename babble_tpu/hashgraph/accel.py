"""TensorConsensus — drives the device voting kernels for a live Hashgraph.

Attached to a Hashgraph by the node's core when ``--accelerator`` is on.
``Hashgraph.insert_event_and_run_consensus`` then defers DecideFame /
DecideRoundReceived to batched device sweeps (the reference runs them per
insert, hashgraph.go:644-668; here a sweep covers a whole sync batch so
device dispatch amortizes across the gossip round — SURVEY.md hard-part 6).

A sweep:
1. snapshots the undecided window (``ops.voting.build_voting_window``),
2. runs fame on device, applies it host-side with the oracle's sticky
   round-decided bookkeeping,
3. runs round-received on device with the host-stamped decided mask,
4. leaves frame/block construction to the untouched oracle
   (``process_decided_rounds``).

Any store eviction or snapshot failure falls back to the oracle sweep for
that round — consensus output is identical either way, and the node keeps
running; the ``fallbacks`` counter surfaces it in /stats.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from babble_tpu.common.errors import StoreError

logger = logging.getLogger("babble_tpu.hashgraph.accel")


class TensorConsensus:
    def __init__(self, sweep_events: int = 256, async_compile: bool = True,
                 min_window: int | None = None):
        # Force a sweep mid-batch once this many inserts accumulate, so the
        # window tensors stay inside one shape bucket even under huge syncs.
        # Normal cadence is one sweep per gossip round (core.sync flush).
        self.sweep_events = sweep_events
        # Crossover threshold: below this many undetermined events the
        # incremental oracle beats the sweep's fixed dispatch cost, so small
        # windows stay on the host and the device takes over exactly when
        # the oracle's O(witnesses² · rounds) voting would start to crawl.
        # None = resolve on first use (lower on a real accelerator, higher
        # on the CPU-XLA fallback). 0 forces the device path (tests).
        self.min_window = min_window
        # Compile window-shape buckets off the consensus thread: the first
        # sweep of a new bucket would otherwise stall gossip for the XLA
        # compile (seconds on CPU, tens of seconds cold on TPU) while
        # holding the core lock. Until a bucket's kernels are ready the
        # oracle carries consensus — output is identical either way.
        self.async_compile = async_compile
        self.sweeps = 0
        self.fallbacks = 0
        self.compile_waits = 0
        self.small_windows = 0  # flushes routed to the oracle by min_window
        self.last_sweep_s = 0.0
        self.total_sweep_s = 0.0
        self.last_window_events = 0
        # Per-stage rolling sums (seconds) for /debug and bench breakdowns.
        self.stage_s = {"build": 0.0, "fame": 0.0, "apply": 0.0,
                        "mask": 0.0, "rr": 0.0}
        self._ready = set()
        self._compiling = set()
        self._lock = threading.Lock()

    def should_sweep(self, pending_inserts: int) -> bool:
        return pending_inserts >= self.sweep_events

    def use_device(self, undetermined: int) -> bool:
        """Window-size gate: route small windows to the oracle."""
        if self.min_window is None:
            import os

            from babble_tpu.ops.device import is_cpu_fallback

            env = os.environ.get("BABBLE_ACCEL_MIN_WINDOW")
            if env is not None:
                self.min_window = int(env)
            else:
                self.min_window = 256 if is_cpu_fallback() else 64
        if undetermined >= self.min_window:
            return True
        self.small_windows += 1
        return False

    @staticmethod
    def _bucket(win) -> tuple:
        return (
            win.n_witnesses,
            win.n_events,
            win.member.shape[1],
            win.member.shape[0],
            win.psi.shape[0],
        )

    def _compile_bucket(self, key: tuple) -> None:
        from babble_tpu.ops import voting

        try:
            t0 = time.perf_counter()
            voting.precompile(*key)
            logger.info(
                "voting kernels ready for bucket %s in %.1fs",
                key,
                time.perf_counter() - t0,
            )
            with self._lock:
                self._ready.add(key)
        except Exception:
            # Leave the bucket un-ready so a later sweep retries the
            # background compile instead of stalling inline on it.
            logger.warning("bucket %s precompile failed", key, exc_info=True)
        finally:
            with self._lock:
                self._compiling.discard(key)

    def sweep(self, hg) -> bool:
        """One fame + round-received sweep. Returns False when the caller
        must fall back to the oracle pipeline."""
        from babble_tpu.ops import voting

        t0 = time.perf_counter()
        try:
            win = voting.build_voting_window(hg)
            if win is None:
                return True  # nothing undecided
            if self.async_compile:
                key = self._bucket(win)
                with self._lock:
                    ready = key in self._ready
                    kick = not ready and key not in self._compiling
                    if kick:
                        self._compiling.add(key)
                if kick:
                    threading.Thread(
                        target=self._compile_bucket, args=(key,), daemon=True
                    ).start()
                if not ready:
                    self.compile_waits += 1
                    return False  # oracle carries this sweep
            t1 = time.perf_counter()
            self.stage_s["build"] += t1 - t0
            see, fame = voting.run_fame(win)
            t2 = time.perf_counter()
            self.stage_s["fame"] += t2 - t1
            voting.apply_fame(hg, win, fame)
            t3 = time.perf_counter()
            self.stage_s["apply"] += t3 - t2
            decided, hard_block = voting.round_masks(hg, win)
            t4 = time.perf_counter()
            self.stage_s["mask"] += t4 - t3
            if decided.any():
                # Receiving requires a decided round; with none in the
                # window the kernel would return all -1, so skip the call.
                rr = voting.run_round_received(win, see, fame, decided,
                                               hard_block)
                t5 = time.perf_counter()
                self.stage_s["rr"] += t5 - t4
                voting.apply_round_received(hg, win, rr)
        except Exception as err:
            # Any failure — store eviction, a tunnel dropping mid-run, a
            # device OOM — must degrade to the oracle, not kill the sync.
            # Writebacks are ordered so no partial mutation precedes a
            # fallible read (see apply_round_received), making the oracle
            # re-run safe.
            self.fallbacks += 1
            if isinstance(err, StoreError):
                logger.warning("accelerated sweep fell back to oracle: %s", err)
            else:
                logger.warning(
                    "accelerated sweep fell back to oracle", exc_info=True
                )
            return False
        self.sweeps += 1
        self.last_window_events = len(win.hashes)
        self.last_sweep_s = time.perf_counter() - t0
        self.total_sweep_s += self.last_sweep_s
        return True

    def stats(self) -> dict:
        avg_ms = (
            1000.0 * self.total_sweep_s / self.sweeps if self.sweeps else 0.0
        )
        return {
            "consensus_engine": "device",
            "accel_sweeps": self.sweeps,
            "accel_fallbacks": self.fallbacks,
            "accel_compile_waits": self.compile_waits,
            "accel_small_windows": self.small_windows,
            "accel_min_window": self.min_window,
            "accel_last_sweep_ms": round(1000.0 * self.last_sweep_s, 3),
            "accel_avg_sweep_ms": round(avg_ms, 3),
            "accel_last_window_events": self.last_window_events,
            "accel_stage_ms": {
                k: round(1000.0 * v, 1) for k, v in self.stage_s.items()
            },
        }
