"""Frame and Root — consensus checkpoints (reference: src/hashgraph/frame.go,
root.go). A Frame is a self-contained restart point: the peer-set history,
per-participant Roots (last ROOT_DEPTH consensus events), and the events
received at one round."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from babble_tpu.crypto.canonical import canonical_dumps
from babble_tpu.crypto.hashing import sha256
from babble_tpu.hashgraph.event import FrameEvent, sort_frame_events
from babble_tpu.peers.peer import Peer
from babble_tpu.peers.peer_set import PeerSet


@dataclass
class Root:
    """Base on top of which a participant's events can be inserted,
    sorted by Lamport timestamp (reference: root.go:13-28)."""

    events: List[FrameEvent] = field(default_factory=list)

    def insert(self, fe: FrameEvent) -> None:
        self.events.append(fe)

    def to_dict(self) -> dict:
        return {"Events": [fe.to_dict() for fe in self.events]}

    @staticmethod
    def from_dict(d: dict) -> "Root":
        return Root(events=[FrameEvent.from_dict(e) for e in d.get("Events") or []])


@dataclass
class Frame:
    """reference: frame.go:13-20."""

    round: int  # round received
    peers: PeerSet  # authoritative peer-set at this round
    roots: Dict[str, Root]  # participant pubkey hex => Root
    events: List[FrameEvent]  # events with round_received == round
    peer_sets: Dict[int, List[Peer]]  # full peer-set history: round => peers
    timestamp: int  # BFT median of famous-witness timestamps

    def sorted_frame_events(self) -> List[FrameEvent]:
        """All events incl. roots', in consensus order (reference: frame.go:24-32)."""
        out: List[FrameEvent] = []
        for r in self.roots.values():
            out.extend(r.events)
        out.extend(self.events)
        return sort_frame_events(out)

    def to_dict(self) -> dict:
        return {
            "Round": self.round,
            "Peers": [p.to_dict() for p in self.peers.peers],
            "Roots": {k: r.to_dict() for k, r in self.roots.items()},
            "Events": [fe.to_dict() for fe in self.events],
            "PeerSets": {
                str(rnd): [p.to_dict() for p in ps]
                for rnd, ps in self.peer_sets.items()
            },
            "Timestamp": self.timestamp,
        }

    def hash(self) -> bytes:
        """SHA256 of the canonical encoding (reference: frame.go:63-69)."""
        return sha256(canonical_dumps(self.to_dict()))

    @staticmethod
    def from_dict(d: dict) -> "Frame":
        return Frame(
            round=d["Round"],
            peers=PeerSet([Peer.from_dict(p) for p in d.get("Peers") or []]),
            roots={k: Root.from_dict(r) for k, r in (d.get("Roots") or {}).items()},
            events=[FrameEvent.from_dict(e) for e in d.get("Events") or []],
            peer_sets={
                int(rnd): [Peer.from_dict(p) for p in ps]
                for rnd, ps in (d.get("PeerSets") or {}).items()
            },
            timestamp=d["Timestamp"],
        )
