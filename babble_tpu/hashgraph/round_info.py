"""RoundInfo — per-round record of created/received events and fame state
(reference: src/hashgraph/roundInfo.go:11-154)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from babble_tpu.common.trilean import Trilean
from babble_tpu.peers.peer_set import PeerSet


@dataclass
class RoundEvent:
    """Witness/fame state of one event (reference: roundInfo.go:17-20)."""

    witness: bool = False
    famous: Trilean = Trilean.UNDEFINED


class RoundInfo:
    """reference: roundInfo.go:23-30. ``decided`` is sticky: once a round is
    decided it stays decided even if new witnesses appear later
    (roundInfo.go:73-96)."""

    def __init__(self) -> None:
        self.created_events: Dict[str, RoundEvent] = {}
        self.received_events: List[str] = []
        self.decided: bool = False

    def add_created_event(self, x: str, witness: bool) -> None:
        """First write wins (reference: roundInfo.go:41-48)."""
        if x not in self.created_events:
            self.created_events[x] = RoundEvent(witness=witness)

    def add_received_event(self, x: str) -> None:
        self.received_events.append(x)

    def set_fame(self, x: str, famous: bool) -> None:
        """reference: roundInfo.go:56-71."""
        e = self.created_events.get(x)
        if e is None:
            e = RoundEvent(witness=True)
            self.created_events[x] = e
        e.famous = Trilean.TRUE if famous else Trilean.FALSE

    def witnesses_decided(self, peer_set: PeerSet) -> bool:
        """True when a super-majority of witnesses are decided and none are
        undecided (reference: roundInfo.go:78-96)."""
        if self.decided:
            return True
        c = 0
        for e in self.created_events.values():
            if e.witness and e.famous != Trilean.UNDEFINED:
                c += 1
            elif e.witness and e.famous == Trilean.UNDEFINED:
                return False
        self.decided = c >= peer_set.super_majority()
        return self.decided

    def witnesses(self) -> List[str]:
        return [x for x, e in self.created_events.items() if e.witness]

    def famous_witnesses(self) -> List[str]:
        return [
            x
            for x, e in self.created_events.items()
            if e.witness and e.famous == Trilean.TRUE
        ]

    def is_decided(self, witness: str) -> bool:
        e = self.created_events.get(witness)
        return e is not None and e.witness and e.famous != Trilean.UNDEFINED

    def to_dict(self) -> dict:
        return {
            "CreatedEvents": {
                x: {"Witness": e.witness, "Famous": int(e.famous)}
                for x, e in self.created_events.items()
            },
            "ReceivedEvents": list(self.received_events),
        }

    @staticmethod
    def from_dict(d: dict) -> "RoundInfo":
        r = RoundInfo()
        for x, e in (d.get("CreatedEvents") or {}).items():
            r.created_events[x] = RoundEvent(
                witness=e["Witness"], famous=Trilean(e["Famous"])
            )
        r.received_events = list(d.get("ReceivedEvents") or [])
        return r
