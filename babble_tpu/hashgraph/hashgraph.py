"""Hashgraph — the consensus engine.

This is the CPU-reference oracle for the TPU kernels (SURVEY.md §7 step 3):
an exact re-implementation of the reference pipeline semantics —
``insert_event → divide_rounds → decide_fame → decide_round_received →
process_decided_rounds`` — against which ``babble_tpu.ops.dag`` is
differential-tested on the golden DAGs.

Reference mapping (file:line into /root/reference/src/hashgraph/hashgraph.go):
- predicates ancestor/selfAncestor/see/stronglySee: 96-206
- round / witness / lamportTimestamp: 208-327, 343-387
- coordinates maintenance: 445-519
- insert path with fork checks: 672-750; trusted frame-event insert: 754-802
- DivideRounds: 807-872; DecideFame incl. coin rounds: 875-998
- DecideRoundReceived: 1002-1095; ProcessDecidedRounds/GetFrame: 1100-1289
- sig pool / anchor block: 1295-1408; Reset/Bootstrap: 1431-1536
- wire conversion: 1538-1595; CheckBlock: 1599-1630
"""

from __future__ import annotations

import logging
import os
from typing import Callable, Dict, List, Optional

import numpy as np

from babble_tpu.common.errors import StoreError, StoreErrorKind, is_store_err
from babble_tpu.common.lru import LRU
from babble_tpu.common.utils import median_int
from babble_tpu.hashgraph.block import Block
from babble_tpu.hashgraph.caches import PendingRound, PendingRoundsCache, SigPool
from babble_tpu.hashgraph.errors import (
    ForkError,
    InvalidSignatureError,
    SelfParentError,
    UnknownParentError,
    UnknownParticipantError,
    is_normal_self_parent_error,
)
from babble_tpu.hashgraph.event import (
    Event,
    EventBody,
    EventCoordinates,
    FrameEvent,
    WireEvent,
    decode_hash,
    sort_frame_events,
)
from babble_tpu.hashgraph.frame import Frame, Root
from babble_tpu.hashgraph.round_info import RoundInfo
from babble_tpu.hashgraph.store import Store
from babble_tpu.obs.trace import staged
from babble_tpu.peers.peer_set import PeerSet

logger = logging.getLogger("babble_tpu.hashgraph")

# How many FrameEvents are included in a Root. Must be identical across
# peers or they produce different Frames/Blocks (reference: hashgraph.go:15-22).
ROOT_DEPTH = 10

# Frequency of coin rounds in the fame decision (reference: hashgraph.go:24-25).
COIN_ROUND_FREQ = 4

# Verbose per-event rejection logging, resolved once at import: the old
# per-reject `import os` + env read sat inside the hot insert path.
_DEBUG_REJECTS = bool(os.environ.get("BABBLE_DEBUG_REJECTS"))

# InternalCommitCallback: commits a block; the node's core layer processes
# the commit response (reference: hashgraph.go:1677-1688).
CommitCallback = Callable[[Block], None]


def dummy_commit_callback(block: Block) -> None:
    """reference: hashgraph.go:1687-1689."""


# Strongly-see sentinel coordinates: a missing last-ancestor /
# first-descendant entry must never satisfy ``la >= fd``, whatever the
# real (non-negative) indexes are.
_LA_MISSING = -(2**62)
_FD_MISSING = 2**62


class _RoundCtx:
    """Per-round data resolved ONCE and reused across the whole ingest
    batch: the round's peer-set columns, super-majority, witness list, and
    the witnesses' first-descendant coordinates as one dense matrix. This
    turns the per-event ``strongly_see`` loop in ``_round`` (and the
    per-voter loop in DecideFame's oracle) into a single vectorized
    compare — the dict-walk version is the profiled host-tail hotspot.

    Invalidation: a witness added to the round (divide_rounds /
    insert_frame_event) or a cached witness's first_descendants mutating
    (the insert-time walk) drops the entry; a peer-set object swap or a
    created-event count change is caught at lookup time."""

    __slots__ = ("peer_set", "sm", "col", "wits", "wit_set", "fd",
                 "n_created")

    def __init__(self, peer_set, wits, fd, n_created):
        self.peer_set = peer_set
        self.sm = peer_set.super_majority()
        self.col = {pk: i for i, pk in enumerate(peer_set.pub_keys())}
        self.wits = wits
        self.wit_set = frozenset(wits)
        self.fd = fd  # int64 [n_wit, n_peers], missing = _FD_MISSING
        self.n_created = n_created


def middle_bit(ehex: str) -> bool:
    """Pseudo-random bit for coin rounds: the middle byte of the event hash,
    False iff zero (reference: hashgraph.go:1666-1675)."""
    hash_ = decode_hash(ehex)
    if len(hash_) > 0 and hash_[len(hash_) // 2] == 0:
        return False
    return True


class Hashgraph:
    """DAG of events + methods extracting a total consensus order of
    transactions onto a blockchain (reference: hashgraph.go:30-80)."""

    def __init__(
        self,
        store: Store,
        commit_callback: CommitCallback = dummy_commit_callback,
    ):
        self.store = store
        # FIFO of events whose consensus order is not yet determined.
        self.undetermined_events: List[str] = []
        # Subset of undetermined_events still awaiting round/lamport
        # assignment — only fresh inserts land here, so divide_rounds scans
        # the new tail instead of re-fetching the whole backlog (the
        # reference rescans UndeterminedEvents, hashgraph.go:807-812; the
        # skip condition there is exactly "round and lamport already set",
        # which for us is "not in this list").
        self._round_pending: List[str] = []
        self.pending_rounds = PendingRoundsCache()
        self.pending_signatures = SigPool()
        self.last_consensus_round: Optional[int] = None
        self.first_consensus_round: Optional[int] = None
        self.anchor_block: Optional[int] = None
        self.round_lower_bound: Optional[int] = None  # fast-sync boundary
        # Checkpoint-prune retention floor (lifecycle tier): rounds below
        # it have been compacted out of the store. None = never pruned.
        self.prune_floor: Optional[int] = None
        # Lowest round the next prune pass needs to re-examine — rounds
        # below it were either dropped or fell below a previous floor
        # with every created event already gone.
        self._prune_scan_base = 0
        self.last_committed_round_events = 0
        self.consensus_transactions = 0
        self.pending_loaded_events = 0
        self.commit_callback = commit_callback
        self.topological_index = 0
        # Device consensus offload (TensorConsensus), attached by the node's
        # core when --accelerator is on. When set, DecideFame and
        # DecideRoundReceived run as batched device sweeps instead of per
        # insert; inserts between sweeps are counted in _accel_pending.
        self.accel = None
        self._accel_pending = 0
        # Pipeline-stage observer (obs.telemetry): fn(stage, seconds)
        # feeding the sync_stage_seconds histogram + the active sync
        # trace. None (bare hashgraphs, BABBLE_OBS=0) keeps the staged
        # methods clockless — the decorator checks this attribute.
        self.stage_observer = None
        # Delta channels for the accelerator's incremental WindowState
        # (ops/window_state.py): the insert path records the two mutations
        # a window snapshot cannot otherwise discover in O(ΔE) — witnesses
        # minted by divide_rounds (possibly into OLD rounds, via laggards)
        # and post-insert first_descendant updates on already-stored
        # events. Collection is gated on _accel_track_delta, which the
        # TensorConsensus sets once it resolves its resident mode, so the
        # channels cost nothing on the oracle path and can never grow
        # unconsumed.
        self._accel_track_delta = False
        self._accel_new_witnesses: List[tuple] = []  # (round, hash)
        self._accel_fd_dirty: set = set()  # event hashes with new fds

        cs = store.cache_size()
        self._ancestor_cache = LRU(cs)
        self._self_ancestor_cache = LRU(cs)
        self._strongly_see_cache = LRU(cs)
        self._round_cache = LRU(cs)
        self._timestamp_cache = LRU(cs)
        self._witness_cache = LRU(cs)
        # round -> _RoundCtx, consulted by _round/_witness on every insert.
        # Entries self-validate against the round's created-event count and
        # peer-set identity; the only mutation that check cannot catch — a
        # cached witness gaining a first-descendant entry — is invalidated
        # explicitly in _update_ancestor_first_descendant.
        self._round_ctx: Dict[int, _RoundCtx] = {}

    def init(self, peer_set: PeerSet) -> None:
        """Set the genesis peer-set at round 0 (reference: hashgraph.go:84-89).

        A store recycled from disk already carries round 0 — the reference
        drops Init's KeyAlreadyExists on that path (core.go:137 ignores
        the error), so this does too."""
        try:
            self.store.set_peer_set(0, peer_set)
        except StoreError as err:
            if not is_store_err(err, StoreErrorKind.KEY_ALREADY_EXISTS):
                raise

    # =========================================================================
    # DAG predicates
    # =========================================================================

    def ancestor(self, x: str, y: str) -> bool:
        """True if y is an ancestor of x — O(1) via lastAncestors
        (reference: hashgraph.go:96-128)."""
        k = (x, y)
        v, ok = self._ancestor_cache.get(k)
        if ok:
            return v
        a = self._ancestor(x, y)
        self._ancestor_cache.add(k, a)
        return a

    def _ancestor(self, x: str, y: str) -> bool:
        if x == y:
            return True
        ex = self.store.get_event(x)
        ey = self.store.get_event(y)
        entry = ex.last_ancestors.get(ey.creator())
        return entry is not None and entry.index >= ey.index()

    def self_ancestor(self, x: str, y: str) -> bool:
        """True if y is a self-ancestor of x (reference: hashgraph.go:131-158)."""
        if x == y:
            # Identity holds without store access (the events may be evicted).
            return True
        k = (x, y)
        v, ok = self._self_ancestor_cache.get(k)
        if ok:
            return v
        ex = self.store.get_event(x)
        ey = self.store.get_event(y)
        a = ex.creator() == ey.creator() and ex.index() >= ey.index()
        self._self_ancestor_cache.add(k, a)
        return a

    def see(self, x: str, y: str) -> bool:
        """Fork detection is unnecessary here because insert_event prevents
        two events at the same height per creator (reference: hashgraph.go:160-169)."""
        return self.ancestor(x, y)

    def strongly_see(self, x: str, y: str, peers: PeerSet) -> bool:
        """x strongly sees y: the count of peers p with
        x.lastAncestors[p] >= y.firstDescendants[p] reaches a super-majority
        (reference: hashgraph.go:172-206)."""
        k = (x, y, peers.hash())
        v, ok = self._strongly_see_cache.get(k)
        if ok:
            return v
        ss = self._strongly_see(x, y, peers)
        self._strongly_see_cache.add(k, ss)
        return ss

    def _strongly_see(self, x: str, y: str, peers: PeerSet) -> bool:
        ex = self.store.get_event(x)
        ey = self.store.get_event(y)
        c = 0
        for p in peers.pub_keys():
            xla = ex.last_ancestors.get(p)
            yfd = ey.first_descendants.get(p)
            if xla is not None and yfd is not None and xla.index >= yfd.index:
                c += 1
        return c >= peers.super_majority()

    def _build_round_ctx(self, peer_set, wits, n_created) -> _RoundCtx:
        """Densify the witnesses' first-descendant coordinates into one
        int64 matrix so strongly-see against ALL of a round's witnesses is
        a single vectorized compare (the exact computation the device
        voting window performs on its fd/la tables — see ops/voting)."""
        fd = np.full(
            (len(wits), len(peer_set.pub_keys())), _FD_MISSING, dtype=np.int64
        )
        col = {pk: i for i, pk in enumerate(peer_set.pub_keys())}
        for i, w in enumerate(wits):
            for p, e in self.store.get_event(w).first_descendants.items():
                j = col.get(p)
                if j is not None:
                    fd[i, j] = e.index
        return _RoundCtx(peer_set, wits, fd, n_created)

    def _round_ctx_for(self, r: int, round_info, peer_set) -> _RoundCtx:
        """Cached per-round ctx, revalidated cheaply on every lookup: a
        created-event count change forces a witness-list recompute, and a
        changed witness list (or peer-set swap) forces a matrix rebuild.
        When only non-witness events were added, the ctx survives with its
        count refreshed — the common case on the hot insert path."""
        ctx = self._round_ctx.get(r)
        n_created = len(round_info.created_events)
        if ctx is not None and ctx.peer_set is peer_set:
            if ctx.n_created == n_created:
                return ctx
            wits = round_info.witnesses()
            if ctx.wits == wits:
                ctx.n_created = n_created
                return ctx
        else:
            wits = round_info.witnesses()
        ctx = self._build_round_ctx(peer_set, wits, n_created)
        if len(self._round_ctx) >= 128:
            # Consensus advances monotonically; old rounds stop being
            # parent rounds, so prune from the bottom.
            for k in sorted(self._round_ctx)[:64]:
                del self._round_ctx[k]
        self._round_ctx[r] = ctx
        return ctx

    def _strongly_seen_mask(self, x: str, ctx: _RoundCtx):
        """Boolean mask over ctx.wits: which witnesses x strongly sees.
        Missing-coordinate sentinels guarantee ``la >= fd`` is False when
        either side is absent, for any real (non-negative) index."""
        ex = self.store.get_event(x)
        la = np.full((len(ctx.col),), _LA_MISSING, dtype=np.int64)
        for p, e in ex.last_ancestors.items():
            j = ctx.col.get(p)
            if j is not None:
                la[j] = e.index
        return (la[None, :] >= ctx.fd).sum(axis=1) >= ctx.sm

    # =========================================================================
    # Round / witness / timestamps
    # =========================================================================

    def round(self, x: str) -> int:
        v, ok = self._round_cache.get(x)
        if ok:
            return v
        r = self._round(x)
        self._round_cache.add(x, r)
        return r

    def round_diff(self, x: str, y: str) -> int:
        """round(x) - round(y) (reference: hashgraph.go:329-341)."""
        return self.round(x) - self.round(y)

    def _round(self, x: str) -> int:
        """Parent round, +1 if x strongly sees a super-majority of
        parent-round witnesses (reference: hashgraph.go:220-282)."""
        ex = self.store.get_event(x)
        if ex.round is not None:
            # Already assigned (divide_rounds / frame insert / annotated
            # reload) — rounds are write-once, so this is the value the
            # recursion would rebuild, and it keeps the walk from
            # descending into parents compaction may have dropped.
            return ex.round

        parent_round = -1
        if ex.self_parent() != "":
            parent_round = self.round(ex.self_parent())
        if ex.other_parent() != "":
            op_round = self.round(ex.other_parent())
            if op_round > parent_round:
                parent_round = op_round

        if parent_round == -1:
            return 0

        round_ = parent_round
        parent_round_obj = self.store.get_round(parent_round)
        parent_round_peer_set = self.store.get_peer_set(parent_round)

        # One vectorized compare against the round's witness fd matrix
        # replaces the per-witness strongly_see loop — the profiled host
        # tail of divide_rounds (thousands of dict walks per ingest batch).
        ctx = self._round_ctx_for(
            parent_round, parent_round_obj, parent_round_peer_set
        )
        c = int(self._strongly_seen_mask(x, ctx).sum()) if ctx.wits else 0
        if c >= parent_round_peer_set.super_majority():
            round_ += 1
        return round_

    def witness(self, x: str) -> bool:
        v, ok = self._witness_cache.get(x)
        if ok:
            return v
        r = self._witness(x)
        self._witness_cache.add(x, r)
        return r

    def _witness(self, x: str) -> bool:
        """First event of a round for a creator belonging to that round's
        peer-set (reference: hashgraph.go:297-327)."""
        ex = self.store.get_event(x)
        x_round = self.round(x)
        peer_set = self.store.get_peer_set(x_round)
        if ex.creator() not in peer_set.by_pub_key:
            return False
        sp_round = -1
        if ex.self_parent() != "":
            sp_round = self.round(ex.self_parent())
        return x_round > sp_round

    def round_received(self, x: str) -> int:
        ex = self.store.get_event(x)
        return ex.round_received if ex.round_received is not None else -1

    def lamport_timestamp(self, x: str) -> int:
        v, ok = self._timestamp_cache.get(x)
        if ok:
            return v
        r = self._lamport_timestamp(x)
        self._timestamp_cache.add(x, r)
        return r

    def _lamport_timestamp(self, x: str) -> int:
        """max(parents' timestamps) + 1; an unknown other-parent contributes
        nothing (reference: hashgraph.go:355-387)."""
        ex = self.store.get_event(x)
        if ex.lamport_timestamp is not None:
            # Write-once, same rationale as _round's short-circuit.
            return ex.lamport_timestamp
        plt = -1
        if ex.self_parent() != "":
            plt = self.lamport_timestamp(ex.self_parent())
        if ex.other_parent() != "":
            try:
                self.store.get_event(ex.other_parent())
            except StoreError:
                pass
            else:
                op_lt = self.lamport_timestamp(ex.other_parent())
                if op_lt > plt:
                    plt = op_lt
        return plt + 1

    # =========================================================================
    # Insert path
    # =========================================================================

    def _check_self_parent(self, event: Event) -> None:
        """The self-parent must be the creator's last known event — this is
        what structurally prevents forks (reference: hashgraph.go:405-429).

        On a mismatch, the occupied (creator, index) slot distinguishes
        three cases the reference folds into one "normal" error:

        - same hash at the slot → a benign concurrent duplicate insert;
        - a DIFFERENT hash at the slot → equivocation. The incoming
          event's signature was already verified (insert_event checks it
          first), and the stored branch was verified at its own insert,
          so the pair is cryptographic proof of a fork — raised as
          :class:`ForkError` carrying both events for the sentry;
        - empty slot (index gap / stale parent) → the benign race.

        The reference dropped the second branch silently and kept
        gossiping with the attacker; here the evidence surfaces."""
        self_parent = event.self_parent()
        creator = event.creator()
        try:
            creator_last_known = self.store.last_event_from(creator)
        except StoreError as err:
            if is_store_err(err, StoreErrorKind.EMPTY) and self_parent == "":
                return  # first event
            raise SelfParentError(str(err), normal=False)
        if self_parent != creator_last_known:
            occupant = None
            try:
                occupant = self.store.participant_event(creator, event.index())
            except StoreError:
                pass
            if occupant is not None and occupant != event.hex():
                existing = None
                try:
                    existing = self.store.get_event(occupant)
                except StoreError:
                    pass
                raise ForkError(creator, event.index(), existing, event)
            # Expected under concurrent duplicate inserts — a "normal" error
            # (reference: errors.go:24-32, hashgraph.go:419-428).
            raise SelfParentError(
                "self-parent not last known event by creator", normal=True
            )

    def _check_other_parent(self, event: Event) -> None:
        """reference: hashgraph.go:432-442."""
        other_parent = event.other_parent()
        if other_parent != "":
            try:
                self.store.get_event(other_parent)
            except StoreError:
                raise UnknownParentError("other-parent not known")

    def _init_event_coordinates(self, event: Event) -> None:
        """lastAncestors = element-wise max of parents' lastAncestors;
        firstDescendants/lastAncestors get the event itself for its creator
        (reference: hashgraph.go:445-483)."""
        event.last_ancestors = {}
        event.first_descendants = {}

        self_parent: Optional[Event] = None
        other_parent: Optional[Event] = None
        try:
            self_parent = self.store.get_event(event.self_parent())
        except StoreError:
            pass
        try:
            other_parent = self.store.get_event(event.other_parent())
        except StoreError:
            pass

        if self_parent is None and other_parent is not None:
            event.last_ancestors = dict(other_parent.last_ancestors)
        elif other_parent is None and self_parent is not None:
            event.last_ancestors = dict(self_parent.last_ancestors)
        elif self_parent is not None and other_parent is not None:
            event.last_ancestors = dict(self_parent.last_ancestors)
            for p, ola in other_parent.last_ancestors.items():
                sla = event.last_ancestors.get(p)
                if sla is None or sla.index < ola.index:
                    event.last_ancestors[p] = EventCoordinates(ola.hash, ola.index)

        me = EventCoordinates(event.hex(), event.index())
        event.first_descendants[event.creator()] = me
        event.last_ancestors[event.creator()] = me

    def _update_ancestor_first_descendant(self, event: Event) -> None:
        """Walk each last-ancestor's self-parent chain, recording this event
        as first descendant, stopping at witnesses or already-filled entries
        (reference: hashgraph.go:486-519)."""
        creator = event.creator()
        coords = EventCoordinates(event.hex(), event.index())
        for c in list(event.last_ancestors.values()):
            ah = c.hash
            while True:
                try:
                    a = self.store.get_event(ah)
                except StoreError:
                    break
                if creator not in a.first_descendants:
                    a.first_descendants[creator] = coords
                    self.store.set_event(a)
                    if self._accel_track_delta:
                        self._accel_fd_dirty.add(ah)
                    # A cached round-ctx matrix snapshots witness fds; this
                    # is the one mutation its count check cannot see.
                    if a.round is not None:
                        ctx = self._round_ctx.get(a.round)
                        if ctx is not None and ah in ctx.wit_set:
                            del self._round_ctx[a.round]
                    # Stop at witnesses so the walk doesn't descend to the
                    # bottom of the graph (reference: hashgraph.go:503-512).
                    try:
                        if self.witness(ah):
                            break
                    except StoreError:
                        pass
                    ah = a.self_parent()
                else:
                    break

    def set_wire_info(self, event: Event) -> None:
        """Fill the (creatorID, parent index) wire fields
        (reference: hashgraph.go:596-633)."""
        self_parent_index = -1
        other_parent_creator_id = 0
        other_parent_index = -1

        creator = self.store.repertoire_by_pub_key().get(event.creator())
        if creator is None:
            raise UnknownParticipantError(
                f"creator {event.creator()} not found"
            )

        if event.self_parent() != "":
            self_parent_index = self.store.get_event(event.self_parent()).index()

        if event.other_parent() != "":
            other_parent = self.store.get_event(event.other_parent())
            op_creator = self.store.repertoire_by_pub_key().get(other_parent.creator())
            if op_creator is None:
                raise UnknownParticipantError(
                    f"creator {other_parent.creator()} not found"
                )
            other_parent_creator_id = op_creator.id
            other_parent_index = other_parent.index()

        event.set_wire_info(
            self_parent_index,
            other_parent_creator_id,
            other_parent_index,
            creator.id,
        )

    def insert_event_and_run_consensus(
        self, event: Event, set_wire_info: bool = False
    ) -> None:
        """The per-event pipeline driver (reference: hashgraph.go:644-668).

        With an accelerator attached, round/witness assignment still happens
        per insert (it gates the insert-time first-descendant walk,
        hashgraph.go:503-512, so it must track every insert exactly like the
        reference), but the voting stages are deferred to a batched device
        sweep — normally once per sync via flush_consensus, or mid-batch
        when enough inserts accumulate."""
        self.insert_event(event, set_wire_info)
        self.divide_rounds()
        if self.accel is not None:
            self._accel_pending += 1
            if self.accel.should_sweep(self._accel_pending):
                self.run_consensus_sweep()
            return
        self.run_consensus_sweep()

    def flush_consensus(self) -> None:
        """Run any deferred accelerated consensus sweep (no-op without an
        accelerator; with one attached, also drains a pipelined sweep's
        pending results even when nothing was inserted since)."""
        if self.accel is not None and (
            self._accel_pending > 0 or self.accel.busy()
        ):
            self.run_consensus_sweep()

    def drain_accel_delta(self) -> tuple:
        """Hand the accumulated delta channels to the accelerator's window
        state (consumed exactly once per snapshot): (new_witnesses,
        fd_dirty). New-witness order is divide_rounds order."""
        nw, self._accel_new_witnesses = self._accel_new_witnesses, []
        fd, self._accel_fd_dirty = self._accel_fd_dirty, set()
        return nw, fd

    def run_consensus_sweep(self) -> None:
        """One batched voting sweep: device kernels when the undecided
        window is big enough to beat the dispatch+readback cost, oracle
        stages otherwise. Output is identical either way."""
        self._accel_pending = 0
        if self.accel is not None and self.accel.flush(self):
            self.process_decided_rounds()
            return
        self.decide_fame()
        self.decide_round_received()
        self.process_decided_rounds()

    @staged("insert")
    def insert_event(self, event: Event, set_wire_info: bool = False) -> None:
        """Verify signature, check parents, prevent forks, maintain
        coordinates, queue for consensus (reference: hashgraph.go:672-750)."""
        if not event.verify():
            if _DEBUG_REJECTS:
                logger.error(
                    "REJECT %s creator=%s idx=%s parents=%r txs=%d itxs=%d "
                    "sigs=%d ts=%s sig=%s",
                    event.hex(), event.creator()[:24], event.index(),
                    [p[:20] for p in event.body.parents],
                    len(event.body.transactions),
                    len(event.body.internal_transactions),
                    len(event.body.block_signatures),
                    event.body.timestamp, event.signature[:40],
                )
            raise InvalidSignatureError(
                f"invalid event signature {event.hex()}", event=event
            )

        self._check_self_parent(event)
        self._check_other_parent(event)

        event.topological_index = self.topological_index
        self.topological_index += 1

        if set_wire_info:
            self.set_wire_info(event)

        self._init_event_coordinates(event)
        self.store.set_event(event)
        self._update_ancestor_first_descendant(event)

        self.undetermined_events.append(event.hex())
        self._round_pending.append(event.hex())

        if event.is_loaded():
            self.pending_loaded_events += 1

        for bs in event.block_signatures():
            self.pending_signatures.add(bs)

    def insert_frame_event(self, frame_event: FrameEvent) -> None:
        """Trusted insert for fast-sync: skips signature/parent checks, primes
        the round/witness/timestamp caches, records as consensus event
        (reference: hashgraph.go:754-802)."""
        event = frame_event.core

        self._round_cache.add(event.hex(), frame_event.round)
        self._witness_cache.add(event.hex(), frame_event.witness)
        self._timestamp_cache.add(event.hex(), frame_event.lamport_timestamp)

        event.set_round(frame_event.round)
        event.set_lamport_timestamp(frame_event.lamport_timestamp)

        try:
            round_info = self.store.get_round(frame_event.round)
        except StoreError as err:
            if not is_store_err(err, StoreErrorKind.KEY_NOT_FOUND):
                raise
            round_info = RoundInfo()
        round_info.add_created_event(event.hex(), frame_event.witness)
        self.store.set_round(frame_event.round, round_info)

        self._init_event_coordinates(event)
        self.store.set_event(event)
        self._update_ancestor_first_descendant(event)
        self.store.add_consensus_event(event)

    # =========================================================================
    # Consensus pipeline
    # =========================================================================

    @staged("divide_rounds")
    def divide_rounds(self) -> None:
        """Assign round + Lamport timestamp to undetermined events, flag
        witnesses, queue pending rounds (reference: hashgraph.go:807-872).

        Scans only the fresh-insert tail (_round_pending): already-assigned
        events can never need reassignment, so re-fetching the full
        undetermined backlog per pass (the reference's loop shape) would be
        pure store/LRU overhead. On error the unprocessed suffix is
        requeued so the next pass retries it.

        set_round writes are coalesced per TOUCHED ROUND rather than issued
        per event: a fresh round still registers immediately (get_round /
        last_round must see it mid-batch), but the per-event re-writes of an
        already-registered round collapse into one flush per round at the
        end of the pass — on the persistent store that turns O(batch) SQL
        upserts into O(distinct rounds). The flush runs in a finally so a
        mid-batch error still persists every mutation already applied to
        the (shared, mutable) RoundInfo objects."""
        pending = self._round_pending
        if not pending:
            return
        self._round_pending = []
        done = 0
        touched: Dict[int, RoundInfo] = {}
        try:
            for hash_ in pending:
                self._assign_round_and_lamport(hash_, touched)
                done += 1
        except BaseException:
            self._round_pending = pending[done:] + self._round_pending
            raise
        finally:
            for r, ri in touched.items():
                self.store.set_round(r, ri)

    def _assign_round_and_lamport(
        self, hash_: str, round_infos: Optional[Dict[int, "RoundInfo"]] = None
    ) -> None:
        ev = self.store.get_event(hash_)
        update_event = False

        if ev.round is None:
            # All fallible reads (round, round-info, witness) run BEFORE the
            # event is mutated: the store hands back this same cached object,
            # so mutating first would make the requeued retry see
            # "round already assigned" and skip witness registration forever.
            round_number = self.round(hash_)
            round_info = (
                None if round_infos is None else round_infos.get(round_number)
            )
            fresh_round = False
            if round_info is None:
                try:
                    round_info = self.store.get_round(round_number)
                except StoreError as err:
                    if not is_store_err(err, StoreErrorKind.KEY_NOT_FOUND):
                        raise
                    round_info = RoundInfo()
                    fresh_round = True
            is_witness = self.witness(hash_)
            ev.set_round(round_number)
            update_event = True

            if (
                not self.pending_rounds.queued(round_number)
                and not round_info.decided
                and (
                    self.round_lower_bound is None
                    or round_number > self.round_lower_bound
                )
            ):
                self.pending_rounds.set(PendingRound(round_number, False))

            round_info.add_created_event(hash_, is_witness)
            if round_infos is None or fresh_round:
                # A fresh round registers immediately — the very next event
                # in the batch may read it via get_round / last_round.
                # Known rounds defer to divide_rounds' per-round flush.
                self.store.set_round(round_number, round_info)
            if round_infos is not None:
                round_infos[round_number] = round_info
            if is_witness and self._accel_track_delta:
                self._accel_new_witnesses.append((round_number, hash_))

        if ev.lamport_timestamp is None:
            # fallible read evaluated before the mutation, same rationale
            lt = self.lamport_timestamp(hash_)
            ev.set_lamport_timestamp(lt)
            update_event = True

        if update_event:
            self.store.set_event(ev)

    @staged("decide_fame")
    def decide_fame(self) -> None:
        """Virtual voting with coin rounds every COIN_ROUND_FREQ rounds
        (reference: hashgraph.go:875-998).

        Per-pass memos: round infos / peer-sets / witness lists are
        fetched once per round, and each voter y's strongly-seen
        witness list of round j-1 is computed once instead of once per
        candidate x — none of it changes within the stage (set_fame only
        mutates the candidate round's info)."""
        votes: Dict[str, Dict[str, bool]] = {}  # votes[y][x] = y's vote on x

        def set_vote(y: str, x: str, vote: bool) -> None:
            votes.setdefault(y, {})[x] = vote

        rounds_memo: Dict[int, tuple] = {}  # j -> (peer_set, witnesses)

        def round_data(j: int) -> tuple:
            e = rounds_memo.get(j)
            if e is None:
                ri = self.store.get_round(j)
                ps = self.store.get_peer_set(j)
                e = (ps, ri.witnesses())
                rounds_memo[j] = e
            return e

        ss_memo: Dict[tuple, list] = {}  # (y, j_prev) -> strongly-seen list
        ctx_memo: Dict[int, _RoundCtx] = {}  # j_prev -> fd-matrix ctx

        def ss_witnesses_of(y: str, j_prev: int) -> list:
            k = (y, j_prev)
            v = ss_memo.get(k)
            if v is None:
                prev_ps, prev_wits = round_data(j_prev)
                # Built from the per-pass captured witness list (NOT the
                # cross-pass _round_ctx), so the voter mask sees exactly
                # the snapshot round_data froze for this stage.
                ctx = ctx_memo.get(j_prev)
                if ctx is None:
                    ctx = self._build_round_ctx(prev_ps, prev_wits, 0)
                    ctx_memo[j_prev] = ctx
                mask = self._strongly_seen_mask(y, ctx)
                v = [w for w, s in zip(prev_wits, mask) if s]
                ss_memo[k] = v
            return v

        decided_rounds: List[int] = []

        for pr in self.pending_rounds.get_ordered_pending_rounds():
            round_index = pr.index
            r_round_info = self.store.get_round(round_index)
            r_peer_set = self.store.get_peer_set(round_index)

            for x in r_round_info.witnesses():
                if r_round_info.is_decided(x):
                    continue
                done = False
                for j in range(round_index + 1, self.store.last_round() + 1):
                    if done:
                        break
                    j_peer_set, j_witnesses = round_data(j)

                    for y in j_witnesses:
                        diff = j - round_index
                        if diff == 1:
                            set_vote(y, x, self.see(y, x))
                        else:
                            # Witnesses of round j-1 strongly seen by y,
                            # based on the round j-1 peer-set.
                            ss_witnesses = ss_witnesses_of(y, j - 1)

                            yays = 0
                            nays = 0
                            for w in ss_witnesses:
                                if votes.get(w, {}).get(x, False):
                                    yays += 1
                                else:
                                    nays += 1
                            v = False
                            t = nays
                            if yays >= nays:
                                v = True
                                t = yays

                            if diff % COIN_ROUND_FREQ > 0:  # normal round
                                if t >= j_peer_set.super_majority():
                                    r_round_info.set_fame(x, v)
                                    set_vote(y, x, v)
                                    done = True  # break out of the j loop
                                    break
                                set_vote(y, x, v)
                            else:  # coin round
                                if t >= j_peer_set.super_majority():
                                    set_vote(y, x, v)
                                else:
                                    set_vote(y, x, middle_bit(y))

            if r_round_info.witnesses_decided(r_peer_set):
                decided_rounds.append(round_index)

            self.store.set_round(round_index, r_round_info)

        self.pending_rounds.update(decided_rounds)

    @staged("round_received")
    def decide_round_received(self) -> None:
        """An event is received at the first decided round whose famous
        witnesses ALL see it (reference: hashgraph.go:1002-1095, quoting the
        whitepaper's 18/03/18 formulation).

        Per-round data (info, decidedness, famous witnesses, threshold) is
        fetched ONCE per pass and shared across the whole undetermined
        scan — none of it can change mid-stage, and the repeated
        store/LRU lookups were the pass's hottest lines. Mutated round
        infos are written back once per round at the end (same final
        store state; received order within a round is the scan order, as
        in the reference)."""
        new_undetermined: List[str] = []
        # round -> None (missing) | (round_info, decided, famous, sm)
        rcache: dict = {}
        dirty: dict = {}
        last_round = self.store.last_round()
        lb = self.round_lower_bound

        def round_entry(i: int):
            e = rcache.get(i, False)
            if e is False:
                try:
                    tr = self.store.get_round(i)
                except StoreError:
                    e = None
                else:
                    tp = self.store.get_peer_set(i)
                    decided = tr.witnesses_decided(tp)
                    fws = tr.famous_witnesses() if decided else ()
                    e = (tr, decided, fws, tp.super_majority())
                rcache[i] = e
            return e

        try:
            self._rr_scan(new_undetermined, round_entry, dirty, last_round, lb)
        finally:
            # flush mutated rounds even if the scan raised mid-pass, so a
            # persistent store's rounds never trail its already-written
            # event rows (the old per-event set_round pairing, batched)
            for i, tr in dirty.items():
                self.store.set_round(i, tr)

        self.undetermined_events = new_undetermined

    def _rr_scan(self, new_undetermined, round_entry, dirty, last_round,
                 lb) -> None:
        for x in self.undetermined_events:
            received = False
            r = self.round(x)

            for i in range(r + 1, last_round + 1):
                entry = round_entry(i)
                if entry is None:
                    if lb is not None and i <= lb:
                        # Compacted round at/below the prune / fast-sync
                        # floor: it is decided and its famous witnesses
                        # are fixed, so it can never receive x — skip
                        # upward exactly as the un-pruned oracle's
                        # decided-round walk does.
                        continue
                    # A joiner's first event can have round 0 while others
                    # have long evicted round 1 (reference:
                    # hashgraph.go:1019-1026).
                    break
                tr, decided, fws, sm = entry

                if not decided:
                    # Rounds below the fast-sync lower bound are never
                    # decided by decide_fame — skip them instead of
                    # bailing (reference: hashgraph.go:1033-1046).
                    if lb is None or lb < i:
                        break
                    else:
                        continue

                if len(fws) >= sm and all(self.see(w, x) for w in fws):
                    received = True
                    ex = self.store.get_event(x)
                    ex.set_round_received(i)
                    self.store.set_event(ex)
                    tr.add_received_event(x)
                    dirty[i] = tr
                    break

            if not received:
                new_undetermined.append(x)

    @staged("commit")
    def process_decided_rounds(self) -> None:
        """Map decided rounds onto Frames and Blocks, committing via the
        callback (reference: hashgraph.go:1100-1181)."""
        processed_rounds: List[int] = []
        try:
            for pr in self.pending_rounds.get_ordered_pending_rounds():
                # Never process a decided round before all earlier rounds are
                # processed (reference: hashgraph.go:1108-1113).
                if not pr.decided:
                    break

                frame = self.get_frame(pr.index)

                if frame.events:
                    for fe in frame.events:
                        self.store.add_consensus_event(fe.core)
                        self.consensus_transactions += len(fe.core.transactions())
                        if fe.core.is_loaded():
                            self.pending_loaded_events -= 1

                    block = Block.from_frame(self.store.last_block_index() + 1, frame)
                    if block.transactions() or block.internal_transactions():
                        # Commit BEFORE publishing via set_block: the
                        # callback mutates the body (state_hash, receipts)
                        # and signs it, and set_block is what advances
                        # last_block_index — publishing first let
                        # concurrent readers hash a half-committed body
                        # and (via the lost-invalidation cache race) left
                        # a stale digest that this node then SIGNED
                        # (surfaced by test_bootstrap_recycle_reproduces_
                        # chain once the batched-ingest path sped gossip
                        # up). The callback's own sign path re-persists
                        # the block; this set_block also covers the
                        # commit-failure case, keeping the reference's
                        # non-fatal semantics (hashgraph.go:1162-1165).
                        try:
                            self.commit_callback(block)
                        except Exception:
                            logger.warning(
                                "failed to commit block %d", block.index(), exc_info=True
                            )
                        self.store.set_block(block)
                    self.last_committed_round_events = len(frame.events)

                processed_rounds.append(pr.index)

                if (
                    self.last_consensus_round is None
                    or pr.index > self.last_consensus_round
                ):
                    self._set_last_consensus_round(pr.index)
        finally:
            self.pending_rounds.clean(processed_rounds)

    # =========================================================================
    # Frames
    # =========================================================================

    def _create_frame_event(self, x: str) -> FrameEvent:
        """reference: hashgraph.go:521-557."""
        ev = self.store.get_event(x)
        round_ = self.round(x)
        round_info = self.store.get_round(round_)
        te = round_info.created_events.get(x)
        if te is None:
            raise ValueError(f"round {round_} created_events[{x}] not found")
        return FrameEvent(
            core=ev,
            round=round_,
            lamport_timestamp=self.lamport_timestamp(x),
            witness=te.witness,
        )

    def _create_root(self, participant: str, head: str) -> Root:
        """Root = the head + up to ROOT_DEPTH prior events of the
        participant, in topological order (reference: hashgraph.go:559-594)."""
        root = Root()
        if head != "":
            head_event = self._create_frame_event(head)
            reverse_root_events = [head_event]
            index = head_event.core.index()
            for _ in range(ROOT_DEPTH):
                index -= 1
                if index < 0:
                    break
                try:
                    peh = self.store.participant_event(participant, index)
                except StoreError:
                    break
                reverse_root_events.append(self._create_frame_event(peh))
            for fe in reversed(reverse_root_events):
                root.insert(fe)
        return root

    def get_frame(self, round_received: int) -> Frame:
        """Compute (or fetch) the Frame of a received round
        (reference: hashgraph.go:1184-1289)."""
        try:
            return self.store.get_frame(round_received)
        except StoreError as err:
            if not is_store_err(err, StoreErrorKind.KEY_NOT_FOUND):
                raise

        round_ = self.store.get_round(round_received)
        peer_set = self.store.get_peer_set(round_received)

        events = [self._create_frame_event(eh) for eh in round_.received_events]
        events = sort_frame_events(events)

        # Roots for participants with events in this frame: built from each
        # participant's first frame-event's self-parent.
        roots: Dict[str, Root] = {}
        for fe in events:
            p = fe.core.creator()
            if p not in roots:
                roots[p] = self._create_root(p, fe.core.self_parent())

        # Every participant known before round_received needs a Root —
        # built from its last consensus event (reference: hashgraph.go:1231-1256).
        for p, peer in self.store.repertoire_by_pub_key().items():
            first_round, ok = self.store.first_round(peer.id)
            if not ok or first_round > round_received:
                continue
            if p not in roots:
                last_consensus_event_hash = self.store.last_consensus_event_from(p)
                roots[p] = self._create_root(p, last_consensus_event_hash)

        all_peer_sets = self.store.get_all_peer_sets()

        # BFT timestamp: median of famous-witness wall-clock timestamps
        # (reference: hashgraph.go:1264-1273).
        timestamps = [
            self.store.get_event(fw).timestamp()
            for fw in round_.famous_witnesses()
        ]
        frame_timestamp = median_int(timestamps)

        res = Frame(
            round=round_received,
            peers=peer_set,
            roots=roots,
            events=events,
            peer_sets=all_peer_sets,
            timestamp=frame_timestamp,
        )
        self.store.set_frame(res)
        return res

    # =========================================================================
    # Signature pool / anchor block
    # =========================================================================

    def process_sig_pool(self) -> None:
        """Match pending block-signatures to stored blocks; validate the
        signer against the block round's peer-set; verify; append
        (reference: hashgraph.go:1295-1367)."""
        for bs in self.pending_signatures.slice():
            try:
                block = self.store.get_block(bs.index)
            except StoreError:
                continue  # block not yet committed locally; keep the sig

            try:
                peer_set = self.store.get_peer_set(block.round_received())
            except StoreError:
                continue

            if bs.validator_hex() not in peer_set.by_pub_key:
                continue  # signer not a validator for that round: drop later

            if not block.verify_signature(bs):
                continue

            block.set_signature(bs)
            self.store.set_block(block)
            self.set_anchor_block(block)
            self.pending_signatures.remove(bs.key())

    def set_anchor_block(self, block: Block) -> None:
        """AnchorBlock = latest block with MORE than 1/3 signatures
        (reference: hashgraph.go:1375-1408)."""
        peer_set = self.store.get_peer_set(block.round_received())
        if len(block.signatures) > peer_set.trust_count() and (
            self.anchor_block is None or block.index() > self.anchor_block
        ):
            self.anchor_block = block.index()

    def get_anchor_block_with_frame(self) -> tuple[Block, Frame]:
        """reference: hashgraph.go:1412-1428."""
        if self.anchor_block is None:
            raise ValueError("no anchor block")
        block = self.store.get_block(self.anchor_block)
        frame = self.get_frame(block.round_received())
        return block, frame

    # =========================================================================
    # Compaction (lifecycle tier — babble_tpu/lifecycle/pruner.py)
    # =========================================================================

    def prune_below(self, floor_round: int) -> Dict[str, int]:
        """Compact history below a sealed anchor: drop events received in
        rounds < floor_round, rounds whose created events are all gone,
        and frames below the floor — from cache AND durable storage.

        Safe because everything at stake is final: rounds below the
        anchor are decided, a decided round's famous witnesses are fixed
        at decision time, and see() only consults coordinates frozen at
        insert — so no event inserted after the prune can ever be
        received at a pruned round, and the live pipeline never reads
        below the floor.  What must survive does: every round ≥ the
        floor and its frame, each participant's last ROOT_DEPTH+1
        consensus events (future _create_root walks), any round below
        the floor that still holds a live created event (its RoundInfo
        backs _create_frame_event for straggler roots), and blocks /
        peer-sets / roots / evidence / consensus counters wholesale.
        """
        if (
            self.last_consensus_round is None
            or floor_round > self.last_consensus_round
        ):
            raise ValueError(
                f"prune floor {floor_round} beyond last consensus round "
                f"{self.last_consensus_round}"
            )
        prev = self.prune_floor
        if prev is not None and floor_round <= prev:
            return {"floor": prev, "events_pruned": 0, "rounds_pruned": 0}

        # Per-participant keep floor: the last ROOT_DEPTH+1 events below
        # each participant's latest consensus event stay, whatever round
        # received them — _create_root walks that far down the index.
        floors: Dict[str, int] = {}
        for p in self.store.repertoire_by_pub_key():
            last = self.store.last_consensus_event_from(p)
            if last == "":
                continue
            try:
                ev = self.store.get_event(last)
            except StoreError:
                continue
            keep_from = ev.index() - ROOT_DEPTH
            if keep_from > 0:
                floors[p] = keep_from

        # Enumerate the drop set from the received-event lists of rounds
        # below the floor. A hash that no longer loads was compacted (or
        # evicted) already — re-listing it only re-issues a no-op delete.
        dropped: set = set()
        drop_events: List[str] = []
        scan_base = self._prune_scan_base
        for r in range(scan_base, floor_round):
            try:
                ri = self.store.get_round(r)
            except StoreError:
                continue
            for h in ri.received_events:
                if h in dropped:
                    continue
                try:
                    ev = self.store.get_event(h)
                except StoreError:
                    dropped.add(h)
                    drop_events.append(h)
                    continue
                fl = floors.get(ev.creator())
                if fl is None or ev.index() >= fl:
                    continue
                dropped.add(h)
                drop_events.append(h)

        # A round goes only when ALL its created events are gone: an
        # event created below the floor but received above it (or still
        # undetermined) keeps its round alive for _create_frame_event.
        drop_rounds: List[int] = []
        new_scan_base = floor_round
        for r in range(scan_base, floor_round):
            try:
                ri = self.store.get_round(r)
            except StoreError:
                continue
            if all(h in dropped for h in ri.created_events):
                drop_rounds.append(r)
                self._round_ctx.pop(r, None)
            elif r < new_scan_base:
                new_scan_base = r

        self.store.prune_below(floor_round, drop_events, drop_rounds, floors)

        self._prune_scan_base = new_scan_base
        self.prune_floor = floor_round
        # Same boundary fast-sync establishes: rounds at/below the floor
        # are never re-queued for fame voting, and the round-received
        # scan skips their gaps (_rr_scan).
        if self.round_lower_bound is None or floor_round > self.round_lower_bound:
            self.round_lower_bound = floor_round

        return {
            "floor": floor_round,
            "events_pruned": len(drop_events),
            "rounds_pruned": len(drop_rounds),
        }

    # =========================================================================
    # Reset / bootstrap
    # =========================================================================

    def reset(self, block: Block, frame: Frame) -> None:
        """Re-base the hashgraph from a frame (fast-sync landing)
        (reference: hashgraph.go:1431-1470)."""
        self.last_consensus_round = None
        self.first_consensus_round = None
        self.anchor_block = None
        self.undetermined_events = []
        self._round_pending = []
        self.pending_rounds = PendingRoundsCache()
        self.pending_loaded_events = 0
        self.topological_index = 0
        self._accel_pending = 0
        self._accel_new_witnesses = []
        self._accel_fd_dirty = set()
        self._round_ctx = {}
        if self.accel is not None:
            # An in-flight sweep's snapshot no longer describes this store.
            self.accel.invalidate()

        cs = self.store.cache_size()
        self._ancestor_cache = LRU(cs)
        self._self_ancestor_cache = LRU(cs)
        self._strongly_see_cache = LRU(cs)
        self._round_cache = LRU(cs)
        self._timestamp_cache = LRU(cs)
        self._witness_cache = LRU(cs)

        self.store.reset(frame)

        for fe in frame.sorted_frame_events():
            self.insert_frame_event(fe)

        self.store.set_block(block)
        self._set_last_consensus_round(block.round_received())
        self.round_lower_bound = block.round_received()

    def bootstrap(self) -> None:
        """Replay a persistent store's events through consensus in
        topological order — only from index 0 (reference: hashgraph.go:1481-1536).
        The persistent store provides topological_events(); InmemStore has
        nothing to replay."""
        topo = getattr(self.store, "topological_events", None)
        if topo is None:
            return
        maintenance = getattr(self.store, "set_maintenance_mode", None)
        if maintenance is not None:
            maintenance(True)
        try:
            batch_size = 100
            index = 0
            while True:
                events = topo(index * batch_size, batch_size)
                for e in events:
                    self.insert_event_and_run_consensus(e, set_wire_info=True)
                self.flush_consensus()
                self.process_sig_pool()
                if len(events) < batch_size:
                    break
                index += 1
        finally:
            if maintenance is not None:
                maintenance(False)

    # =========================================================================
    # Wire conversion / block checks
    # =========================================================================

    def read_wire_info(self, wevent: WireEvent, overlay=None) -> Event:
        """WireEvent → Event: resolve (creatorID, index) pairs back to
        parent hashes via the participant indexes (reference: hashgraph.go:1540-1595).

        ``overlay`` is an optional {(pub_key_hex, index): event_hex} map of
        events decoded earlier in the same sync batch but not yet inserted —
        it lets the accelerator path decode a whole batch ahead of insertion
        for batched signature verification without changing the sequential
        semantics (parents still must be in the store by insert time)."""
        self_parent = ""
        other_parent = ""

        def resolve(pub_hex: str, idx: int) -> str:
            try:
                return self.store.participant_event(pub_hex, idx)
            except Exception:
                if overlay is not None:
                    h = overlay.get((pub_hex, idx))
                    if h is not None:
                        return h
                raise UnknownParentError(
                    f"parent ({pub_hex[:16]}…, {idx}) not known"
                )

        creator = self.store.repertoire_by_id().get(wevent.body.creator_id)
        if creator is None:
            raise UnknownParticipantError(
                f"creator {wevent.body.creator_id} not found"
            )
        creator_bytes = creator.pub_key_bytes()

        if wevent.body.self_parent_index >= 0:
            self_parent = resolve(
                creator.pub_key_hex, wevent.body.self_parent_index
            )

        if wevent.body.other_parent_index >= 0:
            op_creator = self.store.repertoire_by_id().get(
                wevent.body.other_parent_creator_id
            )
            if op_creator is None:
                raise UnknownParticipantError(
                    f"participant {wevent.body.other_parent_creator_id} not found"
                )
            other_parent = resolve(
                op_creator.pub_key_hex, wevent.body.other_parent_index
            )

        body = EventBody(
            transactions=wevent.body.transactions,
            internal_transactions=wevent.body.internal_transactions,
            block_signatures=wevent.block_signatures(creator_bytes),
            parents=[self_parent, other_parent],
            creator=creator_bytes,
            index=wevent.body.index,
            timestamp=wevent.body.timestamp,
            self_parent_index=wevent.body.self_parent_index,
            other_parent_creator_id=wevent.body.other_parent_creator_id,
            other_parent_index=wevent.body.other_parent_index,
            creator_id=wevent.body.creator_id,
        )
        return Event(body, signature=wevent.signature)

    def check_block(self, block: Block, peer_set: PeerSet) -> None:
        """Validate a block carries MORE than 1/3 valid signatures from the
        given peer-set (reference: hashgraph.go:1599-1630)."""
        if peer_set.hash() != block.peers_hash():
            raise ValueError("wrong peer-set")
        valid = 0
        for s in block.get_signatures():
            if s.validator_hex() not in peer_set.by_pub_key:
                continue
            if block.verify_signature(s):
                valid += 1
        if valid <= peer_set.trust_count():
            raise ValueError(
                f"not enough valid signatures: got {valid}, "
                f"need more than {peer_set.trust_count()}"
            )

    # =========================================================================
    # Setters
    # =========================================================================

    def _set_last_consensus_round(self, i: int) -> None:
        self.last_consensus_round = i
        if self.first_consensus_round is None:
            self.first_consensus_round = i
