"""Fast-sync checkpoints for read replicas (docs/clients.md §Checkpoints).

A checkpoint is the reference's Frame/fast-sync idea (docs/fastsync.md)
exposed as a client artifact: the current anchor block (the latest
block carrying MORE than 1/3 valid validator signatures) plus the Frame
it closes. Because hashgraph finality makes a signed block a
self-contained proof object, a fresh replica that verifies the
checkpoint against its known validator set can serve inclusion proofs
from block ``anchor+1`` onward in seconds — no DAG replay.

Schema (all bytes b64, JSON-plain):

    {"format": "babble-checkpoint/1",
     "block":  <Block.to_dict()>,       # body + accumulated signatures
     "frame":  <Frame.to_dict()>,       # peer-set history + roots
     "snapshot": <hex>}                 # optional app snapshot at the
                                        # anchor (validator rejoin only)

Verification lives in ``client.verifier.verify_checkpoint`` (extra keys
like ``snapshot`` are ignored — replicas don't need app state). The
snapshot rides along for REJOINING VALIDATORS (docs/lifecycle.md): the
reference ships it in FastForwardResponse, and a rejoiner that skips
``proxy.restore`` would chain its app state hash from a stale prefix and
commit blocks its peers refuse to countersign.
"""

from __future__ import annotations

import json

from ..crypto.canonical import jsonable
from .verifier import CHECKPOINT_FORMAT, verify_checkpoint  # noqa: F401


def make_checkpoint(block, frame, snapshot: bytes = None) -> dict:
    cp = {
        "format": CHECKPOINT_FORMAT,
        "block": jsonable(block.to_dict()),
        "frame": jsonable(frame.to_dict()),
    }
    if snapshot is not None:
        cp["snapshot"] = snapshot.hex()
    return cp


def export_checkpoint(core) -> dict:
    """Checkpoint from a validator's core — the anchor block + frame
    (core.get_anchor_block_with_frame raises while no block has enough
    signatures yet, typically only in a cluster's first seconds)."""
    block, frame = core.get_anchor_block_with_frame()
    return make_checkpoint(block, frame)


def save_checkpoint(cp: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(cp, f, separators=(",", ":"))


def load_checkpoint(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        return json.load(f)
