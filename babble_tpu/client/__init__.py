"""Light-client gateway tier (docs/clients.md).

Everything between validators and untrusted readers:

- ``subhub``     — streaming commit subscriptions (selector-loop push
                   server with bounded per-subscriber queues and
                   slow-consumer shedding);
- ``proofs``     — the tx→block index and signed Merkle inclusion-proof
                   builder served at ``GET /proof/<txid>``;
- ``verifier``   — STATELESS proof/checkpoint verification from the
                   validator set alone (safe to vendor into clients);
- ``checkpoint`` — signed Frame-style fast-sync snapshots for instant
                   read-replica spin-up;
- ``replica``    — a verifying read replica: checkpoint import +
                   subscription tail + its own proof-serving HTTP
                   endpoint;
- ``gateway``    — the sharded admission front end: fans SubmitTx
                   across mempool-verdict workers, forwards accepted
                   transactions to validators, and re-serves the commit
                   stream to its own subscribers;
- ``swarm``      — a selector-based many-subscriber load client (one
                   thread, thousands of sockets) used by
                   demo/bombard.py, bench.py --clients and the
                   clientsmoke suite.
"""
