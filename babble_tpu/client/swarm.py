"""SubscriberSwarm — thousands of subscriber connections on one thread.

The load side of the gateway bench (docs/clients.md §Benching): a
single selector loop owns M sockets subscribed to one or more hubs,
parses the pushed frames, and tracks per-subscriber ordering (gaps /
out-of-order), push latency (hub send stamp → local receive, same
host), and shed notices. A configurable fraction of subscribers can be
deliberately STALLED (connected + subscribed, never reading) to prove
the hub sheds them without hurting the healthy ones.

Also exports :class:`SubscriberClient`, a tiny blocking single-stream
client for tools and tests that just want one subscription.
"""

from __future__ import annotations

import random
import selectors
import socket
import threading
import time
from typing import Dict, List, Optional

# one implementation of the wire protocol, shared with the server side
from .subhub import _CHUNK, parse_frames, subscribe_frame


class SubscriberClient:
    """One blocking subscription stream (tools, tests, the replica)."""

    def __init__(self, addr: str, start: int = -1, timeout: float = 10.0):
        host, port_s = addr.rsplit(":", 1)
        self._sock = socket.create_connection(
            (host, int(port_s)), timeout=timeout
        )
        self._buf = bytearray()
        self._pending: List[dict] = []
        self._sock.sendall(subscribe_frame(start))
        self.hello = self.recv()
        if self.hello.get("type") != "hello":
            raise ValueError(f"bad hello: {self.hello!r}")

    def recv(self, timeout: Optional[float] = None) -> dict:
        """Next frame, in stream order (blocking; socket.timeout on
        silence)."""
        if timeout is not None:
            self._sock.settimeout(timeout)
        while not self._pending:
            chunk = self._sock.recv(_CHUNK)
            if not chunk:
                raise ConnectionError("stream closed")
            self._buf += chunk
            self._pending.extend(parse_frames(self._buf))
        return self._pending.pop(0)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class _Member:
    __slots__ = (
        "sock", "buf", "idx", "stalled", "subscribed", "expected",
        "blocks", "gaps", "shed", "closed", "latencies",
    )

    def __init__(self, sock: socket.socket, idx: int, stalled: bool):
        self.sock = sock
        self.buf = bytearray()
        self.idx = idx
        self.stalled = stalled
        self.subscribed = False
        self.expected: Optional[int] = None  # next block index expected
        self.blocks = 0
        self.gaps = 0
        self.shed: Optional[str] = None
        self.closed = False
        self.latencies: List[float] = []


class SubscriberSwarm:
    """``addrs`` round-robins subscribers across hubs. ``stall_frac``
    of members never read after subscribing (slow-consumer bait).
    ``latency_sample`` bounds stored latency samples per member."""

    def __init__(
        self,
        addrs: List[str],
        n: int,
        start: int = -1,
        stall_frac: float = 0.0,
        latency_sample: int = 64,
        connect_timeout: float = 10.0,
    ):
        self.addrs = list(addrs)
        self.n = int(n)
        self.start = start
        self.stall_count = int(round(self.n * stall_frac))
        self.latency_sample = latency_sample
        self.connect_timeout = connect_timeout
        self._sel = selectors.DefaultSelector()
        self._members: List[_Member] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.connect_errors = 0

    # -- lifecycle -----------------------------------------------------------

    def start_all(self) -> None:
        """Connect + subscribe everyone (blocking), then run the read
        loop in the background. Stalled members are chosen as the FIRST
        ``stall_count`` indexes so tests can name them."""
        for i in range(self.n):
            addr = self.addrs[i % len(self.addrs)]
            host, port_s = addr.rsplit(":", 1)
            stalled = i < self.stall_count
            try:
                if stalled:
                    # a tiny receive buffer keeps the kernel from hiding
                    # the stall: the hub sees backpressure after a few
                    # KB instead of after megabytes of OS buffering
                    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4096)
                    sock.settimeout(self.connect_timeout)
                    sock.connect((host, int(port_s)))
                else:
                    sock = socket.create_connection(
                        (host, int(port_s)), timeout=self.connect_timeout
                    )
            except OSError:
                self.connect_errors += 1
                continue
            sock.setblocking(False)
            m = _Member(sock, i, stalled=stalled)
            try:
                sock.sendall(subscribe_frame(self.start))
            except OSError:
                self.connect_errors += 1
                continue
            # stalled members subscribe but never register for reads —
            # the socket buffer fills and the hub must shed them
            if not m.stalled:
                self._sel.register(sock, selectors.EVENT_READ, m)
            self._members.append(m)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="swarm-loop"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        for m in self._members:
            try:
                m.sock.close()
            except OSError:
                pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            for key, _ in self._sel.select(timeout=0.1):
                self._readable(key.data)

    def _readable(self, m: _Member) -> None:
        try:
            while True:
                chunk = m.sock.recv(_CHUNK)
                if not chunk:
                    self._close(m)
                    return
                m.buf += chunk
                if len(chunk) < _CHUNK:
                    break
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._close(m)
            return
        now = time.time()  # lint: allow(clock: client-side latency measurement tool; never runs under sim)
        try:
            frames = parse_frames(m.buf)
        except ValueError:
            self._close(m)
            return
        for fr in frames:
            kind = fr.get("type")
            if kind == "hello":
                m.subscribed = True
                m.expected = fr.get("next")
            elif kind == "block":
                idx = fr.get("block", {}).get("Body", {}).get("Index")
                if m.expected is not None and idx != m.expected:
                    m.gaps += 1
                m.expected = (idx + 1) if isinstance(idx, int) else None
                m.blocks += 1
                ts = fr.get("ts")
                if isinstance(ts, (int, float)):
                    if len(m.latencies) >= self.latency_sample:
                        m.latencies[
                            random.randrange(self.latency_sample)  # lint: allow(clock: reservoir sampling in a client-side tool)
                        ] = now - ts
                    else:
                        m.latencies.append(now - ts)
            elif kind == "shed":
                m.shed = fr.get("reason", "?")

    def _close(self, m: _Member) -> None:
        if m.closed:
            return
        m.closed = True
        try:
            self._sel.unregister(m.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            m.sock.close()
        except OSError:
            pass

    # -- observations --------------------------------------------------------

    @property
    def members(self) -> List[_Member]:
        return self._members

    def healthy(self) -> List[_Member]:
        return [m for m in self._members if not m.stalled]

    def stats(self) -> Dict[str, object]:
        healthy = self.healthy()
        lats = sorted(
            lat for m in healthy for lat in m.latencies
        )

        def pct(q: float):
            if not lats:
                return None
            import math

            return lats[min(len(lats) - 1, math.ceil(q * len(lats)) - 1)]

        return {
            "subscribers": len(self._members),
            "stalled": self.stall_count,
            "connect_errors": self.connect_errors,
            "blocks_received": sum(m.blocks for m in healthy),
            "min_blocks": min((m.blocks for m in healthy), default=0),
            "gaps": sum(m.gaps for m in healthy),
            "shed_notices": sum(
                1 for m in self._members if m.shed is not None
            ),
            "closed": sum(1 for m in healthy if m.closed),
            "push_latency_p50_s": pct(0.50),
            "push_latency_p99_s": pct(0.99),
            "latency_samples": len(lats),
        }
