"""Stateless light-client verification (docs/clients.md §Verifying).

Checks an inclusion proof or a fast-sync checkpoint against nothing but
a known validator set — no store, no node, no network. This module is
the part that ships inside clients, so it depends only on the crypto
and peers layers and treats every input as hostile: malformed fields
raise :class:`ProofError` with a stable reason slug, never an arbitrary
exception.

Trust rule (the same finality bar the validators themselves use,
hashgraph.go check_block / peers.PeerSet.trust_count): a block is final
once it carries valid signatures from MORE than 1/3 of the validator
set the client trusts — under the <1/3-Byzantine assumption at least
one of those signers is honest, and honest validators only ever sign
one block per index (Baird 2016 hashgraph finality).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..crypto.canonical import canonical_dumps, unb64
from ..crypto.hashing import sha256
from ..crypto.keys import PublicKey
from ..crypto.merkle import verify_path
from ..peers.peer import Peer
from ..peers.peer_set import PeerSet
from .proofs import PROOF_FORMAT, txid_hex

CHECKPOINT_FORMAT = "babble-checkpoint/1"


class ProofError(ValueError):
    """Verification failure; ``reason`` is a stable slug for tests and
    counters."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}{': ' + detail if detail else ''}")
        self.reason = reason


def as_peer_set(validators) -> PeerSet:
    """Accept a PeerSet, an iterable of Peers, or an iterable of peer
    dicts ({"NetAddr","PubKeyHex","Moniker"} — the /peers wire shape)."""
    if isinstance(validators, PeerSet):
        return validators
    peers: List[Peer] = []
    for v in validators:
        peers.append(v if isinstance(v, Peer) else Peer.from_dict(v))
    return PeerSet(peers)


def count_valid_signatures(
    body_hash: bytes, signatures: dict, peer_set: PeerSet
) -> int:
    """Signatures over ``body_hash`` from members of ``peer_set``.
    Unknown signers and invalid signatures simply don't count — a
    hostile server can pad the dict, never inflate the count."""
    valid = 0
    for validator_hex, sig in signatures.items():
        peer = peer_set.by_pub_key.get(validator_hex)
        if peer is None or not isinstance(sig, str):
            continue
        try:
            pub = PublicKey.from_hex(validator_hex)
            if pub.verify(body_hash, sig):
                valid += 1
        except Exception:  # noqa: BLE001 — hostile input, never raise
            continue
    return valid


def _header_hash(header: dict) -> bytes:
    """Hash of the signed header exactly as BlockBody.hash() computes it
    (the header dict is canonical-normal already: b64 strings, ints)."""
    if not isinstance(header, dict):
        raise ProofError("bad_header", "header is not an object")
    try:
        return sha256(canonical_dumps(header))
    except (TypeError, ValueError) as err:
        raise ProofError("bad_header", str(err)) from None


def verify_proof(proof: dict, validators, min_signatures: Optional[int] = None) -> dict:
    """Check one inclusion proof against the known validator set.

    Returns ``{"txid", "tx", "block_index", "round_received",
    "signatures_valid"}`` on success, raises :class:`ProofError`
    otherwise. ``min_signatures`` overrides the default
    more-than-one-third bar (e.g. a client wanting a supermajority).
    """
    if not isinstance(proof, dict):
        raise ProofError("bad_proof", "proof is not an object")
    if proof.get("format") != PROOF_FORMAT:
        raise ProofError("bad_format", str(proof.get("format")))
    peer_set = as_peer_set(validators)
    if len(peer_set) == 0:
        raise ProofError("empty_validator_set")
    header = proof.get("header")
    if not isinstance(header, dict):
        raise ProofError("bad_header", "missing header")

    # 1. the transaction is in the signed Merkle root
    try:
        tx = unb64(proof["tx"])
        index = int(proof["index"])
        count = int(proof["count"])
        path = [
            (unb64(step["hash"]), bool(step["right"]))
            for step in proof.get("path", [])
        ]
        root = unb64(header["TxRoot"])
    except (KeyError, TypeError, ValueError) as err:
        raise ProofError("bad_proof", str(err)) from None
    if count != header.get("TxCount"):
        raise ProofError("count_mismatch")
    if txid_hex(tx) != proof.get("txid"):
        raise ProofError("txid_mismatch")
    if not verify_path(tx, index, count, path, root):
        raise ProofError("bad_merkle_path")

    # 2. the header is bound to the validator set the client trusts
    try:
        peers_hash = unb64(header["PeersHash"])
    except (KeyError, TypeError, ValueError) as err:
        raise ProofError("bad_header", str(err)) from None
    if peers_hash != peer_set.hash():
        raise ProofError("wrong_validator_set")

    # 3. enough of those validators signed the header
    body_hash = _header_hash(header)
    signatures = proof.get("signatures")
    if not isinstance(signatures, dict):
        raise ProofError("bad_proof", "missing signatures")
    valid = count_valid_signatures(body_hash, signatures, peer_set)
    need = (
        int(min_signatures)
        if min_signatures is not None
        else peer_set.trust_count() + 1
    )
    if valid < need:
        raise ProofError(
            "not_enough_signatures", f"got {valid}, need >= {need}"
        )
    return {
        "txid": proof["txid"],
        "tx": tx,
        "block_index": header.get("Index"),
        "round_received": header.get("RoundReceived"),
        "signatures_valid": valid,
    }


def verify_block(block, validators, min_signatures: Optional[int] = None) -> int:
    """Full-block variant for subscribers (client.replica): the pushed
    block's body hash must carry enough valid signatures from the known
    set, and its PeersHash must be that set's. Returns the valid-sig
    count, raises ProofError."""
    peer_set = as_peer_set(validators)
    if len(peer_set) == 0:
        raise ProofError("empty_validator_set")
    if block.peers_hash() != peer_set.hash():
        raise ProofError("wrong_validator_set")
    valid = count_valid_signatures(
        block.body.hash(), block.signatures, peer_set
    )
    need = (
        int(min_signatures)
        if min_signatures is not None
        else peer_set.trust_count() + 1
    )
    if valid < need:
        raise ProofError(
            "not_enough_signatures", f"got {valid}, need >= {need}"
        )
    return valid


def verify_checkpoint(cp: dict, validators) -> tuple:
    """Check a fast-sync checkpoint (client.checkpoint schema) against
    the known validator set; returns the parsed (Block, Frame) on
    success. The frame is bound to the block through FrameHash, and the
    block to the validators through PeersHash + signatures — so a
    replica importing this snapshot trusts nothing but its validator
    set."""
    from ..hashgraph.block import Block
    from ..hashgraph.frame import Frame

    if not isinstance(cp, dict) or cp.get("format") != CHECKPOINT_FORMAT:
        raise ProofError("bad_format")
    try:
        block = Block.from_dict(cp["block"])
        frame = Frame.from_dict(cp["frame"])
    except Exception as err:  # noqa: BLE001 — hostile input
        raise ProofError("bad_checkpoint", str(err)) from None
    verify_block(block, validators)
    if block.frame_hash() != frame.hash():
        raise ProofError("bad_frame_hash")
    return block, frame
