"""Sharded admission gateway (docs/clients.md §Gateway).

The write-side front end of the client tier: clients speak the exact
``Babble.SubmitTx`` JSON-RPC the validator proxies speak (so
demo/bombard.py points at a gateway unchanged), but the gateway

1. **shards admission** across worker shards (threads, or separate OS
   processes with ``processes=True``) each running the real mempool
   verdict pipeline (docs/mempool.md): dedup, caps, token-bucket rate
   limiting and the committed-LRU — a flood is shed at the edge before
   it ever reaches a validator;
2. **forwards** accepted transactions to the validator proxies
   (sticky per shard, failover across the list);
3. **subscribes on behalf of its clients**: an embedded
   :class:`~babble_tpu.client.replica.ReadReplica` tails and VERIFIES
   the upstream commit stream, feeds committed payloads back into the
   worker mempools (so retries of committed transactions answer
   ``already_committed`` from the edge), serves ``GET /proof/<txid>``
   over HTTP, and re-fans the verified stream to downstream subscribers
   through its own SubscriptionHub — validators see ONE subscriber per
   gateway, not one per client.

Sharding is ``crc32(tx) % shards`` so every retry of a payload lands on
the shard that holds its dedup state.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional

from .replica import ReadReplica
from .subhub import SubscriptionHub

#: worker-side verdict when no validator accepted the forward
UNAVAILABLE = "unavailable"


def _shard_of(tx: bytes, shards: int) -> int:
    return zlib.crc32(tx) % shards


def _worker_loop(worker_id, forward_addrs, mempool_kwargs, task_q, resp_q):
    """One admission shard: mempool verdicts + sticky-with-failover
    forwarding. Runs as a thread or a child process — only stdlib +
    picklable args. Exits on a ``None`` task."""
    from ..mempool.mempool import Mempool
    from ..proxy.socket_proxy import JsonRpcClient

    mp = Mempool(**mempool_kwargs)
    clients: Dict[str, JsonRpcClient] = {}
    n_fwd = len(forward_addrs)

    def forward(tx: bytes) -> str:
        """Push one accepted tx to a validator; the shard's sticky
        choice first, then failover around the ring."""
        import base64

        for i in range(n_fwd):
            addr = forward_addrs[(worker_id + i) % n_fwd]
            cli = clients.get(addr)
            if cli is None:
                cli = clients[addr] = JsonRpcClient(addr, timeout=5.0)
            try:
                result = cli.call(
                    "Babble.SubmitTx",
                    base64.b64encode(tx).decode("ascii"),
                )
                return "accepted" if result is True else str(result)
            except Exception:  # noqa: BLE001 — failover
                continue
        return UNAVAILABLE

    while True:
        item = task_q.get()
        if item is None:
            break
        kind = item[0]
        if kind == "tx":
            _, req_id, tx = item
            verdict = mp.submit(tx)
            # Drain whenever anything is pending — the shard mempool is
            # an admission filter + dedup ledger, not a holding pool.
            # Pending can be nonzero on a non-accepted verdict when an
            # earlier forward failed and the batch was requeued below;
            # any new task is the retry trigger.
            batch = mp.drain() if mp.pending_count else []
            for i, drained in enumerate(batch):
                fwd = forward(drained)
                if fwd in ("throttled", "full", UNAVAILABLE):
                    # The validator shed the tx (or none was reachable):
                    # put THIS tx and the rest of the batch back so a
                    # later task retries them — dropping here would
                    # leave the hash in the in-flight dedup set and
                    # every client retry would bounce off 'duplicate'
                    # while the payload never reached consensus
                    # (blackhole). Terminal verdicts (accepted /
                    # duplicate / already_committed) stay dropped; an
                    # 'oversized' at the validator but not here is a
                    # cap misconfiguration — size the gateway's
                    # event_max_bytes at or below the validators'.
                    mp.requeue(batch[i:])
                    if verdict == "accepted":
                        verdict = fwd
                    break
                if drained == tx and verdict == "accepted":
                    verdict = fwd
            resp_q.put(("verdict", req_id, verdict))
        elif kind == "commit":
            # committed payloads observed by the verifying replica:
            # feeds the committed-LRU so client retries shed at the edge
            mp.mark_committed(item[1])
        elif kind == "stats":
            resp_q.put(
                ("stats", item[1], {
                    "submitted": mp.submitted,
                    "accepted": mp.accepted,
                    "rejected_dup": mp.rejected_dup,
                    "rejected_full": mp.rejected_full,
                    "rejected_throttled": mp.rejected_throttled,
                    "already_committed": mp.committed_dedup_hits,
                })
            )
    for cli in clients.values():
        cli.close()


class Gateway:
    """``forward_addrs`` are validator proxy addrs (Babble.SubmitTx);
    ``upstream`` is a validator's SubscriptionHub addr. ``listen`` /
    ``sub_listen`` / ``http_addr`` bind the gateway's own submit,
    re-fanout, and proof endpoints (empty = feature off; ":0" picks an
    ephemeral port). ``processes=True`` runs each shard as an OS
    process — the production shape; threads are the in-test default."""

    def __init__(
        self,
        forward_addrs: List[str],
        upstream: str,
        validators,
        listen: str = "",
        sub_listen: str = "",
        http_addr: str = "",
        checkpoint: Optional[dict] = None,
        shards: int = 2,
        processes: bool = False,
        mempool_kwargs: Optional[dict] = None,
        submit_timeout: float = 10.0,
        queue_frames: int = 256,
        stall_timeout_s: float = 10.0,
        shed_lag: int = 1024,
    ):
        if not forward_addrs:
            raise ValueError("gateway needs at least one validator addr")
        self.shards = max(1, int(shards))
        self.processes = bool(processes)
        self.submit_timeout = submit_timeout
        mp_kwargs = dict(
            max_txs=20000, max_bytes=32 * 1024 * 1024,
            committed_lru=65536,
        )
        mp_kwargs.update(mempool_kwargs or {})

        if self.processes:
            import multiprocessing as mp_mod

            ctx = mp_mod.get_context("spawn")
            self._task_qs = [ctx.Queue() for _ in range(self.shards)]
            self._resp_q = ctx.Queue()
            self._workers = [
                ctx.Process(
                    target=_worker_loop,
                    args=(i, list(forward_addrs), mp_kwargs,
                          self._task_qs[i], self._resp_q),
                    daemon=True, name=f"gw-shard-{i}",
                )
                for i in range(self.shards)
            ]
        else:
            import queue as q_mod

            self._task_qs = [q_mod.Queue() for _ in range(self.shards)]
            self._resp_q = q_mod.Queue()
            self._workers = [
                threading.Thread(
                    target=_worker_loop,
                    args=(i, list(forward_addrs), mp_kwargs,
                          self._task_qs[i], self._resp_q),
                    daemon=True, name=f"gw-shard-{i}",
                )
                for i in range(self.shards)
            ]

        # verdict routing: req_id -> (event, slot)
        self._pending: Dict[int, tuple] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 0
        self._worker_stats: Dict[int, dict] = {}

        # the verifying read side
        self.replica = ReadReplica(
            upstream, validators, checkpoint=checkpoint,
            http_addr=http_addr,
        )
        self.replica.listeners.append(self._on_verified_block)

        # re-fanout hub over VERIFIED blocks only
        self.hub: Optional[SubscriptionHub] = None
        if sub_listen:
            self.hub = SubscriptionHub(
                sub_listen,
                block_source=self._sealed_source,
                moniker="gateway",
                queue_frames=queue_frames,
                stall_timeout_s=stall_timeout_s,
                shed_lag=shed_lag,
            )

        # the submit front end (same wire as a validator proxy)
        self._server = None
        if listen:
            from ..proxy.socket_proxy import JsonRpcServer

            self._server = JsonRpcServer(
                listen, {"Babble.SubmitTx": self._rpc_submit}
            )
            self.listen_addr = self._server.addr
        self.submitted = 0
        self.forward_unavailable = 0
        self._stop = threading.Event()
        self._resp_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        for w in self._workers:
            w.start()
        self._resp_thread = threading.Thread(
            target=self._resp_loop, daemon=True, name="gw-resp"
        )
        self._resp_thread.start()
        self.replica.start()
        if self.hub is not None:
            self.hub.listen()

    def close(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.close()
        if self.hub is not None:
            self.hub.close()
        self.replica.close()
        for q in self._task_qs:
            try:
                q.put(None)
            except Exception:  # noqa: BLE001 — closed mp queue
                pass
        for w in self._workers:
            w.join(timeout=3.0)
            if self.processes and w.is_alive():
                w.terminate()
        # unblock any submitter still parked on a verdict
        with self._pending_lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for event, slot in pending:
            slot.append(UNAVAILABLE)
            event.set()

    # -- submit path ---------------------------------------------------------

    def _rpc_submit(self, tx_b64: str) -> str:
        from ..crypto.canonical import unb64

        return self.submit(unb64(tx_b64))

    def submit(self, tx: bytes) -> str:
        """Admission verdict for one transaction, end to end: shard
        mempool verdict, forward to a validator when accepted."""
        tx = bytes(tx)
        event = threading.Event()
        slot: list = []
        with self._pending_lock:
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = (event, slot)
        self._task_qs[_shard_of(tx, self.shards)].put(("tx", req_id, tx))
        self.submitted += 1
        if not event.wait(timeout=self.submit_timeout):
            with self._pending_lock:
                self._pending.pop(req_id, None)
            return UNAVAILABLE
        verdict = slot[0]
        if verdict == UNAVAILABLE:
            self.forward_unavailable += 1
        return verdict

    def _resp_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._resp_q.get(timeout=0.2)
            except Exception:  # noqa: BLE001 — queue.Empty / closed mp queue
                continue
            if item[0] == "verdict":
                _, req_id, verdict = item
                with self._pending_lock:
                    waiter = self._pending.pop(req_id, None)
                if waiter is not None:
                    event, slot = waiter
                    slot.append(verdict)
                    event.set()
            elif item[0] == "stats":
                self._worker_stats[item[1]] = item[2]

    # -- read path -----------------------------------------------------------

    def _sealed_source(self, index: int):
        """Block source for the re-fanout hub: only blocks the replica
        has VERIFIED are ever pushed downstream."""
        if index > self.replica.last_verified:
            return None
        return self.replica.get_block(index)

    def _on_verified_block(self, block) -> None:
        # committed-LRU feedback, sharded like admissions
        txs = block.transactions()
        if txs:
            by_shard: Dict[int, list] = {}
            for tx in txs:
                by_shard.setdefault(_shard_of(tx, self.shards), []).append(tx)
            for shard, batch in by_shard.items():
                self._task_qs[shard].put(("commit", batch))
        if self.hub is not None:
            self.hub.publish(block.index())

    def get_proof(self, txid: str) -> Optional[dict]:
        return self.replica.get_proof(txid)

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        # refresh worker-side counters (best effort, async)
        for i, q in enumerate(self._task_qs):
            try:
                q.put(("stats", i))
            except Exception:  # noqa: BLE001
                pass
        out = {
            "shards": self.shards,
            "processes": self.processes,
            "submitted": self.submitted,
            "forward_unavailable": self.forward_unavailable,
            "replica": self.replica.stats(),
            "workers": dict(self._worker_stats),
        }
        if self.hub is not None:
            out["hub"] = self.hub.stats()
        return out


def main(argv=None) -> int:
    """Standalone gateway: ``python -m babble_tpu.client.gateway
    --forward addr,addr --upstream addr --peers peers.json --listen
    host:port [--sub-listen ...] [--http ...] [--checkpoint file]
    [--shards N] [--processes]``."""
    import argparse
    import json
    import signal as _signal
    import sys
    import time as _time

    p = argparse.ArgumentParser(prog="babble_tpu.client.gateway")
    p.add_argument("--forward", required=True,
                   help="comma-separated validator proxy addrs")
    p.add_argument("--upstream", required=True,
                   help="a validator's --client-listen addr")
    p.add_argument("--peers", required=True,
                   help="peers.json with the trusted validator set")
    p.add_argument("--listen", default="127.0.0.1:0")
    p.add_argument("--sub-listen", dest="sub_listen", default="")
    p.add_argument("--http", default="")
    p.add_argument("--checkpoint", default="")
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--processes", action="store_true")
    args = p.parse_args(argv)

    with open(args.peers, encoding="utf-8") as f:
        validators = json.load(f)
    checkpoint = None
    if args.checkpoint:
        from .checkpoint import load_checkpoint

        checkpoint = load_checkpoint(args.checkpoint)
    gw = Gateway(
        [a.strip() for a in args.forward.split(",") if a.strip()],
        args.upstream, validators,
        listen=args.listen, sub_listen=args.sub_listen,
        http_addr=args.http, checkpoint=checkpoint,
        shards=args.shards, processes=args.processes,
    )
    gw.start()
    print(
        f"gateway up: submit {getattr(gw, 'listen_addr', '-')}, "
        f"subscribe {gw.hub.bind_addr if gw.hub else '-'}, "
        f"http {gw.replica.http_addr or '-'}",
        file=sys.stderr,
    )
    stop = {"flag": False}

    def _stop(signum, frame):
        stop["flag"] = True

    _signal.signal(_signal.SIGINT, _stop)
    _signal.signal(_signal.SIGTERM, _stop)
    while not stop["flag"]:
        _time.sleep(0.2)  # lint: allow(clock: gateway daemon wait loop; operator tool, never under sim)
    gw.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
