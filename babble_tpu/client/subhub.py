"""Streaming commit subscriptions (docs/clients.md §Subscriptions).

``SubscriptionHub`` is a one-thread selector-loop push server (the
net/atcp.py pattern applied to the read path): N long-lived subscriber
connections are multiplexed on a single selector, so serving 10k
subscribers costs one thread and no per-client polling of ``/history``.

Wire protocol (every frame: 4-byte big-endian length + canonical JSON):

    client -> hub   {"type": "subscribe", "from": <index|-1>}
    hub -> client   {"type": "hello", "last": <sealed head>, "next":
                     <first index this stream will push>, "moniker": m}
                    {"type": "block", "ts": <hub send stamp, s>,
                     "block": <Block.to_dict()>}   # strictly in order
                    {"type": "shed", "reason": <slug>}   # then close

``from`` = first block index wanted (backfilled from the store);
``-1``/omitted = live tail only. Blocks are pushed only once SEALED —
carrying MORE than 1/3 validator signatures — so every pushed block
verifies offline (client.verifier.verify_block) and doubles as its own
inclusion proof substrate.

Flow control: each subscriber owns a bounded frame queue
(``queue_frames``); the hub never buffers beyond it — a lagging
subscriber simply reads older blocks out of the store at its own pace.
A subscriber is SHED (counter + shed frame + close) when it stalls
(no socket progress with queued data for ``stall_timeout_s``) or trails
the sealed head by more than ``shed_lag`` blocks — one stuck consumer
can never hold memory or delay the others, because per-subscriber
queues are independent and writes are non-blocking.

Block frames are encoded ONCE per block (bounded cache) and the same
bytes object is queued to every subscriber.
"""

from __future__ import annotations

import json
import selectors
import socket
import struct
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

from ..crypto.canonical import jsonable

_U32 = struct.Struct(">I")
_CHUNK = 1 << 16
#: inbound frames are a single small subscribe request
MAX_REQUEST = 4096
#: largest pushed frame a CLIENT accepts (client.swarm imports this —
#: both halves of the protocol live in this module so they cannot drift)
MAX_FRAME = 64 << 20
#: encoded block frames kept for re-push to lagging subscribers
FRAME_CACHE = 1024


def pack_frame(obj: dict) -> bytes:
    """Envelope framing: sorted-key compact JSON (NOT canonical_dumps —
    the envelope legitimately carries a float send stamp, which the
    consensus codec rejects by design; the block payload inside is
    already canonical-normalized)."""
    body = json.dumps(
        obj, sort_keys=True, separators=(",", ":"), allow_nan=False
    ).encode("utf-8")
    return _U32.pack(len(body)) + body


def subscribe_frame(start: int) -> bytes:
    """The one client→hub request."""
    return pack_frame({"type": "subscribe", "from": int(start)})


def parse_frames(buf: bytearray, max_frame: int = MAX_FRAME) -> List[dict]:
    """Consume every complete frame in ``buf`` (mutates it) — the
    client-side decoder twin of pack_frame. Every frame must be a JSON
    OBJECT: a valid-JSON-but-not-a-dict body (``[1,2]``, ``42``) from a
    hostile peer must fail HERE as a protocol error, not later as an
    AttributeError inside whatever loop called ``frame.get(...)``."""
    out: List[dict] = []
    while len(buf) >= 4:
        (length,) = _U32.unpack_from(buf, 0)
        if length > max_frame:
            raise ValueError("oversized frame")
        if len(buf) < 4 + length:
            break
        frame = json.loads(bytes(buf[4:4 + length]))
        if not isinstance(frame, dict):
            raise ValueError(f"frame is not an object: {type(frame).__name__}")
        out.append(frame)
        del buf[:4 + length]
    return out


def encode_block_frame(block, ts: Optional[float] = None) -> bytes:
    """The pushed block frame. ``ts`` (hub wall clock at encode) lets a
    same-host subscriber measure push latency; it is omitted when None
    so deterministic-sim digests stay stable across runs."""
    obj: dict = {"type": "block", "block": jsonable(block.to_dict())}
    if ts is not None:
        obj["ts"] = ts
    return pack_frame(obj)


class _Sub:
    """One subscriber connection owned by the hub loop thread."""

    __slots__ = (
        "sock", "rbuf", "wq", "wq_frames", "wview", "subscribed", "next",
        "next0", "last0", "stalled_since", "wait_since", "closed",
    )

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.rbuf = bytearray()
        self.wq: List[bytes] = []
        self.wq_frames = 0          # queued frames (the bound)
        self.wview: Optional[memoryview] = None
        self.subscribed = False
        self.next = 0               # next block index to push
        self.next0 = 0              # first index at subscribe time
        self.last0 = -1             # committed head at subscribe time
        self.stalled_since: Optional[float] = None
        self.wait_since: Optional[float] = None  # next unfetchable since
        self.closed = False


class SubscriptionHub:
    """``block_source(i)`` must return a SEALED block (> 1/3 validator
    signatures) or None (not committed / not sealed yet / evicted) — the
    hub re-polls Nones on its tick. ``publish(index)`` is the commit
    hook: O(1), safe from any thread, never blocks consensus."""

    def __init__(
        self,
        bind_addr: str,
        block_source: Callable[[int], Optional[object]],
        moniker: str = "",
        queue_frames: int = 256,
        stall_timeout_s: float = 10.0,
        shed_lag: int = 1024,
        sndbuf: int = 0,
        clock=None,
    ):
        from ..common.clock import WALL

        self._bind_addr = bind_addr
        self._source = block_source
        self._moniker = moniker
        self.queue_frames = max(1, int(queue_frames))
        self.stall_timeout_s = float(stall_timeout_s)
        self.shed_lag = max(1, int(shed_lag))
        # Cap the kernel send buffer per subscriber socket (0 = OS
        # default): a stalled consumer then backs up into the hub's
        # OWN bounded queue quickly, making the stall timer (and the
        # shed) deterministic instead of hiding behind megabytes of
        # kernel buffering.
        self.sndbuf = int(sndbuf)
        self._clock = clock or WALL
        self._sel = selectors.DefaultSelector()
        self._listener: Optional[socket.socket] = None
        self._thread: Optional[threading.Thread] = None
        self._shutdown = threading.Event()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        # the write end must be non-blocking too: publish() runs on the
        # CONSENSUS commit path, and a full socketpair buffer (hub loop
        # busy while commits keep arriving) must drop the redundant wake
        # byte (BlockingIOError ⊂ OSError, swallowed below), never block
        # Core.commit
        self._wake_w.setblocking(False)
        self._subs: List[_Sub] = []
        self._frames: "OrderedDict[int, bytes]" = OrderedDict()
        #: highest COMMITTED block index published to us (sealing may
        #: trail it; -1 before the first commit)
        self.last_published = -1
        # -- counters (obs catalog client_* instruments read these) ----
        self.subscribers_total = 0
        self.pushed_blocks = 0
        self.shed_total = 0
        self.shed_reasons: Dict[str, int] = {}

    # -- lifecycle -----------------------------------------------------------

    def listen(self) -> str:
        host, port_s = self._bind_addr.rsplit(":", 1)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host or "0.0.0.0", int(port_s)))
        srv.listen(512)
        srv.setblocking(False)
        self._listener = srv
        self._bind_addr = f"{host}:{srv.getsockname()[1]}"
        self._sel.register(srv, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="subhub-loop"
        )
        self._thread.start()
        return self._bind_addr

    @property
    def bind_addr(self) -> str:
        return self._bind_addr

    def close(self) -> None:
        if self._shutdown.is_set():
            return
        self._shutdown.set()
        self._wakeup()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        else:
            self._teardown()

    # -- the commit hook -----------------------------------------------------

    def publish(self, index: int) -> None:
        """Called from the consensus commit path: advance the head
        watermark and wake the loop. Never blocks, never raises."""
        if index > self.last_published:
            self.last_published = index
        self._wakeup()

    def _wakeup(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    # -- stats ---------------------------------------------------------------

    def stats(self) -> dict:
        subs = self._subs
        return {
            "subscribers": sum(1 for s in subs if not s.closed),
            "subscribers_total": self.subscribers_total,
            "queue_frames_max": max(
                (s.wq_frames for s in subs if not s.closed), default=0
            ),
            "pushed_blocks": self.pushed_blocks,
            "shed": self.shed_total,
            "shed_reasons": dict(self.shed_reasons),
            "last_published": self.last_published,
        }

    # -- loop ----------------------------------------------------------------

    def _loop(self) -> None:
        try:
            while not self._shutdown.is_set():
                for key, events in self._sel.select(timeout=0.1):
                    data = key.data
                    if data == "wake":
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    elif data == "accept":
                        self._accept()
                    elif isinstance(data, _Sub):
                        if events & selectors.EVENT_READ:
                            self._readable(data)
                        if events & selectors.EVENT_WRITE and not data.closed:
                            self._flush(data)
                self._pump()
        finally:
            self._teardown()

    def _teardown(self) -> None:
        for sub in list(self._subs):
            self._drop(sub)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        try:
            self._sel.close()
        except Exception:  # noqa: BLE001 — double-teardown is benign
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass

    def _accept(self) -> None:
        assert self._listener is not None
        while True:
            try:
                sock, _ = self._listener.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if self.sndbuf > 0:
                    sock.setsockopt(
                        socket.SOL_SOCKET, socket.SO_SNDBUF, self.sndbuf
                    )
            except OSError:
                pass
            sub = _Sub(sock)
            try:
                self._sel.register(sock, selectors.EVENT_READ, sub)
            except (ValueError, OSError):
                try:
                    sock.close()
                except OSError:
                    continue
                continue
            self._subs.append(sub)

    def _readable(self, sub: _Sub) -> None:
        try:
            chunk = sub.sock.recv(_CHUNK)
            if not chunk:
                self._drop(sub)
                return
            sub.rbuf += chunk
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._drop(sub)
            return
        if sub.subscribed:
            # subscribers only read; anything further is protocol abuse
            if len(sub.rbuf) > MAX_REQUEST:
                self._shed(sub, "protocol")
            return
        if len(sub.rbuf) < 4:
            return
        (length,) = _U32.unpack_from(sub.rbuf, 0)
        if length > MAX_REQUEST:
            self._shed(sub, "protocol")
            return
        if len(sub.rbuf) < 4 + length:
            return
        try:
            req = json.loads(bytes(sub.rbuf[4:4 + length]))
            del sub.rbuf[:4 + length]
            # hostile input: the body must be an OBJECT before any
            # .get() — a bare list/number here must shed THIS client,
            # never escape into the loop and tear the hub down
            if not isinstance(req, dict) or req.get("type") != "subscribe":
                raise ValueError("not a subscribe request")
            start = int(req.get("from", -1))
        except (ValueError, TypeError, KeyError):
            self._shed(sub, "protocol")
            return
        sealed = self._sealed_head()
        sub.next = sealed + 1 if start < 0 else start
        sub.next0 = sub.next
        sub.last0 = self.last_published
        sub.subscribed = True
        self.subscribers_total += 1
        self._enqueue(
            sub,
            pack_frame(
                {
                    "type": "hello",
                    "last": sealed,
                    "next": sub.next,
                    "moniker": self._moniker,
                }
            ),
            count_block=False,
        )

    def _sealed_head(self) -> int:
        """Highest index known sealed RIGHT NOW (walks back from the
        committed head; bounded by the frame the cache covers)."""
        i = self.last_published
        floor = max(-1, i - 4)  # sealing trails commits by a round or two
        while i > floor:
            if i in self._frames or self._fetch(i) is not None:
                return i
            i -= 1
        return i

    # -- pushing -------------------------------------------------------------

    def _fetch(self, index: int) -> Optional[bytes]:
        """Encoded frame for one sealed block; None while unsealed."""
        frame = self._frames.get(index)
        if frame is not None:
            self._frames.move_to_end(index)
            return frame
        try:
            block = self._source(index)
        except Exception:  # noqa: BLE001 — store faults must not kill the loop
            return None
        if block is None:
            return None
        frame = encode_block_frame(block, ts=self._clock.time())
        self._frames[index] = frame
        while len(self._frames) > FRAME_CACHE:
            self._frames.popitem(last=False)
        return frame

    def _pump(self) -> None:
        """Advance every subscriber: queue sealed blocks up to the
        per-subscriber bound, then enforce the shed policies."""
        now = self._clock.monotonic()
        for sub in list(self._subs):
            if sub.closed or not sub.subscribed:
                continue
            blocked_unfetchable = False
            while (
                sub.wq_frames < self.queue_frames
                and sub.next <= self.last_published
            ):
                frame = self._fetch(sub.next)
                if frame is None:
                    # not sealed yet (or evicted) — re-poll next tick
                    blocked_unfetchable = True
                    break
                sub.wait_since = None
                self._enqueue(sub, frame)
                sub.next += 1
            if sub.closed:
                continue
            # A block that stays unfetchable while LATER blocks are
            # servable fell out of the store's retention — re-polling
            # would spin forever. Shed with a distinct reason so the
            # client knows to resync from a checkpoint instead of
            # reconnecting at the same index. (Plain sealing lag clears
            # in a round or two and never has a later index cached.)
            if blocked_unfetchable:
                if sub.wait_since is None:
                    sub.wait_since = now
                elif now - sub.wait_since > max(
                    2 * self.stall_timeout_s, 10.0
                ) and any(i > sub.next for i in self._frames):
                    self._shed(sub, "behind_retention")
                    continue
            else:
                sub.wait_since = None
            # stall detection: queued data but zero socket progress
            if sub.wq or sub.wview is not None:
                if sub.stalled_since is None:
                    sub.stalled_since = now
                elif (
                    self.stall_timeout_s > 0
                    and now - sub.stalled_since > self.stall_timeout_s
                ):
                    self._shed(sub, "stalled")
                    continue
            else:
                sub.stalled_since = None
            # deficit shed: blocks committed since subscribe minus blocks
            # delivered since subscribe — a consumer chronically slower
            # than production. Instantaneous lag would wrongly shed a
            # healthy backfiller that subscribed from old history.
            deficit = (self.last_published - sub.last0) - (
                sub.next - sub.next0
            )
            if deficit > self.shed_lag:
                self._shed(sub, "lagging")

    def _enqueue(self, sub: _Sub, frame: bytes, count_block: bool = True) -> None:
        sub.wq.append(frame)
        sub.wq_frames += 1
        if count_block:
            self.pushed_blocks += 1
        self._flush(sub)

    def _flush(self, sub: _Sub) -> None:
        try:
            while sub.wview is not None or sub.wq:
                if sub.wview is None:
                    sub.wview = memoryview(sub.wq.pop(0))
                    sub.wq_frames -= 1
                n = sub.sock.send(sub.wview)
                sub.stalled_since = None
                if n < len(sub.wview):
                    sub.wview = sub.wview[n:]
                    break
                sub.wview = None
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._drop(sub)
            return
        self._interest(sub)

    def _interest(self, sub: _Sub) -> None:
        mask = selectors.EVENT_READ
        if sub.wq or sub.wview is not None:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(sub.sock, mask, sub)
        except (KeyError, ValueError, OSError):
            pass

    # -- shedding ------------------------------------------------------------

    def _shed(self, sub: _Sub, reason: str) -> None:
        self.shed_total += 1
        self.shed_reasons[reason] = self.shed_reasons.get(reason, 0) + 1
        try:  # best-effort goodbye; a truly stalled socket just drops it
            sub.sock.send(pack_frame({"type": "shed", "reason": reason}))
        except OSError:
            pass
        self._drop(sub)

    def _drop(self, sub: _Sub) -> None:
        if sub.closed:
            return
        sub.closed = True
        try:
            self._sel.unregister(sub.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            sub.sock.close()
        except OSError:
            pass
        sub.wq.clear()
        sub.wview = None
        try:
            self._subs.remove(sub)
        except ValueError:
            pass
