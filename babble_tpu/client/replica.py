"""Checkpointed read replica (docs/clients.md §Read replicas).

A ``ReadReplica`` is an UNTRUSTED-side process that serves reads
without ever joining consensus:

1. **Spin-up**: import a signed checkpoint (client.checkpoint) — after
   ``verify_checkpoint`` against the validator set the operator trusts,
   the replica can answer proofs for everything after the anchor in
   seconds, no DAG replay.
2. **Tail**: subscribe to a validator's SubscriptionHub and VERIFY
   every pushed block (client.verifier.verify_block): >1/3 valid
   signatures from a validator set reachable from the trust root.
   Blocks that fail verification are counted and dropped, never served.
3. **Validator-set ratchet**: a verified block's accepted
   PEER_ADD/PEER_REMOVE receipts derive the successor set; the replica
   keeps every set reachable from its trust root keyed by peers-hash,
   so blocks signed under a post-churn set verify without any
   out-of-band refresh.
4. **Serve**: ``GET /proof/<txid>`` / ``/block/<i>`` / ``/checkpoint``
   / ``/stats`` over its own HTTP endpoint, and optionally re-fan the
   verified stream to downstream subscribers through an embedded hub
   (the gateway does exactly that).
"""

from __future__ import annotations

import json
import socket
import threading
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..hashgraph.block import Block
from ..hashgraph.internal_transaction import TransactionType
from ..peers.peer_set import PeerSet
from .proofs import TxIndex, build_proof
from .swarm import SubscriberClient
from .verifier import ProofError, as_peer_set, verify_block, verify_checkpoint

DEFAULT_RETENTION = 4096


class ReadReplica:
    """``validators`` is the operator's trust root (PeerSet / peer
    dicts). ``checkpoint`` (optional) fast-syncs the starting point;
    without one the replica tails from block 0 (fine for young
    clusters, the checkpoint is what makes old ones instant)."""

    def __init__(
        self,
        upstream: str,
        validators,
        checkpoint: Optional[dict] = None,
        retention: int = DEFAULT_RETENTION,
        http_addr: str = "",
    ):
        self.upstream = upstream
        root = as_peer_set(validators)
        self.known_sets: Dict[bytes, PeerSet] = {root.hash(): root}
        self.current_set: PeerSet = root
        self.retention = max(16, int(retention))
        self.blocks: "OrderedDict[int, Block]" = OrderedDict()
        self.txindex = TxIndex()
        self.checkpoint: Optional[dict] = None
        self.last_verified = -1
        self.start_index = 0
        self.verified_blocks = 0
        self.rejected_blocks = 0
        self.reject_reasons: Dict[str, int] = {}
        self.proofs_served = 0
        self.proof_misses = 0
        self.stream_resets = 0
        #: set when the upstream repeatedly sheds us without any block
        #: landing — our next index fell out of the validator's
        #: retention and only a FRESH checkpoint can move us forward
        #: (docs/clients.md §Read replicas); reconnects then back off
        self.resync_required = False
        self._sheds_without_progress = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self.http_addr = http_addr
        #: commit listeners for re-fanout (the gateway's hub publish)
        self.listeners: List = []
        if checkpoint is not None:
            block, _frame = verify_checkpoint(checkpoint, root)
            self.checkpoint = checkpoint
            self._ingest(block)
            # the anchor block may itself carry accepted membership
            # receipts — derive the successor set NOW, exactly like the
            # streaming path, or every post-churn pushed block would be
            # rejected as an unknown validator set
            self._ratchet(block, root)
            self.start_index = block.index() + 1

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.http_addr:
            self._serve_http()
        self._thread = threading.Thread(
            target=self._tail_loop, daemon=True, name="replica-tail"
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        t = self._thread
        if t is not None:
            t.join(timeout=3.0)

    # -- the verifying tail --------------------------------------------------

    def _tail_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client = SubscriberClient(
                    self.upstream, start=self.last_verified + 1
                )
            except (OSError, ValueError, ConnectionError):
                self.stream_resets += 1
                if self._stop.wait(0.5):
                    return
                continue
            before = self.last_verified
            shed_reason = None
            try:
                while not self._stop.is_set():
                    try:
                        frame = client.recv(timeout=1.0)
                    except (TimeoutError, socket.timeout):
                        continue  # silence — KEEP the stream, poll _stop
                    kind = frame.get("type")
                    if kind == "block":
                        self._on_block_frame(frame)
                    elif kind == "shed":
                        shed_reason = frame.get("reason")
                        raise ConnectionError("shed by upstream")
            except (ConnectionError, OSError, ValueError):
                self.stream_resets += 1
            finally:
                client.close()
            # Repeatedly shed with zero progress means our next index
            # fell out of the upstream's retention ("behind_retention",
            # or legacy hubs' lagging shed): reconnecting at the same
            # index would livelock. Flag for an operator/gateway
            # checkpoint resync and back the reconnects off hard.
            if self.last_verified > before:
                self._sheds_without_progress = 0
            elif shed_reason is not None:
                self._sheds_without_progress += 1
                if (
                    shed_reason == "behind_retention"
                    or self._sheds_without_progress >= 3
                ):
                    self.resync_required = True
            if self._stop.wait(10.0 if self.resync_required else 0.5):
                return

    def _on_block_frame(self, frame: dict) -> None:
        try:
            block = Block.from_dict(frame["block"])
        except Exception:  # noqa: BLE001 — hostile upstream
            self._reject("bad_frame")
            return
        if block.index() <= self.last_verified:
            return  # duplicate/old push
        peer_set = self.known_sets.get(block.peers_hash())
        if peer_set is None:
            self._reject("unknown_validator_set")
            return
        try:
            verify_block(block, peer_set)
        except ProofError as err:
            self._reject(err.reason)
            return
        self._ingest(block)
        self._ratchet(block, peer_set)
        for fn in self.listeners:
            try:
                fn(block)
            except Exception:  # noqa: BLE001 — downstream faults stay local
                pass

    def _reject(self, reason: str) -> None:
        self.rejected_blocks += 1
        self.reject_reasons[reason] = self.reject_reasons.get(reason, 0) + 1

    def _ingest(self, block: Block) -> None:
        self.blocks[block.index()] = block
        while len(self.blocks) > self.retention:
            self.blocks.popitem(last=False)
        self.txindex.index_block(block)
        self.last_verified = max(self.last_verified, block.index())
        self.verified_blocks += 1

    def _ratchet(self, block: Block, peer_set: PeerSet) -> None:
        """Derive the successor validator set from the verified block's
        accepted membership receipts (the signed block carries them, so
        no extra trust is involved — mirrors
        Core.process_accepted_internal_transactions)."""
        nxt = peer_set
        for r in block.internal_transaction_receipts():
            if not r.accepted:
                continue
            body = r.internal_transaction.body
            if body.type == TransactionType.PEER_ADD:
                nxt = nxt.with_new_peer(body.peer)
            elif body.type == TransactionType.PEER_REMOVE:
                nxt = nxt.with_removed_peer(body.peer)
        if nxt is not peer_set:
            self.known_sets[nxt.hash()] = nxt
            self.current_set = nxt

    # -- reads ---------------------------------------------------------------

    def get_block(self, index: int) -> Optional[Block]:
        return self.blocks.get(index)

    def get_proof(self, txid: str) -> Optional[dict]:
        loc = self.txindex.lookup(txid)
        if loc is None:
            self.proof_misses += 1
            return None
        block = self.blocks.get(loc[0])
        if block is None:  # aged past retention
            self.proof_misses += 1
            return None
        self.proofs_served += 1
        return build_proof(block, loc[1])

    def stats(self) -> dict:
        return {
            "upstream": self.upstream,
            "last_verified": self.last_verified,
            "start_index": self.start_index,
            "verified_blocks": self.verified_blocks,
            "rejected_blocks": self.rejected_blocks,
            "reject_reasons": dict(self.reject_reasons),
            "blocks_held": len(self.blocks),
            "txindex": self.txindex.stats(),
            "proofs_served": self.proofs_served,
            "proof_misses": self.proof_misses,
            "stream_resets": self.stream_resets,
            "resync_required": self.resync_required,
            "validator_sets_known": len(self.known_sets),
            "validators": len(self.current_set),
            "from_checkpoint": self.checkpoint is not None,
        }

    # -- HTTP ----------------------------------------------------------------

    def _serve_http(self) -> None:
        replica = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def do_GET(self):
                try:
                    self._route()
                except Exception as err:  # noqa: BLE001
                    _send(self, 500, {"error": str(err)})

            def _route(self):
                path = self.path.split("?", 1)[0]
                if path.startswith("/proof/"):
                    proof = replica.get_proof(path[len("/proof/"):])
                    if proof is None:
                        _send(self, 404, {"error": "unknown txid"})
                    else:
                        _send(self, 200, proof)
                elif path.startswith("/block/"):
                    block = replica.get_block(int(path[len("/block/"):]))
                    if block is None:
                        _send(self, 404, {"error": "unknown block"})
                    else:
                        from ..crypto.canonical import jsonable

                        _send(self, 200, jsonable(block.to_dict()))
                elif path == "/checkpoint":
                    if replica.checkpoint is None:
                        _send(self, 404, {"error": "no checkpoint"})
                    else:
                        _send(self, 200, replica.checkpoint)
                elif path == "/stats":
                    _send(self, 200, replica.stats())
                else:
                    _send(self, 404, {"error": f"no route {path}"})

        host, port_s = self.http_addr.rsplit(":", 1)
        self._httpd = ThreadingHTTPServer(
            (host or "0.0.0.0", int(port_s)), Handler
        )
        self.http_addr = f"{host}:{self._httpd.server_address[1]}"
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="replica-http",
        ).start()


def _send(req: BaseHTTPRequestHandler, code: int, body) -> None:
    payload = json.dumps(body).encode()
    req.send_response(code)
    req.send_header("Content-Type", "application/json")
    req.send_header("Content-Length", str(len(payload)))
    req.end_headers()
    req.wfile.write(payload)
