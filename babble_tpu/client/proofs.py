"""Server side of signed inclusion proofs (docs/clients.md §Proofs).

``TxIndex`` maps txid (sha256 of the payload) to (block index,
position) as blocks commit; ``build_proof`` assembles the proof object
``GET /proof/<txid>`` serves: the signed block *header* (transactions
committed via the Merkle root, hashgraph/block.py ``header_dict``), the
accumulated validator signatures, and the Merkle audit path. The
client-side check lives in ``client.verifier`` and needs nothing but
the validator set.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Tuple

from ..crypto.canonical import b64, jsonable
from ..crypto.merkle import merkle_path

PROOF_FORMAT = "babble-proof/1"


def txid_hex(tx: bytes) -> str:
    return hashlib.sha256(tx).hexdigest()


class TxIndex:
    """Bounded txid → (block index, position) map, fed at commit.

    LRU on insertion order: when the cap is reached the OLDEST indexed
    transactions age out first — a proof request for an aged-out txid is
    a 404, exactly like a txid that never committed (the retention
    tradeoff is documented in docs/clients.md). A txid committed twice
    (the cross-node-retry caveat, docs/mempool.md) keeps its FIRST
    coordinates."""

    def __init__(self, cap: int = 1 << 18):
        self.cap = max(1, int(cap))
        self._map: "OrderedDict[str, Tuple[int, int]]" = OrderedDict()
        self._lock = threading.Lock()
        self.indexed_total = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._map)

    def index_block(self, block) -> None:
        txs = block.transactions()
        if not txs:
            return
        bi = block.index()
        with self._lock:
            for pos, tx in enumerate(txs):
                tid = txid_hex(tx)
                if tid in self._map:  # first commit wins
                    continue
                self._map[tid] = (bi, pos)
                self.indexed_total += 1
            while len(self._map) > self.cap:
                self._map.popitem(last=False)
                self.evictions += 1

    def lookup(self, tid: str) -> Optional[Tuple[int, int]]:
        with self._lock:
            return self._map.get(tid)

    def stats(self) -> dict:
        return {
            "entries": len(self._map),
            "indexed_total": self.indexed_total,
            "evictions": self.evictions,
        }


def build_proof(block, position: int) -> dict:
    """Proof object for ``block.transactions()[position]`` — everything
    a stateless verifier needs besides the validator set. JSON-plain
    (bytes already b64) so it serializes straight onto HTTP."""
    txs = block.transactions()
    tx = txs[position]
    path = merkle_path(txs, position)
    return {
        "format": PROOF_FORMAT,
        "txid": txid_hex(tx),
        "tx": b64(tx),
        "index": position,
        "count": len(txs),
        "path": [{"hash": b64(h), "right": right} for h, right in path],
        "header": jsonable(block.body.header_dict()),
        "signatures": dict(block.signatures),
    }
