"""Metrics registry: counters, gauges, fixed-bucket histograms, and
function-backed instruments, with Prometheus text exposition.

Design constraints (docs/observability.md):

- **Hot-path increments are lock-free.** ``Counter.inc`` /
  ``Histogram.observe`` are plain attribute/list-element arithmetic;
  under the GIL a racing update can be *lost* (bounded under-count,
  monotone) but never corrupted. Locks are only taken for child
  creation (``labels``) and never on the increment path.
- **Function-backed instruments cost nothing until scraped.** Most of
  the codebase already maintains plain integer counters on its objects;
  those register as ``func_counter``/``func_gauge`` closures evaluated
  at collect time, so converting them to "registry instruments" adds
  zero hot-path work.
- **Kill switch.** ``BABBLE_OBS=0`` makes ``counter()``/``gauge()``/
  ``histogram()`` return shared no-op instruments (and registries skip
  them at render time); function-backed instruments keep working, so
  the compatibility ``get_stats`` view and ``/metrics`` stay truthful
  with the overhead disabled. The flag is read once at import
  (``set_enabled`` is the test hook).
"""

from __future__ import annotations

import math
import os
import threading
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence, Tuple

_ENABLED = os.environ.get("BABBLE_OBS", "1") != "0"


def enabled() -> bool:
    """Whether hot-path instruments are live (BABBLE_OBS kill switch)."""
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Test hook: flips the default for registries created AFTER the
    call (existing registries keep their resolved instruments)."""
    global _ENABLED
    _ENABLED = bool(on)


# Default buckets, in seconds. LATENCY covers submit→commit on a
# gossiping cluster (5 ms .. 60 s); STAGE covers individual pipeline
# stages (100 µs .. 2.5 s, the sub-millisecond end matters for decode/
# verify/insert on small syncs).
LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0,
)
STAGE_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5,
)


def _fmt(v) -> str:
    """Prometheus float formatting: integers render without the dot."""
    f = float(v)
    if f == math.inf:
        return "+Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{str(v).replace(chr(92), chr(92) * 2).replace(chr(34), chr(92) + chr(34))}"'
        for k, v in labels.items()
    )
    return "{" + inner + "}"


class Counter:
    """Monotone counter. ``inc`` is a single add — lock-free."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Settable instantaneous value."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


class Histogram:
    """Fixed-bucket histogram. ``observe`` is a bisect + two adds —
    lock-free. Quantiles are estimated by linear interpolation inside
    the matched bucket (standard Prometheus ``histogram_quantile``
    semantics), so accuracy is bounded by bucket width."""

    __slots__ = ("uppers", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = LATENCY_BUCKETS) -> None:
        self.uppers: Tuple[float, ...] = tuple(sorted(buckets))
        if not self.uppers:
            raise ValueError("histogram needs at least one bucket")
        # one slot per finite bucket + the +Inf overflow slot
        self.counts = [0] * (len(self.uppers) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        # bisect_left: Prometheus `le` bounds are INCLUSIVE — a value
        # exactly on a bucket boundary belongs in that bucket
        self.counts[bisect_left(self.uppers, v)] += 1
        self.sum += v
        self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        total = self.count
        if total <= 0:
            return None
        target = q * total
        cum = 0
        lo = 0.0
        for i, n in enumerate(self.counts):
            hi = self.uppers[i] if i < len(self.uppers) else self.uppers[-1]
            if cum + n >= target:
                if n <= 0 or i >= len(self.uppers):
                    return hi
                frac = (target - cum) / n
                return lo + frac * (hi - lo)
            cum += n
            lo = hi
        return self.uppers[-1]

    def summary(self) -> Dict[str, Optional[float]]:
        """count/sum plus interpolated p50/p90/p99 (seconds)."""
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


class _NullInstrument:
    """Shared no-op stand-in when the kill switch is on."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    def dec(self, n=1):
        pass

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def labels(self, **kv):
        return self

    value = 0
    count = 0
    sum = 0.0

    def quantile(self, q):
        return None

    def summary(self):
        return {"count": 0, "sum": 0.0, "p50": None, "p90": None, "p99": None}


NULL = _NullInstrument()


class _Labeled:
    """Parent holding per-label-value children; creation takes the
    registry lock, lookups are a dict get."""

    __slots__ = ("labelnames", "children", "_make", "_lock")

    def __init__(self, labelnames, make, lock):
        self.labelnames = tuple(labelnames)
        self.children: Dict[Tuple[str, ...], object] = {}
        self._make = make
        self._lock = lock

    def labels(self, **kv):
        key = tuple(str(kv[n]) for n in self.labelnames)
        child = self.children.get(key)
        if child is None:
            with self._lock:
                child = self.children.setdefault(key, self._make())
        return child

    def items_snapshot(self):
        """Sorted (key, child) pairs copied under the lock — a render
        racing a first-time labels() insert must not hit 'dict changed
        size during iteration'."""
        with self._lock:
            return sorted(self.children.items())


class _Registered:
    """One registry entry: instrument (or reader fn) + metadata."""

    __slots__ = ("name", "kind", "help", "labelnames", "inst", "fn")

    def __init__(self, name, kind, help_, labelnames=(), inst=None, fn=None):
        self.name = name
        self.kind = kind  # counter | gauge | histogram
        self.help = help_
        self.labelnames = tuple(labelnames)
        self.inst = inst
        self.fn = fn


class Registry:
    """Named collection of instruments with Prometheus rendering.

    Two registration families:

    - ``counter``/``gauge``/``histogram``: real hot-path instruments
      (no-ops when disabled);
    - ``func_counter``/``func_gauge``: zero-overhead readers over
      existing attributes, evaluated at collect time. A labeled func
      instrument's reader returns ``{label_value: number}``.
    """

    def __init__(self, enabled: Optional[bool] = None) -> None:
        self.enabled = _ENABLED if enabled is None else enabled
        self._lock = threading.Lock()
        self._entries: Dict[str, _Registered] = {}

    # -- registration -------------------------------------------------------

    def _add(self, entry: _Registered):
        with self._lock:
            existing = self._entries.get(entry.name)
            if existing is not None:
                return existing
            self._entries[entry.name] = entry
            return entry

    def counter(self, name: str, help_: str, labelnames=()):
        if not self.enabled:
            return NULL
        e = self._add(
            _Registered(
                name, "counter", help_, labelnames,
                inst=_Labeled(labelnames, Counter, self._lock)
                if labelnames else Counter(),
            )
        )
        return e.inst

    def gauge(self, name: str, help_: str, labelnames=()):
        if not self.enabled:
            return NULL
        e = self._add(
            _Registered(
                name, "gauge", help_, labelnames,
                inst=_Labeled(labelnames, Gauge, self._lock)
                if labelnames else Gauge(),
            )
        )
        return e.inst

    def histogram(self, name: str, help_: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS, labelnames=()):
        if not self.enabled:
            return NULL
        e = self._add(
            _Registered(
                name, "histogram", help_, labelnames,
                inst=_Labeled(
                    labelnames, lambda b=tuple(buckets): Histogram(b),
                    self._lock,
                )
                if labelnames else Histogram(buckets),
            )
        )
        return e.inst

    def func_counter(self, name: str, help_: str,
                     fn: Callable[[], object], labelnames=()) -> None:
        self._add(_Registered(name, "counter", help_, labelnames, fn=fn))

    def func_gauge(self, name: str, help_: str,
                   fn: Callable[[], object], labelnames=()) -> None:
        self._add(_Registered(name, "gauge", help_, labelnames, fn=fn))

    # -- reads --------------------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def describe(self) -> List[Tuple[str, str, Tuple[str, ...], str]]:
        """(name, kind, labelnames, help) for every registered entry."""
        with self._lock:
            return [
                (e.name, e.kind, e.labelnames, e.help)
                for e in self._entries.values()
            ]

    def get(self, name: str, **labels):
        """Current value of a counter/gauge (test/assertion helper);
        labeled funcs take the single label value as a kwarg."""
        e = self._entries.get(name)
        if e is None:
            raise KeyError(name)
        if e.fn is not None:
            v = _safe(e.fn)
            if e.labelnames:
                v = (v or {}).get(labels[e.labelnames[0]], 0)
            return v
        inst = e.inst
        if e.labelnames:
            inst = inst.labels(**labels)
        return inst.value if not isinstance(inst, Histogram) else inst.count

    def histogram_summary(self, name: str, **labels):
        e = self._entries.get(name)
        if e is None or e.kind != "histogram" or e.inst is None:
            return None
        inst = e.inst
        if e.labelnames:
            inst = inst.labels(**labels)
        return inst.summary()

    # -- exposition ---------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        with self._lock:
            entries = list(self._entries.values())
        for e in sorted(entries, key=lambda x: x.name):
            lines.append(f"# HELP {e.name} {e.help}")
            lines.append(f"# TYPE {e.name} {e.kind}")
            if e.fn is not None:
                self._render_func(e, lines)
            elif isinstance(e.inst, _Labeled):
                for key, child in e.inst.items_snapshot():
                    labels = dict(zip(e.labelnames, key))
                    self._render_inst(e.name, child, labels, lines)
            else:
                self._render_inst(e.name, e.inst, {}, lines)
        return "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _render_func(e: _Registered, lines: List[str]) -> None:
        v = _safe(e.fn)
        if e.labelnames:
            for lv, n in sorted(((v or {})).items()):
                n = _numeric(n)
                if n is not None:
                    lines.append(
                        f"{e.name}{_label_str({e.labelnames[0]: lv})} "
                        f"{_fmt(n)}"
                    )
        else:
            n = _numeric(v)
            if n is not None:
                lines.append(f"{e.name} {_fmt(n)}")

    @staticmethod
    def _render_inst(name, inst, labels, lines) -> None:
        if isinstance(inst, Histogram):
            # +Inf and _count are derived from the SAME bucket-counts
            # snapshot as the finite buckets, never from inst.count: a
            # concurrent observe() (or a GIL-race-lost count update)
            # must not produce a non-monotone cumulative series, which
            # would break histogram_quantile downstream.
            counts = list(inst.counts)
            cum = 0
            for i, upper in enumerate(inst.uppers):
                cum += counts[i]
                lines.append(
                    f"{name}_bucket"
                    f"{_label_str({**labels, 'le': _fmt(upper)})} {cum}"
                )
            cum += counts[-1]
            lines.append(
                f"{name}_bucket{_label_str({**labels, 'le': '+Inf'})} "
                f"{cum}"
            )
            lines.append(f"{name}_sum{_label_str(labels)} {_fmt(inst.sum)}")
            lines.append(f"{name}_count{_label_str(labels)} {cum}")
        else:
            lines.append(f"{name}{_label_str(labels)} {_fmt(inst.value)}")

    def snapshot(self) -> Dict[str, object]:
        """JSON-friendly view: scalars for counters/gauges, summary
        dicts (count/sum/p50/p90/p99) for histograms; labeled
        instruments nest by label value."""
        out: Dict[str, object] = {}
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if e.fn is not None:
                v = _safe(e.fn)
                if e.labelnames and isinstance(v, dict):
                    out[e.name] = {
                        str(k): _numeric(n) for k, n in sorted(v.items())
                    }
                else:
                    out[e.name] = _numeric(v)
            elif isinstance(e.inst, _Labeled):
                out[e.name] = {
                    "|".join(key): (
                        child.summary()
                        if isinstance(child, Histogram)
                        else child.value
                    )
                    for key, child in e.inst.items_snapshot()
                }
            elif isinstance(e.inst, Histogram):
                out[e.name] = e.inst.summary()
            else:
                out[e.name] = e.inst.value
        return out


def _safe(fn):
    try:
        return fn()
    except Exception:
        return None


def _numeric(v):
    """Coerce collector outputs to numbers; non-numeric (strings, None)
    are skipped from exposition."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)):
        return v
    return None


# Process-global registry: instruments shared by every co-located node
# (serialization caches). Node-scoped registries render alongside it.
GLOBAL = Registry(enabled=True)
_global_wired = False
_global_lock = threading.Lock()


def wire_global() -> None:
    """Register the process-wide cache counters exactly once."""
    global _global_wired
    with _global_lock:
        if _global_wired:
            return
        from ..crypto.canonical import NORM_CACHE
        from ..hashgraph.event import WIRE_CACHE

        GLOBAL.func_counter(
            "wire_cache_hits_total",
            "Process-wide wire-event serialization cache hits.",
            lambda: WIRE_CACHE.hits,
        )
        GLOBAL.func_counter(
            "wire_cache_misses_total",
            "Process-wide wire-event serialization cache misses.",
            lambda: WIRE_CACHE.misses,
        )
        GLOBAL.func_counter(
            "norm_cache_hits_total",
            "Process-wide canonical-JSON normalization cache hits.",
            lambda: NORM_CACHE.hits,
        )
        GLOBAL.func_counter(
            "norm_cache_misses_total",
            "Process-wide canonical-JSON normalization cache misses.",
            lambda: NORM_CACHE.misses,
        )
        from ..crypto.batch import VERIFY_CACHE

        GLOBAL.func_counter(
            "verify_cache_hits_total",
            "Process-wide signature-verdict cache hits.",
            lambda: VERIFY_CACHE.hits,
        )
        GLOBAL.func_counter(
            "verify_cache_misses_total",
            "Process-wide signature-verdict cache misses.",
            lambda: VERIFY_CACHE.misses,
        )
        from ..net.codec import CODEC_STATS

        GLOBAL.func_counter(
            "wire_bytes_sent_total",
            "Process-wide bytes written to gossip sockets.",
            lambda: CODEC_STATS.bytes_sent,
        )
        GLOBAL.func_counter(
            "wire_bytes_received_total",
            "Process-wide bytes read from gossip sockets.",
            lambda: CODEC_STATS.bytes_received,
        )
        GLOBAL.func_counter(
            "codec_events_encoded_total",
            "Process-wide wire events encoded into binary blobs.",
            lambda: CODEC_STATS.events_encoded,
        )
        GLOBAL.func_counter(
            "codec_event_cache_hits_total",
            "Process-wide event sends served from the binary blob memo.",
            lambda: CODEC_STATS.event_cache_hits,
        )
        GLOBAL.func_counter(
            "codec_events_decoded_total",
            "Process-wide binary event blobs decoded at ingest.",
            lambda: CODEC_STATS.events_decoded,
        )
        GLOBAL.func_counter(
            "codec_conns_binary_total",
            "Process-wide inbound connections negotiated binary.",
            lambda: CODEC_STATS.conns_binary,
        )
        GLOBAL.func_counter(
            "codec_conns_json_total",
            "Process-wide inbound connections on the legacy JSON framing.",
            lambda: CODEC_STATS.conns_json,
        )
        # Sampling profiler (obs/profile.py): registered here — not at
        # sampler start — so the instrument exists whether or not the
        # profiler ever runs (the catalog's global-scope contract);
        # it reads {} until a sampler ticks.
        from . import profile as _profile

        GLOBAL.func_counter(
            "profile_stage_samples",
            "Sampling-profiler thread-stack samples per stage bucket.",
            _profile.stage_counts,
            ("stage",),
        )
        _global_wired = True
