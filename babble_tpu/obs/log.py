"""One logging entry point for the whole framework.

Every process (CLI node, signal server, dummy app, demos, benches)
configures logging through ``configure()`` instead of ad-hoc per-module
setup: one handler on the ``babble_tpu`` root logger, plain or JSON
format, level from ``Config.log_level`` / ``--log``, JSON via
``Config.log_json`` / ``--log-json``.

The JSON formatter emits one object per line with stable keys —
``ts``, ``level``, ``logger``, ``msg`` — plus correlation fields when
present on the record or configured process-wide: ``node`` (moniker),
``node_id``, ``peer``, ``sync_id``. Handlers are installed
idempotently (reconfiguring replaces the previous obs handler, never
stacks a second one), and propagation to the root logger is disabled
so embedding applications keep their own logging untouched.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional

ROOT = "babble_tpu"
_HANDLER_TAG = "_babble_obs_handler"

# Correlation fields copied from log-record attributes when set (via
# ``logger.info(..., extra={"peer": id, "sync_id": n})``).
_EXTRA_FIELDS = ("node", "node_id", "peer", "sync_id")


class JsonFormatter(logging.Formatter):
    """One JSON object per line; correlation fields ride along."""

    def __init__(self, node: Optional[str] = None,
                 node_id: Optional[int] = None):
        super().__init__()
        self._node = node
        self._node_id = node_id

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(time.time(), 6),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if self._node is not None:
            out["node"] = self._node
        if self._node_id is not None:
            out["node_id"] = self._node_id
        for f in _EXTRA_FIELDS:
            v = getattr(record, f, None)
            if v is not None and f not in out:
                out[f] = v
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"), default=str)


def configure(
    level: str = "info",
    json_mode: bool = False,
    node: Optional[str] = None,
    node_id: Optional[int] = None,
    stream=None,
) -> logging.Logger:
    """Install (or replace) the framework's single log handler.

    ``level`` is a name (debug/info/warning/error); ``json_mode``
    switches the structured formatter on; ``node``/``node_id`` stamp
    every line for multi-node log aggregation."""
    root = logging.getLogger(ROOT)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    root.propagate = False
    handler = logging.StreamHandler(stream)
    if json_mode:
        handler.setFormatter(JsonFormatter(node=node, node_id=node_id))
    else:
        fmt = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"
        handler.setFormatter(logging.Formatter(fmt))
    setattr(handler, _HANDLER_TAG, True)
    for h in list(root.handlers):
        if getattr(h, _HANDLER_TAG, False):
            root.removeHandler(h)
    root.addHandler(handler)
    return root


def configure_from(conf, node: Optional[str] = None,
                   node_id: Optional[int] = None) -> logging.Logger:
    """Configure from a ``Config`` (log_level + log_json)."""
    return configure(
        level=conf.log_level,
        json_mode=bool(getattr(conf, "log_json", False)),
        node=node if node is not None else (conf.moniker or None),
        node_id=node_id,
    )
