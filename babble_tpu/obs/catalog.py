"""Instrument catalog — the single source of truth for every metric.

``NodeTelemetry`` registers instruments BY NAME through this catalog
(an unknown name raises, so an undocumented instrument cannot ship);
``docs/observability.md`` carries the same set as a markdown table; and
``python -m babble_tpu.obs.lint`` fails the build when the two drift in
either direction. Scopes:

- ``node``   — registered for every node;
- ``accel``  — registered only when the node runs with ``--accelerator``;
- ``global`` — process-wide (shared by co-located nodes).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple


class Instrument(NamedTuple):
    name: str
    kind: str  # counter | gauge | histogram
    labels: Tuple[str, ...]
    scope: str  # node | accel | global
    help: str


_C, _G, _H = "counter", "gauge", "histogram"

CATALOG: Tuple[Instrument, ...] = (
    # -- end-to-end latency + pipeline stages -------------------------------
    Instrument(
        "commit_latency_seconds", _H, (), "node",
        "End-to-end submit-to-commit latency for transactions admitted by "
        "THIS node's mempool (admit timestamp to Core.commit).",
    ),
    Instrument(
        "tx_stage_seconds", _H, ("stage",), "node",
        "Transaction lifecycle split: mempool_wait (admit to drain into a "
        "self-event) and consensus (drain to block commit).",
    ),
    Instrument(
        "sync_stage_seconds", _H, ("stage",), "node",
        "Per-stage wall time of the gossip/consensus pipeline: "
        "request_sync, decode, batch_verify, insert, divide_rounds, "
        "decide_fame, round_received, commit, proxy_deliver, "
        "process_sig_pool, diff, eager_sync, mempool_drain, self_event.",
    ),
    Instrument(
        "core_lock_wait_seconds", _H, (), "node",
        "Time spent WAITING to acquire the core lock per contended "
        "acquisition (uncontended acquires are not observed).",
    ),
    # -- core lock / ingest fast path ---------------------------------------
    Instrument(
        "core_lock_wait_seconds_total", _C, (), "node",
        "Total core-lock acquisition wait (the legacy lock_wait_ms_total, "
        "in seconds).",
    ),
    Instrument(
        "core_lock_acquisitions_total", _C, (), "node",
        "Core-lock acquisitions.",
    ),
    Instrument(
        "ingest_syncs_total", _C, (), "node",
        "Incoming syncs ingested (pull responses + eager pushes).",
    ),
    Instrument(
        "ingest_batch_verifies_total", _C, (), "node",
        "Native batch signature-verification calls (one per sync chunk on "
        "the happy path).",
    ),
    Instrument(
        "ingest_batch_size_max", _G, (), "node",
        "Largest batch handed to the batch verifier so far.",
    ),
    Instrument(
        "ingest_fallback_singles_total", _C, (), "node",
        "Per-event scalar signature re-checks after a batch reported "
        "failures (offender pinpointing).",
    ),
    # -- gossip / RPC surface ----------------------------------------------
    Instrument(
        "sync_requests_total", _C, (), "node",
        "SyncRequest RPCs served.",
    ),
    Instrument(
        "sync_errors_total", _C, (), "node",
        "SyncRequest handler errors.",
    ),
    Instrument(
        "rpc_errors_total", _C, ("type",), "node",
        "Handler crashes per RPC type (sync, eager_sync, fast_forward, "
        "join) — crashes, not remote faults.",
    ),
    Instrument(
        "gossip_transport_errors_total", _C, (), "node",
        "Outbound gossip rounds that failed with a TransportError "
        "(network faults, not handler errors).",
    ),
    Instrument(
        "sync_limit_truncations_total", _C, (), "node",
        "Incoming batches truncated to our sync_limit (receiving-side "
        "cap).",
    ),
    Instrument(
        "sync_diff_truncations_total", _C, (), "node",
        "Outbound push diffs cut to sync_limit before sending "
        "(sender-side cap) — a chronically-truncating peer is more than "
        "one sync_limit behind us.",
    ),
    Instrument(
        "submit_queue_depth", _G, (), "node",
        "Transactions sitting in the proxy submit queue (sampled at "
        "scrape).",
    ),
    # -- async gossip engine (docs/gossip.md) -------------------------------
    Instrument(
        "gossip_inflight_syncs", _G, (), "node",
        "Inbound syncs currently in the decode→verify→insert pipeline "
        "(between submit and response).",
    ),
    Instrument(
        "gossip_inflight_syncs_peak", _G, (), "node",
        "High-water mark of gossip_inflight_syncs.",
    ),
    Instrument(
        "gossip_pipelined_syncs_total", _C, (), "node",
        "Inbound syncs that went through the pipeline's bounded insert "
        "queue (vs handled inline).",
    ),
    Instrument(
        "gossip_backpressure_stalls_total", _C, (), "node",
        "Pipeline submits that found the insert queue full "
        "(backpressure propagating to the transport).",
    ),
    Instrument(
        "gossip_pipeline_queue_depth", _G, (), "node",
        "Prepared syncs sitting in the pipeline's bounded insert queue "
        "RIGHT NOW (sampled at scrape; the live-backpressure twin of "
        "the stall counters).",
    ),
    Instrument(
        "gossip_pull_pipelined_total", _C, (), "node",
        "Gossip pull legs whose insert tail went through the staged "
        "pipeline instead of running on the gossip thread.",
    ),
    Instrument(
        "gossip_pipeline_soft_depth", _G, (), "node",
        "Adaptive soft cap on the pipeline's insert queue: submits "
        "backpressure at this depth (shrinks under ingest congestion; "
        "equals the hard depth when uncongested).",
    ),
    # -- adaptive gossip scheduler (docs/gossip.md §Adaptive scheduling) ----
    Instrument(
        "adaptive_interval_seconds", _G, (), "node",
        "Gossip interval currently published by the adaptive scheduler "
        "(the fixed two-speed choice when adaptation is off).",
    ),
    Instrument(
        "adaptive_fanout", _G, (), "node",
        "Distinct gossip partners per tick currently published by the "
        "adaptive scheduler (1 when adaptation is off).",
    ),
    Instrument(
        "adaptive_adjustments_total", _C, (), "node",
        "Times the adaptive scheduler re-published interval, fan-out, "
        "or pipeline soft depth (hysteresis-gated output changes).",
    ),
    Instrument(
        "gossip_peer_behind_max", _G, (), "node",
        "Max events any peer trails US by, from the last exchanged "
        "known-maps (the adaptive spread signal).",
    ),
    Instrument(
        "gossip_self_behind_max", _G, (), "node",
        "Max events WE trail any peer by, from the last exchanged "
        "known-maps (the adaptive tempo signal).",
    ),
    Instrument(
        "selfevent_coalesced_total", _C, (), "node",
        "Extra self-events minted by hot-mempool coalescing (beyond the "
        "reference's one per tick).",
    ),
    # -- consensus progress -------------------------------------------------
    Instrument(
        "node_last_block_index", _G, (), "node",
        "Index of the last committed block.",
    ),
    Instrument(
        "node_last_consensus_round", _G, (), "node",
        "Last round that reached consensus (-1 before the first).",
    ),
    Instrument(
        "node_consensus_events", _G, (), "node",
        "Events that reached consensus order.",
    ),
    Instrument(
        "node_undetermined_events", _G, (), "node",
        "Events whose round-received is still undecided.",
    ),
    Instrument(
        "node_consensus_transactions_total", _C, (), "node",
        "Transactions carried by consensus events so far.",
    ),
    Instrument(
        "node_peers", _G, (), "node",
        "Current peer-set size as seen by the selector.",
    ),
    # -- mempool ------------------------------------------------------------
    Instrument(
        "mempool_pending", _G, (), "node",
        "Pending (admitted, not yet drained) transactions.",
    ),
    Instrument(
        "mempool_pending_bytes", _G, (), "node",
        "Bytes held by pending transactions.",
    ),
    Instrument(
        "mempool_inflight", _G, (), "node",
        "Drained-but-uncommitted transaction hashes tracked for dedup.",
    ),
    Instrument(
        "mempool_submitted_total", _C, (), "node",
        "Admission attempts.",
    ),
    Instrument(
        "mempool_accepted_total", _C, (), "node",
        "Admissions that returned `accepted`.",
    ),
    Instrument(
        "mempool_rejected_total", _C, ("reason",), "node",
        "Rejected admissions by verdict: full, duplicate, oversized, "
        "throttled, already_committed.",
    ),
    Instrument(
        "mempool_committed_total", _C, (), "node",
        "Transactions marked committed through this node's commit path.",
    ),
    Instrument(
        "mempool_evictions_total", _C, (), "node",
        "Oldest-pending evictions under the evict-oldest overflow policy.",
    ),
    Instrument(
        "mempool_requeued_total", _C, (), "node",
        "Drained transactions put back after a failed self-event insert.",
    ),
    Instrument(
        "mempool_commit_drops_total", _C, (), "node",
        "Pending copies dropped because the same tx committed via another "
        "node's event.",
    ),
    Instrument(
        "mempool_inflight_aged_total", _C, (), "node",
        "In-flight hashes aged out past the dedup cap.",
    ),
    # -- light-client gateway tier (docs/clients.md) ------------------------
    Instrument(
        "client_subscribers", _G, (), "node",
        "Live streaming-subscription connections on this node's "
        "SubscriptionHub (0 when --client-listen is off).",
    ),
    Instrument(
        "client_sub_queue_frames_max", _G, (), "node",
        "Largest per-subscriber outbound frame queue right now "
        "(sampled at scrape; the bound is sub_queue_frames).",
    ),
    Instrument(
        "client_pushed_blocks_total", _C, (), "node",
        "Sealed block frames queued to subscribers (one per block per "
        "subscriber).",
    ),
    Instrument(
        "client_shed_subscribers_total", _C, (), "node",
        "Subscribers shed for stalling (no socket progress with queued "
        "frames) or a chronic delivery deficit.",
    ),
    Instrument(
        "client_proofs_served_total", _C, (), "node",
        "GET /proof/<txid> requests answered with a signed Merkle "
        "inclusion proof.",
    ),
    Instrument(
        "client_proof_misses_total", _C, (), "node",
        "Proof lookups for unknown or aged-out transactions (404s).",
    ),
    Instrument(
        "client_txindex_entries", _G, (), "node",
        "Transactions currently indexed for proof serving (bounded by "
        "txindex_cap, oldest aged out).",
    ),
    Instrument(
        "client_checkpoint_exports_total", _C, (), "node",
        "GET /checkpoint fast-sync snapshots exported.",
    ),
    # -- lifecycle tier (docs/lifecycle.md) ---------------------------------
    Instrument(
        "lifecycle_events_retained", _G, (), "node",
        "Events currently held by the hashgraph store (post-compaction "
        "retained set; the plateau signal of checkpoint-prune).",
    ),
    Instrument(
        "lifecycle_rounds_retained", _G, (), "node",
        "Rounds currently held by the hashgraph store.",
    ),
    Instrument(
        "lifecycle_store_bytes", _G, (), "node",
        "Durable store footprint in bytes (SQLite page_count x "
        "page_size; 0 for a purely in-memory store).",
    ),
    Instrument(
        "lifecycle_prune_floor_round", _G, (), "node",
        "Retention floor: consensus history below this round has been "
        "compacted away (-1 before the first prune).",
    ),
    Instrument(
        "lifecycle_prune_lag_rounds", _G, (), "node",
        "Rounds of committed history retained above the prune floor "
        "(last_consensus_round - floor); grows unbounded when pruning "
        "is off or stalled.",
    ),
    Instrument(
        "lifecycle_prunes_total", _C, (), "node",
        "Checkpoint-prune compactions completed.",
    ),
    Instrument(
        "lifecycle_pruned_events_total", _C, (), "node",
        "Events dropped by compaction, cumulative.",
    ),
    Instrument(
        "lifecycle_behind_retention_total", _C, (), "node",
        "/checkpoint requests refused with the behind_retention slug "
        "(client asked for history below the prune floor).",
    ),
    # -- causal tracing / flight recorder ----------------------------------
    Instrument(
        "trace_sampled_txs_total", _C, (), "node",
        "Transactions sampled into the commit-provenance table "
        "(deterministic cross-node sampling, docs/observability.md "
        "§Causal tracing).",
    ),
    Instrument(
        "trace_provenance_entries", _G, (), "node",
        "Live commit-provenance records (bounded table, oldest evicted).",
    ),
    Instrument(
        "trace_provenance_evictions_total", _C, (), "node",
        "Provenance records evicted past the table cap.",
    ),
    Instrument(
        "trace_ctx_rpcs_total", _C, (), "node",
        "Inbound Sync/EagerSync/FastForward RPCs that carried a wire "
        "trace context.",
    ),
    Instrument(
        "watchdog_trips_total", _C, (), "node",
        "Stall-watchdog trips (busy node, no consensus progress past "
        "the threshold).",
    ),
    Instrument(
        "flight_dumps_total", _C, (), "node",
        "Flight-recorder artifacts written (bounded per node).",
    ),
    # -- peer selector / gossip health -------------------------------------
    Instrument(
        "selector_unhealthy_peers", _G, (), "node",
        "Peers with a nonzero consecutive-failure count.",
    ),
    Instrument(
        "selector_backed_off_peers", _G, (), "node",
        "Peers currently inside a backoff window.",
    ),
    Instrument(
        "selector_backoff_skips_total", _C, (), "node",
        "Peer picks skipped because the peer was backed off.",
    ),
    Instrument(
        "selector_probe_picks_total", _C, (), "node",
        "Deterministic probe picks of expired-backoff peers.",
    ),
    Instrument(
        "selector_starvation_overrides_total", _C, (), "node",
        "All-backed-off liveness overrides.",
    ),
    Instrument(
        "selector_quarantine_skips_total", _C, (), "node",
        "Peer picks skipped because the sentry quarantined the peer.",
    ),
    Instrument(
        "selector_quarantine_overrides_total", _C, (), "node",
        "All-quarantined liveness overrides.",
    ),
    # -- sentry -------------------------------------------------------------
    Instrument(
        "sentry_quarantined_peers", _G, (), "node",
        "Peers currently quarantined.",
    ),
    Instrument(
        "sentry_quarantines_total", _C, (), "node",
        "Quarantines imposed.",
    ),
    Instrument(
        "sentry_quarantine_deferrals_total", _C, (), "node",
        "Quarantines deferred by the BFT framing-guard cap.",
    ),
    Instrument(
        "sentry_readmissions_total", _C, (), "node",
        "Quarantine expiries that re-admitted a peer.",
    ),
    Instrument(
        "sentry_refused_rpcs_total", _C, (), "node",
        "Inbound syncs refused from quarantined peers.",
    ),
    Instrument(
        "sentry_proofs", _G, (), "node",
        "Durable equivocation proofs on file.",
    ),
    Instrument(
        "sentry_rejects_total", _C, ("cause",), "node",
        "Classified ingest rejections by cause slug "
        "(docs/robustness.md attack catalog).",
    ),
    # -- accelerator (scope: accel) ----------------------------------------
    Instrument(
        "accel_stage_seconds", _H, ("stage",), "accel",
        "Per-stage device-sweep time: build, delta_scan, pack, dispatch, "
        "kernel, readback, apply.",
    ),
    Instrument(
        "accel_sweeps_total", _C, (), "accel",
        "Voting sweeps executed on the device path.",
    ),
    Instrument(
        "accel_fallbacks_total", _C, (), "accel",
        "Sweeps that fell back to the host oracle.",
    ),
    Instrument(
        "accel_compile_waits_total", _C, (), "accel",
        "Sweeps that waited on an XLA compile.",
    ),
    Instrument(
        "accel_stale_drops_total", _C, (), "accel",
        "Sweep results dropped for arriving with a stale window "
        "generation.",
    ),
    Instrument(
        "accel_rebuilds_total", _C, (), "accel",
        "Window-state rebuilds (incremental path fell back to a full "
        "snapshot).",
    ),
    Instrument(
        "accel_rows_delta_total", _C, (), "accel",
        "Window rows uploaded as deltas.",
    ),
    Instrument(
        "accel_rows_reused_total", _C, (), "accel",
        "Window rows served from device-resident buffers.",
    ),
    Instrument(
        "accel_mesh_pad_rows_total", _C, (), "accel",
        "Witness rows padded onto windows to align the W axis with the "
        "mesh shard count.",
    ),
    Instrument(
        "accel_mesh_fallbacks_total", _C, (), "accel",
        "Mesh sweeps that fell back to the single-device program "
        "(unaligned window that could not be padded).",
    ),
    Instrument(
        "copro_waves_total", _C, (), "accel",
        "Coprocessor dispatch waves: batched sweep launches over a "
        "shared device mesh (process-wide).",
    ),
    Instrument(
        "copro_windows_total", _C, (), "accel",
        "Validator windows multiplexed through coprocessor waves "
        "(process-wide).",
    ),
    Instrument(
        "copro_validators", _G, (), "accel",
        "Distinct validators that have shared the coprocessor mesh "
        "(process-wide).",
    ),
    Instrument(
        "accel_breaker_state", _G, (), "accel",
        "Circuit-breaker state: 0=closed, 1=half_open, 2=open.",
    ),
    Instrument(
        "accel_breaker_opens_total", _C, (), "accel",
        "closed-to-open breaker transitions.",
    ),
    # -- process-wide (scope: global) --------------------------------------
    Instrument(
        "wire_cache_hits_total", _C, (), "global",
        "Wire-event serialization cache hits (process-wide).",
    ),
    Instrument(
        "wire_cache_misses_total", _C, (), "global",
        "Wire-event serialization cache misses (process-wide).",
    ),
    Instrument(
        "norm_cache_hits_total", _C, (), "global",
        "Canonical-JSON normalization cache hits (process-wide).",
    ),
    Instrument(
        "norm_cache_misses_total", _C, (), "global",
        "Canonical-JSON normalization cache misses (process-wide).",
    ),
    Instrument(
        "verify_cache_hits_total", _C, (), "global",
        "Signature-verdict cache hits (process-wide).",
    ),
    Instrument(
        "verify_cache_misses_total", _C, (), "global",
        "Signature-verdict cache misses (process-wide).",
    ),
    Instrument(
        "wire_bytes_sent_total", _C, (), "global",
        "Bytes written to gossip sockets, all transports and protocols "
        "(process-wide).",
    ),
    Instrument(
        "wire_bytes_received_total", _C, (), "global",
        "Bytes read from gossip sockets, all transports and protocols "
        "(process-wide).",
    ),
    Instrument(
        "codec_events_encoded_total", _C, (), "global",
        "Wire events encoded into binary blobs (blob-memo misses; "
        "process-wide).",
    ),
    Instrument(
        "codec_event_cache_hits_total", _C, (), "global",
        "Event sends served from the binary blob memo — one encode per "
        "event per process, however many peers it is pushed to.",
    ),
    Instrument(
        "codec_events_decoded_total", _C, (), "global",
        "Binary event blobs decoded at ingest (process-wide).",
    ),
    Instrument(
        "codec_conns_binary_total", _C, (), "global",
        "Inbound connections that negotiated the binary protocol "
        "(process-wide).",
    ),
    Instrument(
        "codec_conns_json_total", _C, (), "global",
        "Inbound connections that fell back to the legacy JSON framing "
        "(process-wide).",
    ),
    Instrument(
        "profile_stage_samples", _C, ("stage",), "global",
        "Sampling-profiler thread-stack samples bucketed into the stage "
        "taxonomy by frame matching (sync + accel stages plus "
        "lock_wait, idle, other; docs/observability.md §Sampling "
        "profiler).",
    ),
)

BY_NAME: Dict[str, Instrument] = {i.name: i for i in CATALOG}

# Stage label values documented for the span tables (docs lint checks
# the stage table too, so a new stage must be documented to ship).
SYNC_STAGES = (
    "request_sync", "decode", "batch_verify", "insert", "divide_rounds",
    "decide_fame", "round_received", "commit", "proxy_deliver",
    "process_sig_pool", "diff", "eager_sync", "mempool_drain",
    "self_event",
)
TX_STAGES = ("mempool_wait", "consensus")
ACCEL_STAGES = (
    "build", "delta_scan", "pack", "dispatch", "kernel", "readback",
    "apply",
)
# Profiler stage buckets (obs/profile.py): the union of the two stage
# families above plus the sampler-only buckets.
PROFILE_STAGES = SYNC_STAGES + ACCEL_STAGES + ("lock_wait", "idle", "other")


def spec(name: str) -> Instrument:
    """Catalog lookup used at registration time: an instrument that is
    not documented here cannot be registered at all."""
    try:
        return BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"instrument {name!r} is not in the obs catalog — add it to "
            "babble_tpu/obs/catalog.py AND docs/observability.md"
        ) from None
