"""Span tracer: follow one sync (or one self-originating gossip round)
through the pipeline.

A ``SyncTrace`` is opened by the node around a gossip leg; pipeline
stages timed anywhere below it (core decode/verify, hashgraph insert/
voting/commit — they call the telemetry's ``observe_stage``) attach to
the ACTIVE trace through a thread-local, so the deep consensus code
needs no span plumbing. Finishing a trace:

- feeds every stage duration into ``sync_stage_seconds{stage=...}``
  (already done eagerly at observe time), and
- appends a compact record to a bounded ring served at ``/telemetry``
  (``recent_syncs``): trace id, peer, total wall time, ordered stage
  list.

Overhead: two ``perf_counter`` calls per stage plus one list append —
and with ``BABBLE_OBS=0`` the node skips opening traces entirely (the
null trace below costs one attribute read per stage).
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

def staged(stage: str):
    """Method decorator timing one pipeline stage against the owning
    object's ``stage_observer`` attribute. When the observer is None
    (telemetry disabled, or a bare object outside a node) the original
    method runs with no clock reads — only one attribute check."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            obs = self.stage_observer
            if obs is None:
                return fn(self, *args, **kwargs)
            # the owner's injected stage clock, if any (Core wires the
            # node Clock here so simulated stages record virtual time)
            clk = getattr(self, "stage_clock", None) or time.perf_counter
            t0 = clk()
            try:
                return fn(self, *args, **kwargs)
            finally:
                obs(stage, clk() - t0)

        return wrapper

    return deco


class _NullStage:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_STAGE = _NullStage()


class NullTrace:
    """Stand-in when tracing is disabled; safe to call everywhere."""

    __slots__ = ()
    trace_id = 0

    def stage(self, name: str):
        return _NULL_STAGE

    def add(self, stage: str, seconds: float) -> None:
        pass

    def finish(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_TRACE = NullTrace()


class SyncTrace:
    """One gossip round's span. Not thread-safe by design: a trace is
    owned by the gossip thread that opened it (stages recorded from
    other threads attach to THEIR active trace, or none).

    Stage recordings are AGGREGATED per stage name (first-seen order,
    count + total seconds): a 1000-event sync observes ``insert`` once
    per event, and appending raw tuples would balloon each ring record
    to sync_limit entries and every /telemetry response to multi-MB."""

    __slots__ = ("trace_id", "kind", "peer_id", "t0", "_agg", "_tracer")

    def __init__(self, tracer: "Tracer", kind: str, peer_id: int):
        # ids and the clock come from the OWNING tracer (not process
        # globals) so two identical simulated runs in one process produce
        # identical trace records (docs/simulation.md determinism).
        self.trace_id = next(tracer._ids)
        self.kind = kind
        self.peer_id = peer_id
        self.t0 = tracer.clock()
        # stage -> [count, total_seconds]; dicts preserve insertion order
        self._agg: dict = {}
        self._tracer = tracer

    def stage(self, name: str):
        return _Stage(self, name)

    def add(self, stage: str, seconds: float) -> None:
        agg = self._agg.get(stage)
        if agg is None:
            self._agg[stage] = [1, seconds]
        else:
            agg[0] += 1
            agg[1] += seconds

    @property
    def stages(self) -> List[Tuple[str, float]]:
        """(stage, total_seconds) in first-observation order."""
        return [(name, agg[1]) for name, agg in self._agg.items()]

    def stage_counts(self) -> List[Tuple[str, int]]:
        return [(name, agg[0]) for name, agg in self._agg.items()]

    def finish(self) -> None:
        self._tracer._finish(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finish()
        return False


class _Stage:
    __slots__ = ("_trace", "_name", "_t0")

    def __init__(self, trace: SyncTrace, name: str):
        self._trace = trace
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._trace._tracer.observe(
            self._name, time.perf_counter() - self._t0, trace=self._trace
        )
        return False


class Tracer:
    """Owns the thread-local active trace and the recent-trace ring.
    ``stage_sink`` is the telemetry callback feeding the
    ``sync_stage_seconds`` histogram children."""

    def __init__(self, stage_sink=None, ring: int = 64,
                 clock=time.perf_counter):
        self._local = threading.local()
        self._ring: Deque[dict] = deque(maxlen=ring)
        self.stage_sink = stage_sink
        # per-tracer id stream + clock: deterministic under the sim
        # engine's virtual time (module-global state would leak between
        # runs in one process)
        self._ids = itertools.count(1)
        self.clock = clock
        self.traces_started = 0
        self.traces_finished = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self, kind: str, peer_id: int) -> SyncTrace:
        tr = SyncTrace(self, kind, peer_id)
        self._local.trace = tr
        self.traces_started += 1
        return tr

    def active(self) -> Optional[SyncTrace]:
        return getattr(self._local, "trace", None)

    def _finish(self, tr: SyncTrace) -> None:
        if getattr(self._local, "trace", None) is tr:
            self._local.trace = None
        self.traces_finished += 1
        self._ring.append(
            {
                "id": tr.trace_id,
                "kind": tr.kind,
                "peer": tr.peer_id,
                "total_ms": round(
                    1e3 * (self.clock() - tr.t0), 3
                ),
                "stages": [
                    [name, round(1e3 * s, 3)] for name, s in tr.stages
                ],
            }
        )

    # -- stage recording ----------------------------------------------------

    def observe(self, stage: str, seconds: float, trace=None) -> None:
        """Record one stage duration: histogram always, active trace
        when one is open on this thread."""
        sink = self.stage_sink
        if sink is not None:
            sink(stage, seconds)
        tr = trace if trace is not None else getattr(
            self._local, "trace", None
        )
        if tr is not None:
            tr.add(stage, seconds)

    def recent(self) -> List[dict]:
        return list(self._ring)
