"""Metrics-docs lint: the instrument catalog and the docs table must
match exactly, in both directions.

Usage:  python -m babble_tpu.obs.lint [docs/observability.md]

The docs file marks its instrument table with HTML comments::

    <!-- metrics-table-start -->
    | name | type | labels | scope | meaning |
    ...
    <!-- metrics-table-end -->

Every first-column backticked name between the markers is compared to
``obs.catalog.CATALOG``. A cataloged instrument missing from the table,
or a documented name missing from the catalog, fails with exit code 1
(wired into CI as ``make metricslint``).
"""

from __future__ import annotations

import re
import sys

from .catalog import CATALOG

START = "<!-- metrics-table-start -->"
END = "<!-- metrics-table-end -->"
_ROW = re.compile(r"^\|\s*`([a-zA-Z_][a-zA-Z0-9_]*)`")


def documented_names(text: str):
    try:
        body = text.split(START, 1)[1].split(END, 1)[0]
    except IndexError:
        raise SystemExit(
            f"metrics lint: marker comments {START!r}/{END!r} not found "
            "in the docs file"
        )
    names = set()
    for line in body.splitlines():
        m = _ROW.match(line.strip())
        if m:
            names.add(m.group(1))
    return names


def run(path: str) -> int:
    with open(path, encoding="utf-8") as f:
        docs = documented_names(f.read())
    cataloged = {i.name for i in CATALOG}
    missing_from_docs = sorted(cataloged - docs)
    missing_from_catalog = sorted(docs - cataloged)
    if missing_from_docs:
        print(
            "metrics lint: registered instruments missing from the docs "
            f"table in {path}:",
            file=sys.stderr,
        )
        for n in missing_from_docs:
            print(f"  - {n}", file=sys.stderr)
    if missing_from_catalog:
        print(
            "metrics lint: documented names missing from "
            "babble_tpu/obs/catalog.py:",
            file=sys.stderr,
        )
        for n in missing_from_catalog:
            print(f"  - {n}", file=sys.stderr)
    if missing_from_docs or missing_from_catalog:
        return 1
    print(
        f"metrics lint ok: {len(cataloged)} instruments match "
        f"between catalog and {path}"
    )
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else "docs/observability.md"
    return run(path)


if __name__ == "__main__":
    sys.exit(main())
