"""Metrics-docs lint — compat shim.

The metricslint implementation moved into the babblelint suite as its
``metrics`` pass (``babble_tpu/analysis/metrics_pass.py``,
docs/static_analysis.md); this module keeps the historical surface —
``python -m babble_tpu.obs.lint [docs/observability.md]``, plus the
``documented_names``/``run``/``main`` functions and the table markers —
so ``make metricslint`` and existing imports keep working unchanged.
"""

from __future__ import annotations

import sys

from ..analysis.metrics_pass import (  # noqa: F401
    END,
    START,
    documented_names,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
