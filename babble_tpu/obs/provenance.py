"""Per-transaction commit provenance: the cross-node causal-tracing
substrate (docs/observability.md §"Causal tracing").

Two pieces live here:

- the **wire trace context** helpers: a compact dict
  ``{"id": str, "origin": int, "hop": int, "ts": int-µs}`` carried on
  ``Sync``/``EagerSync``/``FastForward`` requests (``net/rpc.py``
  serializes it only when present, so peers that predate the field
  interoperate untouched). ``ts`` is integer microseconds since the
  sender's epoch clock — the canonical wire codec rejects floats, and
  µs resolution is far below cross-host clock skew anyway.

- the **ProvenanceTable**: a bounded per-node table keyed by tx hash
  recording where a transaction's latency went *on this node* — admit
  (mempool admission), drain (packaged into a self-event; origin node
  only), first_seen (first inserted via gossip, with wire/queue/insert
  attribution against the carrying sync's context), and commit (block
  index + round received). ``obs/traceview.py`` merges several nodes'
  exports into one cross-node timeline.

Sampling is **deterministic across nodes** — every node must trace the
SAME transactions or the merge shows partial hops. The filter is
``crc32(tx) % inverse == 0`` (cheap, byte-stable, no dependence on the
sha256 the hot ingest path would otherwise have to pay per tx just to
decide "not sampled"); clients (``demo/bombard.py --trace``) apply the
same filter to know which of their submissions are traceable.

Timestamps come from the owning node's ``Config.clock`` (``clock.time``)
— NEVER wall time directly — so simulated runs produce byte-identical
provenance for the same seed (docs/simulation.md), and live nodes stamp
comparable epoch seconds. Cross-host merges inherit host clock skew;
traceview orders hops by first-seen time, which survives modest skew.

``BABBLE_OBS=0`` (or ``sample=0``) disables the table entirely: call
sites gate on ``prov.enabled`` before touching transaction bytes.
"""

from __future__ import annotations

import threading
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional

from ..config.config import (
    DEFAULT_TRACE_SAMPLE,
    DEFAULT_TRACE_TABLE_CAP,
)
from ..crypto.hashing import sha256

#: default sampling rate — 1 in 64 transactions. Low enough that the
#: crc+sha cost disappears under the signature-verify budget, high
#: enough that any sustained workload populates the table. Value lives
#: in config.py (single source shared with the Config knobs).
DEFAULT_SAMPLE = DEFAULT_TRACE_SAMPLE
DEFAULT_CAP = DEFAULT_TRACE_TABLE_CAP

_CTX_ID_MAX = 64  # hostile peers must not park megabytes in our table


def make_ctx(trace_id: str, origin: int, ts_s: float, hop: int = 0) -> dict:
    """Build a wire trace context. ``ts_s`` is the sender's epoch clock
    in float seconds; the wire carries integer microseconds."""
    return {
        "id": str(trace_id)[:_CTX_ID_MAX],
        "origin": int(origin),
        "hop": int(hop),
        "ts": int(ts_s * 1e6),
    }


def parse_ctx(d) -> Optional[dict]:
    """Validate a received trace context; anything malformed degrades to
    None (no trace recorded, nothing rejected — the compat contract)."""
    if not isinstance(d, dict):
        return None
    try:
        return {
            "id": str(d["id"])[:_CTX_ID_MAX],
            "origin": int(d.get("origin", -1)),
            "hop": int(d.get("hop", 0)),
            "ts": int(d["ts"]),
        }
    except (KeyError, TypeError, ValueError):
        return None


def ctx_ts_s(ctx: dict) -> float:
    return ctx["ts"] / 1e6


def sample_inverse(sample: float) -> int:
    """Sampling rate -> crc modulus. <=0 disables (returns 0)."""
    if sample <= 0:
        return 0
    if sample >= 1:
        return 1
    return max(1, int(round(1.0 / sample)))


def tx_sampled(tx: bytes, inverse: int) -> bool:
    """The cross-node sampling law. ``inverse`` from sample_inverse()."""
    if inverse <= 0:
        return False
    if inverse == 1:
        return True
    return zlib.crc32(tx) % inverse == 0


class ProvenanceTable:
    """Bounded per-node provenance records, keyed by tx sha256 hex.

    All mutators take the table's own lock (callers already hold the
    mempool or core lock; this lock nests strictly inside both and is
    never held while calling out). Records are plain dicts so export is
    a shallow copy.
    """

    def __init__(self, clock=None, sample: float = DEFAULT_SAMPLE,
                 cap: int = DEFAULT_CAP, enabled: bool = True):
        if clock is None:
            from ..common.clock import WALL

            clock = WALL
        self._clock = clock
        self._lock = threading.Lock()
        self._recs: "OrderedDict[str, dict]" = OrderedDict()
        self.sample = sample
        self._inv = sample_inverse(sample)
        self.cap = max(1, cap)
        self._on = enabled
        # counters (obs catalog: trace_*)
        self.sampled_total = 0
        self.evictions = 0

    # -- knobs ---------------------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._on and self._inv > 0

    def configure(self, sample: Optional[float] = None,
                  cap: Optional[int] = None) -> None:
        """Apply Config knobs (Node.__init__ — the table is built by
        NodeTelemetry before the Config is in reach)."""
        with self._lock:
            if sample is not None:
                self.sample = sample
                self._inv = sample_inverse(sample)
            if cap is not None:
                self.cap = max(1, cap)

    def should_trace(self, tx: bytes) -> bool:
        return tx_sampled(tx, self._inv)

    # -- record plumbing -----------------------------------------------------

    def _rec(self, txid: str) -> dict:
        rec = self._recs.get(txid)
        if rec is None:
            rec = {"txid": txid}
            self._recs[txid] = rec
            self.sampled_total += 1
            while len(self._recs) > self.cap:
                self._recs.popitem(last=False)
                self.evictions += 1
        return rec

    # -- stamps --------------------------------------------------------------

    def admit(self, tx: bytes) -> None:
        """Mempool admission on the ORIGIN node."""
        if not self.enabled or not tx_sampled(tx, self._inv):
            return
        now = self._clock.time()
        txid = sha256(tx).hex()
        with self._lock:
            rec = self._rec(txid)
            rec.setdefault("admit", now)

    def drain(self, tx: bytes) -> None:
        """Packaged into a self-event (origin node; first drain wins —
        a requeued tx keeps its original stamp)."""
        if not self.enabled or not tx_sampled(tx, self._inv):
            return
        now = self._clock.time()
        txid = sha256(tx).hex()
        with self._lock:
            rec = self._rec(txid)
            rec.setdefault("drain", now)

    def first_seen_batch(self, txs, hop: Optional[dict]) -> None:
        """One inserted gossip event's transactions: stamp this node's
        first sight of each sampled tx, with per-hop attribution from
        the carrying sync's ``hop`` info (``{"from", "ctx", "recv",
        "start"}`` — see Core.sync)."""
        if not self.enabled:
            return
        inv = self._inv
        sampled = [tx for tx in txs if tx_sampled(tx, inv)]
        if not sampled:
            return
        now = self._clock.time()
        hop = hop or {}
        ctx = hop.get("ctx")
        recv = hop.get("recv")
        start = hop.get("start")
        with self._lock:
            for tx in sampled:
                rec = self._rec(sha256(tx).hex())
                if "first_seen" in rec or "drain" in rec:
                    # first sight wins; locally-drained txs were never a
                    # gossip hop on this node
                    continue
                rec["first_seen"] = now
                if hop.get("from") is not None:
                    rec["from"] = hop["from"]
                if recv is not None:
                    rec["recv"] = recv
                    if start is not None:
                        rec["queue_s"] = round(start - recv, 6)
                if ctx is not None:
                    rec["ctx"] = ctx["id"]
                    rec["origin"] = ctx["origin"]
                    rec["hop"] = ctx["hop"] + 1
                    if recv is not None:
                        rec["wire_s"] = round(recv - ctx_ts_s(ctx), 6)
                if start is not None:
                    rec["insert_s"] = round(now - start, 6)

    def commit_batch(self, txs, block_index: int,
                     round_received: int) -> None:
        """Block commit on THIS node (every node commits every block)."""
        if not self.enabled:
            return
        inv = self._inv
        sampled = [tx for tx in txs if tx_sampled(tx, inv)]
        if not sampled:
            return
        now = self._clock.time()
        with self._lock:
            for tx in sampled:
                rec = self._rec(sha256(tx).hex())
                if "commit" not in rec:
                    rec["commit"] = now
                    rec["block"] = block_index
                    rec["round_received"] = round_received

    # -- views ---------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._recs)

    def get(self, txid: str) -> Optional[dict]:
        with self._lock:
            rec = self._recs.get(txid)
            return dict(rec) if rec is not None else None

    def export(self, limit: Optional[int] = None) -> List[dict]:
        """Newest-last snapshot of up to ``limit`` records."""
        with self._lock:
            recs = list(self._recs.values())
        if limit is not None and limit >= 0:
            recs = recs[-limit:]
        return [dict(r) for r in recs]

    def stats(self) -> Dict[str, object]:
        return {
            "entries": len(self._recs),
            "sampled_total": self.sampled_total,
            "evictions": self.evictions,
            "sample": self.sample,
            "cap": self.cap,
            "enabled": self.enabled,
        }
