"""Cluster healthview: every node's /metrics + /stats + /suspects
merged into one "is the cluster healthy and where is it hurting"
snapshot.

Usage (live cluster — each node's service address):

    python -m babble_tpu.obs.healthview --nodes 127.0.0.1:8000,127.0.0.1:8001
    python -m babble_tpu.obs.healthview --nodes ... --window 5 [--json]
    python -m babble_tpu.obs.healthview --from-json dump.json

The live mode scrapes every endpoint twice, ``--window`` seconds
apart, and derives per node:

- **progress**: last consensus round / block index plus their advance
  rates over the window (a node with zero advance while the cluster
  moves is stalled, whatever its counters say);
- **lag**: round delta vs the cluster max round — the "peer lag
  matrix" collapsed to the number that matters per node;
- **queue depths**: submit queue, inbound-sync pipeline occupancy and
  its bounded insert queue (``gossip_pipeline_queue_depth``), mempool
  pending — live backpressure at a glance;
- **quarantine state**: the sentry's view (count + who, from
  ``/suspects``);
- **SLO**: commit-latency p50 vs the 500 ms north-star target, scored
  two ways — cumulative (the histogram since boot) and **windowed burn
  rate**: the share of commits inside the scrape window that exceeded
  500 ms divided by the 50% error budget the p50 target implies (burn
  > 1.0 means the window is eating budget faster than the SLO allows).

``--from-json`` consumes saved exports so deterministic-sim runs and
bench harnesses merge through the identical code path: either a list
of per-node entries ``{"node":…, "moniker":…, "stats": {…typed stats
snapshot…}}`` (single sample — rates/burn unavailable) or
``{"window_s": W, "samples": [[entry…], [entry…]]}`` for two-sample
exports with rates.

Output: a terminal table plus (``--json``) one machine-readable object
(the shape ``demo/bombard.py`` prints at exit and ``make healthsmoke``
asserts on).
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

SLO_COMMIT_P50_S = 0.5  # the north-star target (ROADMAP)
DEFAULT_WINDOW_S = 5.0
DEFAULT_MAX_LAG = 3  # rounds behind cluster max before a node is lagging


# -- Prometheus text parsing -------------------------------------------------


def parse_prom(text: str) -> Dict[str, float]:
    """{'name{labels}': value} for every sample line; malformed lines
    are skipped (a scrape mid-write must not kill the whole view)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out


def prom_histogram(samples: Dict[str, float],
                   name: str) -> Optional[Dict[str, object]]:
    """Cumulative buckets / sum / count of one (unlabeled) histogram."""
    buckets: List[Tuple[float, float]] = []
    for key, v in samples.items():
        if key.startswith(f'{name}_bucket{{le="'):
            le = key[len(f'{name}_bucket{{le="'):-2]
            buckets.append(
                (float("inf") if le == "+Inf" else float(le), v)
            )
    if not buckets:
        return None
    buckets.sort()
    return {
        "buckets": buckets,
        "sum": samples.get(f"{name}_sum", 0.0),
        "count": samples.get(f"{name}_count", 0.0),
    }


def hist_quantile(hist: Dict[str, object], q: float) -> Optional[float]:
    buckets = hist["buckets"]
    total = hist["count"]
    if not total:
        return None
    target = q * total
    lo = 0.0
    prev = 0.0
    for le, cum in buckets:
        if cum >= target:
            if le == float("inf"):
                return lo
            frac = (target - prev) / (cum - prev) if cum > prev else 1.0
            return lo + frac * (le - lo)
        lo = le if le != float("inf") else lo
        prev = cum
    return lo


def _share_over(hist_after: Dict[str, object],
                hist_before: Optional[Dict[str, object]],
                threshold: float) -> Tuple[Optional[float], float]:
    """(share of observations above ``threshold``, observation count)
    for the delta window between two cumulative histograms (or since
    boot when ``hist_before`` is None)."""

    def under(h):
        best = 0.0
        for le, cum in h["buckets"]:
            if le <= threshold:
                best = cum
            else:
                break
        return best

    count_b = hist_before["count"] if hist_before else 0.0
    under_b = under(hist_before) if hist_before else 0.0
    n = hist_after["count"] - count_b
    if n <= 0:
        return None, 0.0
    over = n - (under(hist_after) - under_b)
    return max(0.0, over) / n, n


# -- scraping ----------------------------------------------------------------


def _get_json(ep: str, path: str, timeout: float) -> Optional[dict]:
    try:
        with urllib.request.urlopen(
            f"http://{ep}{path}", timeout=timeout
        ) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None


def scrape_node(ep: str, timeout: float = 5.0) -> Optional[dict]:
    """One node's raw health sample over HTTP; None when the node is
    unreachable (the merge reports it as down rather than crashing)."""
    try:
        with urllib.request.urlopen(
            f"http://{ep}/metrics", timeout=timeout
        ) as r:
            metrics = parse_prom(r.read().decode())
    except Exception:
        return None
    stats = _get_json(ep, "/stats", timeout) or {}
    suspects = _get_json(ep, "/suspects", timeout) or {}
    return {
        "endpoint": ep,
        "ts": time.time(),
        "metrics": metrics,
        "clat": prom_histogram(metrics, "commit_latency_seconds"),
        "stats": stats,
        "suspects": suspects,
    }


def _metric(sample: dict, name: str, default: float = 0.0) -> float:
    return sample["metrics"].get(name, default)


def sample_from_stats(entry: dict) -> dict:
    """Normalize one saved-export entry (typed stats snapshot, the
    ``get_stats_snapshot()`` shape sim harnesses dump) into the scrape
    sample shape. No histogram buckets — the windowed burn rate is
    unavailable, the stats percentiles stand in for cumulative SLO."""
    stats = entry.get("stats", {})

    def num(key, default=0.0):
        v = stats.get(key)
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    metrics = {
        "node_last_consensus_round": num("last_consensus_round", -1.0),
        "node_last_block_index": num("last_block_index", -1.0),
        "submit_queue_depth": 0.0,
        "gossip_inflight_syncs": num("gossip_inflight_syncs"),
        "gossip_pipeline_queue_depth": num("gossip_pipeline_queue_depth"),
        "mempool_pending": num("mempool_pending_count",
                               num("transaction_pool")),
        "sentry_quarantined_peers": num("sentry_quarantined_peers"),
        "client_subscribers": num("client_subscribers"),
        "client_shed_subscribers_total": num("client_shed_subscribers"),
        "client_proofs_served_total": num("client_proofs_served"),
        # lifecycle tier (docs/lifecycle.md): retained store footprint
        "lifecycle_events_retained": num("lifecycle_events_retained"),
        "lifecycle_rounds_retained": num("lifecycle_rounds_retained"),
        "lifecycle_store_bytes": num("lifecycle_store_bytes"),
        "lifecycle_prune_floor_round": num("lifecycle_prune_floor", -1.0),
        "lifecycle_prune_lag_rounds": num("lifecycle_prune_lag_rounds"),
    }
    clat_p50_ms = stats.get("commit_latency_p50_ms")
    return {
        "endpoint": entry.get("endpoint"),
        "node": entry.get("node", stats.get("id")),
        "moniker": entry.get("moniker", stats.get("moniker")),
        "ts": entry.get("ts", 0.0),
        "metrics": metrics,
        "clat": None,
        "clat_p50_s": (
            None if clat_p50_ms is None else float(clat_p50_ms) / 1e3
        ),
        "clat_count": num("commit_latency_samples"),
        "stats": stats,
        "suspects": {},
    }


# -- the merge ---------------------------------------------------------------


def merge(samples0: List[Optional[dict]], samples1: List[Optional[dict]],
          window_s: Optional[float],
          max_lag: int = DEFAULT_MAX_LAG) -> dict:
    """Two rounds of per-node samples → the cluster health snapshot.
    ``samples0`` may be empty/None-padded (single-sample exports):
    rates and burn become None, liveness falls back to cumulative
    signals."""
    nodes = []
    rounds = []
    for i, s1 in enumerate(samples1):
        if s1 is None:
            nodes.append({"index": i, "down": True})
            continue
        s0 = samples0[i] if i < len(samples0) else None
        rnd = _metric(s1, "node_last_consensus_round", -1.0)
        blk = _metric(s1, "node_last_block_index", -1.0)
        rounds.append(rnd)
        round_rate = block_rate = None
        if s0 is not None and window_s:
            round_rate = (
                rnd - _metric(s0, "node_last_consensus_round", -1.0)
            ) / window_s
            block_rate = (
                blk - _metric(s0, "node_last_block_index", -1.0)
            ) / window_s
        # SLO: cumulative p50 + the windowed burn rate when buckets
        # (live scrape) are available, stats percentiles otherwise.
        p50 = burn = None
        window_n = 0.0
        if s1.get("clat") is not None:
            p50 = hist_quantile(s1["clat"], 0.5)
            share, window_n = _share_over(
                s1["clat"], s0.get("clat") if s0 else None,
                SLO_COMMIT_P50_S,
            )
            if share is not None:
                # p50 < target ⇔ at most 50% of commits over target:
                # the error budget is 0.5, burn = share / budget.
                burn = share / 0.5
        elif s1.get("clat_p50_s") is not None:
            p50 = s1["clat_p50_s"]
        stats = s1.get("stats", {})
        suspects = s1.get("suspects") or {}
        quarantined = suspects.get("quarantined") or []
        nodes.append({
            "index": i,
            "endpoint": s1.get("endpoint"),
            "node": s1.get("node", stats.get("id")),
            "moniker": s1.get("moniker", stats.get("moniker")),
            "state": stats.get("state"),
            "down": False,
            "round": rnd,
            "block": blk,
            "round_rate_per_s": (
                None if round_rate is None else round(round_rate, 3)
            ),
            "block_rate_per_s": (
                None if block_rate is None else round(block_rate, 3)
            ),
            "queues": {
                "submit": _metric(s1, "submit_queue_depth"),
                "pipeline_inflight": _metric(s1, "gossip_inflight_syncs"),
                "pipeline_queue": _metric(
                    s1, "gossip_pipeline_queue_depth"
                ),
                "mempool_pending": _metric(s1, "mempool_pending"),
            },
            # light-client read tier (docs/clients.md): live
            # subscription fan-out + slow-consumer shedding per node
            "subscribers": int(_metric(s1, "client_subscribers")),
            "shed_subscribers": int(
                _metric(s1, "client_shed_subscribers_total")
            ),
            "proofs_served": int(
                _metric(s1, "client_proofs_served_total")
            ),
            # lifecycle tier (docs/lifecycle.md): what each node still
            # holds after checkpoint-prune compaction — a node whose
            # retained set grows while its peers plateau has pruning
            # off or stalled (watch prune_lag_rounds climb).
            "store": {
                "events_retained": int(
                    _metric(s1, "lifecycle_events_retained")
                ),
                "rounds_retained": int(
                    _metric(s1, "lifecycle_rounds_retained")
                ),
                "store_bytes": int(_metric(s1, "lifecycle_store_bytes")),
                "prune_floor": int(
                    _metric(s1, "lifecycle_prune_floor_round", -1.0)
                ),
                "prune_lag_rounds": int(
                    _metric(s1, "lifecycle_prune_lag_rounds")
                ),
            },
            "quarantined_peers": int(
                _metric(s1, "sentry_quarantined_peers")
            ),
            "quarantined": quarantined,
            "commit_p50_ms": (
                None if p50 is None else round(1e3 * p50, 1)
            ),
            "slo_burn_rate": None if burn is None else round(burn, 3),
            "slo_window_commits": int(window_n),
        })

    max_round = max(rounds) if rounds else -1.0
    worst_lag = None
    for n in nodes:
        if n.get("down"):
            continue
        n["lag_rounds"] = int(max_round - n["round"])
        if worst_lag is None or n["lag_rounds"] > worst_lag["lag_rounds"]:
            worst_lag = n
        stalled = (
            n["round_rate_per_s"] is not None
            and n["round_rate_per_s"] <= 0
            and n["lag_rounds"] > 0
        )
        n["healthy"] = (
            not stalled
            and n["lag_rounds"] <= max_lag
            and n["quarantined_peers"] == 0
        )

    up = [n for n in nodes if not n.get("down")]
    p50s = [n["commit_p50_ms"] for n in up if n["commit_p50_ms"] is not None]
    cluster_p50 = max(p50s) if p50s else None  # worst node carries the SLO
    slo_ok = cluster_p50 is not None and cluster_p50 < 1e3 * SLO_COMMIT_P50_S
    return {
        "format": "babble-healthview/1",
        "ts": round(time.time(), 3),
        "window_s": window_s,
        "nodes": nodes,
        "cluster": {
            "n_nodes": len(nodes),
            "n_up": len(up),
            "n_healthy": sum(1 for n in up if n.get("healthy")),
            "max_round": max_round,
            "worst_lag_node": (
                None if worst_lag is None else {
                    "moniker": worst_lag.get("moniker"),
                    "endpoint": worst_lag.get("endpoint"),
                    "lag_rounds": worst_lag["lag_rounds"],
                }
            ),
            "commit_p50_ms_worst": cluster_p50,
            "slo_target_ms": 1e3 * SLO_COMMIT_P50_S,
            "slo_verdict": (
                "no-data" if cluster_p50 is None
                else ("ok" if slo_ok else "breach")
            ),
            "slo_burn_rate_max": max(
                (n["slo_burn_rate"] for n in up
                 if n["slo_burn_rate"] is not None),
                default=None,
            ),
            "all_healthy": bool(up) and all(
                n.get("healthy") for n in up
            ) and len(up) == len(nodes),
        },
    }


def _scrape_all(endpoints: List[str],
                timeout: float) -> List[Optional[dict]]:
    """One scrape round, all endpoints CONCURRENTLY — sequential
    scrapes of a fast cluster would read node N rounds later than node
    0 and fabricate lag."""
    import threading

    out: List[Optional[dict]] = [None] * len(endpoints)

    def one(i: int, ep: str) -> None:
        out[i] = scrape_node(ep, timeout)

    threads = [
        threading.Thread(target=one, args=(i, ep), daemon=True)
        for i, ep in enumerate(endpoints)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 1.0)
    return out


def collect(endpoints: List[str], window_s: float = DEFAULT_WINDOW_S,
            max_lag: int = DEFAULT_MAX_LAG,
            timeout: float = 5.0) -> dict:
    """Live two-sample scrape + merge over HTTP."""
    s0 = _scrape_all(endpoints, timeout)
    if window_s > 0:
        time.sleep(window_s)
    s1 = _scrape_all(endpoints, timeout)
    return merge(s0, s1, window_s or None, max_lag)


def from_export(payload, max_lag: int = DEFAULT_MAX_LAG) -> dict:
    """Saved-export merge (sim/bench JSON; see module docstring)."""
    if isinstance(payload, dict) and "samples" in payload:
        sample_sets = payload["samples"]
        window_s = payload.get("window_s")
        if len(sample_sets) == 1:
            s0: List[Optional[dict]] = []
            s1 = [sample_from_stats(e) for e in sample_sets[0]]
        else:
            s0 = [sample_from_stats(e) for e in sample_sets[-2]]
            s1 = [sample_from_stats(e) for e in sample_sets[-1]]
        return merge(s0, s1, window_s, max_lag)
    if isinstance(payload, list):
        return merge([], [sample_from_stats(e) for e in payload],
                     None, max_lag)
    raise ValueError(
        "export must be a list of node entries or "
        "{'window_s':…, 'samples': [[…], […]]}"
    )


# -- rendering ---------------------------------------------------------------


def render(view: dict) -> str:
    c = view["cluster"]
    lines = [
        f"cluster: {c['n_up']}/{c['n_nodes']} up, "
        f"{c['n_healthy']} healthy; max round {c['max_round']:.0f}; "
        f"SLO commit p50 {c['commit_p50_ms_worst']}ms vs "
        f"{c['slo_target_ms']:.0f}ms → {c['slo_verdict'].upper()}"
        + (
            f" (burn {c['slo_burn_rate_max']})"
            if c.get("slo_burn_rate_max") is not None else ""
        ),
        f"{'node':<10} {'state':<10} {'round':>7} {'lag':>4} "
        f"{'rnd/s':>7} {'blk/s':>7} {'p50ms':>8} {'burn':>6} "
        f"{'queues s/p/q/m':>16} {'subs':>5} {'shed':>4} "
        f"{'ev':>6} {'rnds':>5} {'storKB':>7} {'plag':>5} {'quar':>4}"
        "  health",
    ]
    for n in view["nodes"]:
        if n.get("down"):
            lines.append(f"{('#' + str(n['index'])):<10} DOWN")
            continue
        q = n["queues"]
        fmt_rate = (
            lambda v: "-" if v is None else f"{v:.2f}"
        )
        lines.append(
            f"{str(n.get('moniker') or n.get('node') or n['index']):<10} "
            f"{str(n.get('state') or '?'):<10} "
            f"{n['round']:>7.0f} {n['lag_rounds']:>4} "
            f"{fmt_rate(n['round_rate_per_s']):>7} "
            f"{fmt_rate(n['block_rate_per_s']):>7} "
            f"{('-' if n['commit_p50_ms'] is None else n['commit_p50_ms']):>8} "
            f"{('-' if n['slo_burn_rate'] is None else n['slo_burn_rate']):>6} "
            f"{q['submit']:.0f}/{q['pipeline_inflight']:.0f}"
            f"/{q['pipeline_queue']:.0f}/{q['mempool_pending']:>.0f}"
            f"{'':>4}{n.get('subscribers', 0):>5} "
            f"{n.get('shed_subscribers', 0):>4} "
            f"{n.get('store', {}).get('events_retained', 0):>6} "
            f"{n.get('store', {}).get('rounds_retained', 0):>5} "
            f"{n.get('store', {}).get('store_bytes', 0) // 1024:>7} "
            f"{n.get('store', {}).get('prune_lag_rounds', 0):>5} "
            f"{n['quarantined_peers']:>4}  "
            + ("ok" if n.get("healthy") else "UNHEALTHY")
        )
    return "\n".join(lines)


def summary_line(view: dict) -> str:
    """The one-liner bombard.py prints at exit."""
    c = view["cluster"]
    wl = c.get("worst_lag_node") or {}
    return (
        f"healthview: {c['n_healthy']}/{c['n_up']} healthy "
        f"(of {c['n_nodes']}), SLO {c['slo_verdict']} "
        f"(p50 {c['commit_p50_ms_worst']}ms vs {c['slo_target_ms']:.0f}ms"
        + (
            f", burn {c['slo_burn_rate_max']}"
            if c.get("slo_burn_rate_max") is not None else ""
        )
        + f"), worst lag {wl.get('moniker')}={wl.get('lag_rounds')} round(s)"
    )


# -- CLI ---------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m babble_tpu.obs.healthview",
        description="merge every node's /metrics + /stats + /suspects "
        "into one cluster health snapshot",
    )
    p.add_argument("--nodes", default="",
                   help="comma-separated service host:port list")
    p.add_argument("--from-json", dest="from_json", default="",
                   help="merge a saved export instead of scraping")
    p.add_argument("--window", type=float, default=DEFAULT_WINDOW_S,
                   help="seconds between the two scrape rounds (rates + "
                   "SLO burn window)")
    p.add_argument("--max-lag", type=int, default=DEFAULT_MAX_LAG,
                   help="rounds behind cluster max before unhealthy")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable snapshot")
    args = p.parse_args(sys.argv[1:] if argv is None else argv)

    if args.from_json:
        with open(args.from_json, encoding="utf-8") as f:
            view = from_export(json.load(f), args.max_lag)
    elif args.nodes:
        eps = [e.strip() for e in args.nodes.split(",") if e.strip()]
        view = collect(eps, args.window, args.max_lag)
    else:
        p.error("one of --nodes or --from-json is required")
        return 2

    if args.as_json:
        print(json.dumps(view, separators=(",", ":")))
    else:
        print(render(view))
    return 0 if view["cluster"]["n_up"] == view["cluster"]["n_nodes"] else 1


if __name__ == "__main__":
    sys.exit(main())
