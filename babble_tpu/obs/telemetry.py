"""NodeTelemetry: the per-node metrics registry and its wiring.

One instance is created by ``Core.__init__`` (so cores used standalone
— benches, tests — carry the same instruments as full nodes) and
extended by ``Node`` via ``bind_node``. It owns:

- the **hot instruments**: ``commit_latency_seconds``,
  ``sync_stage_seconds{stage}``, ``tx_stage_seconds{stage}``,
  ``core_lock_wait_seconds`` (observed from the mempool's commit feed,
  the pipeline stage observers, and the TimedLock hook);
- **function-backed instruments** over every subsystem's existing
  counters (core ingest_*, mempool, sentry, selector, accel, node RPC
  counters) — zero hot-path cost, evaluated at scrape;
- the **tracer** (span ring served at ``/telemetry``);
- the **legacy snapshot**: ``stats_snapshot()`` yields the typed
  ``get_stats`` payload (numbers stay numbers; ``Node.get_stats``
  stringifies at the edge — the compatibility contract recorded in
  docs/parity.md).

Every instrument name must exist in ``obs.catalog`` (registration
raises otherwise), which is what keeps the docs table honest.

With ``BABBLE_OBS=0`` the hot instruments are no-ops, the stage
observers are ``None`` (callers skip even the clock reads), and traces
are never opened — only the scrape-time function instruments remain.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from . import catalog
from .metrics import (
    GLOBAL,
    LATENCY_BUCKETS,
    STAGE_BUCKETS,
    Registry,
    enabled as obs_enabled,
    wire_global,
)
from .provenance import ProvenanceTable
from .trace import NULL_TRACE, Tracer


class NodeTelemetry:
    def __init__(self, core, enabled: Optional[bool] = None):
        self.enabled = obs_enabled() if enabled is None else enabled
        self.registry = Registry(enabled=self.enabled)
        wire_global()
        self._core = core
        self._node = None
        # The node's time source: trace spans and stage durations are
        # measured against it, so a simulated node's histograms hold
        # virtual-time latencies instead of host-load noise (the
        # wall-clock stamping bug this replaces made sim percentiles
        # garbage). Cores predating the clock field fall back to wall.
        from ..common.clock import WALL

        self.clock = getattr(core, "clock", None) or WALL

        # -- hot instruments ------------------------------------------------
        self.commit_latency = self._histogram(
            "commit_latency_seconds", LATENCY_BUCKETS
        )
        self._sync_stage = self._histogram(
            "sync_stage_seconds", STAGE_BUCKETS
        )
        self._tx_stage = self._histogram(
            "tx_stage_seconds", LATENCY_BUCKETS
        )
        self.lock_wait = self._histogram(
            "core_lock_wait_seconds", STAGE_BUCKETS
        )
        # Pre-resolved per-stage children so the hot path pays one dict
        # get, not a labels() call.
        self._stage_children: Dict[str, object] = {}
        self.tracer = Tracer(
            stage_sink=self._observe_stage_hist,
            clock=self.clock.perf_counter,
        )
        # Per-transaction commit provenance (docs/observability.md
        # §"Causal tracing"): admit/drain/first-seen/commit stamps keyed
        # by tx hash, deterministically sampled so every node traces the
        # same transactions. Node.__init__ applies the Config knobs via
        # provenance.configure(); standalone cores keep the defaults.
        self.provenance = ProvenanceTable(
            clock=self.clock, enabled=self.enabled
        )

        # The observer the pipeline code null-checks: None when disabled
        # so instrumented code skips even its perf_counter reads.
        self.stage_observer = self.tracer.observe if self.enabled else None
        self.lock_wait_observer = (
            self.lock_wait.observe if self.enabled else None
        )

        self._wire_core(core)
        self._wire_mempool(core.mempool)
        self._wire_sentry(core.sentry)
        self._wire_selector(core)
        if core.hg.accel is not None:
            self._wire_accel(core.hg.accel)

    # -- registration helpers ----------------------------------------------

    def _histogram(self, name, buckets):
        s = catalog.spec(name)
        return self.registry.histogram(name, s.help, buckets, s.labels)

    def _func(self, name, fn):
        s = catalog.spec(name)
        if s.kind == "counter":
            self.registry.func_counter(name, s.help, fn, s.labels)
        else:
            self.registry.func_gauge(name, s.help, fn, s.labels)

    # -- stage observation --------------------------------------------------

    def _observe_stage_hist(self, stage: str, seconds: float) -> None:
        child = self._stage_children.get(stage)
        if child is None:
            child = self._sync_stage.labels(stage=stage)
            self._stage_children[stage] = child
        child.observe(seconds)

    def observe_stage(self, stage: str, seconds: float) -> None:
        """Histogram + active-trace stage record (no-op when disabled)."""
        if self.stage_observer is not None:
            self.stage_observer(stage, seconds)

    def start_sync_trace(self, peer_id: int, kind: str = "sync"):
        if not self.enabled:
            return NULL_TRACE
        return self.tracer.start(kind, peer_id)

    def wire_ctx(self, node_id: int):
        """Trace context for an outbound Sync/EagerSync/FastForward RPC
        (obs/provenance.py wire format), tagged with the active gossip
        span's id so the receiver's records join this round. None when
        telemetry is disabled — the wire field is simply omitted.

        Built inline (not via make_ctx): this runs once per outbound
        gossip RPC, and the ids are short by construction so the
        hostile-length clamp is the receiver's job (parse_ctx)."""
        if not self.enabled:
            return None
        tr = self.tracer.active()
        tid = tr.trace_id if tr is not None else next(self.tracer._ids)
        return {
            "id": f"{node_id:x}-{tid}",
            "origin": node_id,
            "hop": 0,
            "ts": int(self.clock.time() * 1e6),
        }

    # -- wiring -------------------------------------------------------------

    def _wire_core(self, core) -> None:
        self._func("ingest_syncs_total", lambda: core.ingest_syncs)
        self._func(
            "ingest_batch_verifies_total",
            lambda: core.ingest_batch_verifies,
        )
        self._func(
            "ingest_batch_size_max", lambda: core.ingest_batch_size_max
        )
        self._func(
            "ingest_fallback_singles_total",
            lambda: core.ingest_fallback_singles,
        )
        self._func(
            "node_last_block_index", lambda: core.get_last_block_index()
        )
        self._func(
            "node_last_consensus_round",
            lambda: (
                -1
                if core.get_last_consensus_round_index() is None
                else core.get_last_consensus_round_index()
            ),
        )
        self._func(
            "node_consensus_events",
            lambda: core.get_consensus_events_count(),
        )
        self._func(
            "node_undetermined_events",
            lambda: len(core.get_undetermined_events()),
        )
        self._func(
            "node_consensus_transactions_total",
            lambda: core.get_consensus_transactions_count(),
        )
        self._func(
            "node_peers", lambda: len(core.peer_selector.get_peers())
        )

    def _wire_mempool(self, m) -> None:
        if self.enabled:
            m.attach_telemetry(
                self.commit_latency,
                self._tx_stage.labels(stage="mempool_wait"),
                self._tx_stage.labels(stage="consensus"),
            )
            m.attach_provenance(self.provenance)
        self._func(
            "trace_sampled_txs_total",
            lambda: self.provenance.sampled_total,
        )
        self._func(
            "trace_provenance_entries", lambda: len(self.provenance)
        )
        self._func(
            "trace_provenance_evictions_total",
            lambda: self.provenance.evictions,
        )
        self._func("mempool_pending", lambda: m.pending_count)
        self._func("mempool_pending_bytes", lambda: m.pending_bytes)
        self._func("mempool_inflight", lambda: len(m._inflight))
        self._func("mempool_submitted_total", lambda: m.submitted)
        self._func("mempool_accepted_total", lambda: m.accepted)
        self._func(
            "mempool_rejected_total",
            lambda: {
                "full": m.rejected_full,
                "duplicate": m.rejected_dup,
                "oversized": m.rejected_oversized,
                "throttled": m.rejected_throttled,
                "already_committed": m.committed_dedup_hits,
            },
        )
        self._func("mempool_committed_total", lambda: m.committed_total)
        self._func("mempool_evictions_total", lambda: m.evictions)
        self._func("mempool_requeued_total", lambda: m.requeued)
        self._func("mempool_commit_drops_total", lambda: m.commit_drops)
        self._func("mempool_inflight_aged_total", lambda: m.inflight_aged)

    def _wire_sentry(self, s) -> None:
        self._func(
            "sentry_quarantined_peers",
            lambda: s.stats()["sentry_quarantined_peers"],
        )
        self._func(
            "sentry_quarantines_total", lambda: s.quarantines_total
        )
        self._func(
            "sentry_quarantine_deferrals_total",
            lambda: s.quarantine_deferrals,
        )
        self._func("sentry_readmissions_total", lambda: s.readmissions)
        self._func("sentry_refused_rpcs_total", lambda: s.refused_rpcs)
        self._func("sentry_proofs", lambda: len(s._proofs))
        self._func("sentry_rejects_total", lambda: dict(s.rejects))

    def _wire_selector(self, core) -> None:
        # The selector object is REPLACED on membership changes
        # (Core.set_peers), so readers resolve it through the core on
        # every scrape instead of capturing the instance.
        # The two _peers gauges need a sweep over per-peer health state,
        # which only stats() computes (under the selector lock); the
        # plain counters are read as attributes so a scrape doesn't take
        # the selector lock once per instrument. A short-TTL memo lets
        # ONE sweep serve both gauges within a single collect pass.
        sel_memo = {"t": -1.0, "v": None}

        def _sel_stats():
            now = time.monotonic()
            if sel_memo["v"] is None or now - sel_memo["t"] > 0.05:
                sel_memo["v"] = core.peer_selector.stats()
                sel_memo["t"] = now
            return sel_memo["v"]

        for key in (
            "selector_unhealthy_peers",
            "selector_backed_off_peers",
        ):
            self._func(key, lambda k=key: _sel_stats()[k])
        for attr in (
            "backoff_skips",
            "probe_picks",
            "starvation_overrides",
            "quarantine_skips",
            "quarantine_overrides",
        ):
            self._func(
                f"selector_{attr}_total",
                lambda a=attr: getattr(core.peer_selector, a),
            )

    def _wire_accel(self, accel) -> None:
        hist = self._histogram("accel_stage_seconds", STAGE_BUCKETS)
        children: Dict[str, object] = {}

        def observe(stage: str, seconds: float) -> None:
            child = children.get(stage)
            if child is None:
                child = hist.labels(stage=stage)
                children[stage] = child
            child.observe(seconds)

        if self.enabled:
            accel.stage_observer = observe
        self._func("accel_sweeps_total", lambda: accel.sweeps)
        self._func("accel_fallbacks_total", lambda: accel.fallbacks)
        self._func(
            "accel_compile_waits_total", lambda: accel.compile_waits
        )
        self._func("accel_stale_drops_total", lambda: accel.stale_drops)
        self._func(
            "accel_rebuilds_total",
            lambda: (
                accel.window_state.rebuilds
                if accel.window_state is not None
                else 0
            ),
        )
        self._func(
            "accel_rows_delta_total", lambda: accel.rows_delta_total
        )
        self._func(
            "accel_rows_reused_total", lambda: accel.rows_reused_total
        )
        self._func(
            "accel_mesh_pad_rows_total", lambda: accel.mesh_pad_rows
        )
        self._func(
            "accel_mesh_fallbacks_total", lambda: accel.mesh_fallbacks
        )

        def _copro(key: str, default=0):
            from babble_tpu.hashgraph.sweep_batcher import SweepBatcher

            b = SweepBatcher._instance
            return b.stats().get(key, default) if b is not None else default

        self._func("copro_waves_total", lambda: _copro("copro_waves"))
        self._func("copro_windows_total", lambda: _copro("copro_windows"))
        self._func(
            "copro_validators", lambda: _copro("copro_validators")
        )
        self._func(
            "accel_breaker_state",
            lambda: {"closed": 0, "half_open": 1, "open": 2}.get(
                accel.breaker.stats()["breaker_state"], -1
            ),
        )
        self._func(
            "accel_breaker_opens_total", lambda: accel.breaker.opens
        )

    def bind_node(self, node) -> None:
        """Register the node-level instruments (RPC counters, queue
        depth) once the Node wrapping this core exists."""
        self._node = node
        self._func("sync_requests_total", lambda: node.sync_requests)
        self._func("sync_errors_total", lambda: node.sync_errors)
        self._func("rpc_errors_total", lambda: dict(node.rpc_errors))
        self._func(
            "gossip_transport_errors_total",
            lambda: node.gossip_transport_errors,
        )
        self._func(
            "sync_limit_truncations_total",
            lambda: node.sync_limit_truncations,
        )
        self._func(
            "sync_diff_truncations_total",
            lambda: node.sync_diff_truncations,
        )
        self._func("submit_queue_depth", lambda: node.submit_q.qsize())
        self._func(
            "core_lock_wait_seconds_total",
            lambda: round(node.core_lock.wait_s_total, 6),
        )
        self._func(
            "core_lock_acquisitions_total",
            lambda: node.core_lock.acquisitions,
        )
        self._func(
            "trace_ctx_rpcs_total", lambda: node.trace_ctx_rpcs
        )
        # Async gossip engine (docs/gossip.md): pipeline occupancy.
        # node.pipeline is None when the pipeline is disabled (sim clock
        # or config) — the instruments then read 0.
        self._func(
            "gossip_inflight_syncs",
            lambda: node.pipeline.inflight if node.pipeline else 0,
        )
        self._func(
            "gossip_inflight_syncs_peak",
            lambda: node.pipeline.inflight_peak if node.pipeline else 0,
        )
        self._func(
            "gossip_pipelined_syncs_total",
            lambda: node.pipeline.pipelined_syncs if node.pipeline else 0,
        )
        self._func(
            "gossip_backpressure_stalls_total",
            lambda: (
                node.pipeline.backpressure_stalls if node.pipeline else 0
            ),
        )
        self._func(
            "gossip_pipeline_queue_depth",
            lambda: node.pipeline.queue_depth() if node.pipeline else 0,
        )
        self._func(
            "gossip_pull_pipelined_total",
            lambda: node.pipeline.pull_pipelined if node.pipeline else 0,
        )
        self._func(
            "gossip_pipeline_soft_depth",
            lambda: (
                node.pipeline.soft_depth
                if node.pipeline
                else node.conf.gossip_pipeline_depth
            ),
        )
        # Adaptive gossip scheduler (docs/gossip.md §Adaptive
        # scheduling): the published plan, its change count, and the
        # per-peer lag extremes feeding the control law. With the
        # controller off the gauges read the fixed law's choices.
        self._func(
            "adaptive_interval_seconds",
            lambda: (
                node.adaptive.current().interval
                if node.adaptive is not None
                # gossip_plan IS the fixed law (pure) with the
                # controller off — one implementation, no drift
                else node.gossip_plan()[0]
            ),
        )
        self._func(
            "adaptive_fanout",
            lambda: (
                node.adaptive.current().fanout
                if node.adaptive is not None
                else 1
            ),
        )
        self._func(
            "adaptive_adjustments_total",
            lambda: (
                node.adaptive.adjustments
                if node.adaptive is not None
                else 0
            ),
        )
        # One lag sweep serves both gauges within a collect pass (the
        # sweep takes the selector + lag locks and prunes stale
        # entries — same short-TTL memo shape as the selector gauges).
        lag_memo = {"t": -1.0, "v": (0, 0)}

        def _lag():
            now = time.monotonic()
            if lag_memo["t"] < 0 or now - lag_memo["t"] > 0.05:
                lag_memo["v"] = node._lag_extremes()
                lag_memo["t"] = now
            return lag_memo["v"]

        self._func("gossip_peer_behind_max", lambda: _lag()[0])
        self._func("gossip_self_behind_max", lambda: _lag()[1])
        self._func(
            "selfevent_coalesced_total",
            lambda: node.core.selfevent_coalesced,
        )
        # Light-client gateway tier (docs/clients.md): hub gauges read 0
        # while --client-listen is off; the proof index always runs.
        # One stats() sweep serves all four hub instruments per collect
        # pass (the selector/lag memo shape).
        hub_memo = {"t": -1.0, "v": None}

        def _hub_stats():
            now = time.monotonic()
            if hub_memo["v"] is None or now - hub_memo["t"] > 0.05:
                hub = node.client_hub
                hub_memo["v"] = hub.stats() if hub is not None else {}
                hub_memo["t"] = now
            return hub_memo["v"]

        self._func(
            "client_subscribers",
            lambda: _hub_stats().get("subscribers", 0),
        )
        self._func(
            "client_sub_queue_frames_max",
            lambda: _hub_stats().get("queue_frames_max", 0),
        )
        self._func(
            "client_pushed_blocks_total",
            lambda: _hub_stats().get("pushed_blocks", 0),
        )
        self._func(
            "client_shed_subscribers_total",
            lambda: _hub_stats().get("shed", 0),
        )
        self._func("client_proofs_served_total", lambda: node.proofs_served)
        self._func("client_proof_misses_total", lambda: node.proof_misses)
        self._func("client_txindex_entries", lambda: len(node.txindex))
        self._func(
            "client_checkpoint_exports_total",
            lambda: node.checkpoint_exports,
        )
        # Lifecycle tier (docs/lifecycle.md): compaction progress and
        # the retained store footprint. The size gauges share the
        # node's 1s-TTL size_stats memo (COUNT(*) on a persistent
        # store), so a scrape never runs the queries more than once.
        self._func(
            "lifecycle_events_retained",
            lambda: node._store_size_stats().get("events", 0),
        )
        self._func(
            "lifecycle_rounds_retained",
            lambda: node._store_size_stats().get("rounds", 0),
        )
        self._func(
            "lifecycle_store_bytes",
            lambda: node._store_size_stats().get("store_bytes", 0),
        )
        self._func(
            "lifecycle_prune_floor_round",
            lambda: (
                -1
                if node.core.hg.prune_floor is None
                else node.core.hg.prune_floor
            ),
        )

        def _prune_lag():
            lcr = node.core.get_last_consensus_round_index()
            if lcr is None:
                return 0
            floor = node.core.hg.prune_floor or 0
            return max(0, int(lcr) - max(floor, 0))

        self._func("lifecycle_prune_lag_rounds", _prune_lag)
        self._func(
            "lifecycle_prunes_total",
            lambda: node.pruner.prunes if node.pruner else 0,
        )
        self._func(
            "lifecycle_pruned_events_total",
            lambda: node.pruner.events_pruned if node.pruner else 0,
        )
        self._func(
            "lifecycle_behind_retention_total",
            lambda: node.behind_retention_rejections,
        )
        self._func(
            "watchdog_trips_total",
            lambda: getattr(node.watchdog, "trips", 0),
        )
        self._func(
            "flight_dumps_total",
            lambda: getattr(node.watchdog, "dumps", 0),
        )

    # -- views --------------------------------------------------------------

    def commit_latency_ms(self) -> Dict[str, object]:
        """p50/p90/p99 (ms) + sample count of the end-to-end commit
        latency histogram — the north-star numbers."""
        s = self.commit_latency.summary()
        return {
            "count": s["count"],
            "p50_ms": None if s["p50"] is None else round(1e3 * s["p50"], 1),
            "p90_ms": None if s["p90"] is None else round(1e3 * s["p90"], 1),
            "p99_ms": None if s["p99"] is None else round(1e3 * s["p99"], 1),
        }

    def render_metrics(self) -> str:
        """Prometheus text exposition: node registry + process-global."""
        return self.registry.render() + GLOBAL.render()

    def telemetry_view(self) -> Dict[str, object]:
        """Structured JSON for /telemetry: every instrument (histograms
        with computed p50/p90/p99) + the recent sync-trace ring."""
        out: Dict[str, object] = {
            "enabled": self.enabled,
            "instruments": self.registry.snapshot(),
            "global": GLOBAL.snapshot(),
            "commit_latency_ms": self.commit_latency_ms(),
            "recent_syncs": self.tracer.recent(),
        }
        if self._node is not None:
            out["node"] = {
                "id": self._node.get_id(),
                "moniker": self._core.validator.moniker,
                "state": str(self._node.get_state()),
            }
        return out

    def value(self, name: str, **labels):
        """Assertion helper: current value of one instrument."""
        return self.registry.get(name, **labels)
