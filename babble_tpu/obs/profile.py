"""Always-on sampling profiler: thread stacks → stage-attributed
collapsed-stack flamegraphs.

A single process-wide daemon thread wakes ~``hz`` times a second
(default 50), snapshots every live thread's Python stack via
``sys._current_frames()``, and aggregates two views:

- **collapsed stacks** — ``root;frame;...;leaf  count`` lines (the
  Brendan Gregg flamegraph format), each stack rooted at its **stage
  bucket** so one glance shows where the CPU goes *per pipeline stage*;
- **stage counts** — samples bucketed into the existing stage taxonomy
  (``sync_stage_seconds`` stages, ``accel_stage_seconds`` stages, plus
  ``lock_wait`` / ``idle`` / ``other``) by frame matching: the
  innermost frame that matches a known (function, file) pair names the
  stage, a thread parked in ``TimedLock.acquire`` is ``lock_wait``, and
  a thread blocked in the stdlib's wait/select/accept plumbing is
  ``idle``. The counts feed the ``profile_stage_samples{stage}``
  instrument (process-global scope — co-located nodes share one
  interpreter and therefore one profiler).

Sampling is wait-free for the profiled threads — no locks are taken,
no code is instrumented; the only cost is the sampler thread's own
slice (measured alongside the obs kill switch in ``bench.py --obs``,
acceptance bound <2%). ``BABBLE_OBS=0`` or ``profile_hz=0`` keeps the
sampler off entirely.

On-demand windows (``GET /profile?seconds=N`` on the service) diff two
aggregate snapshots rather than starting anything; when no sampler is
running (killed, or a standalone tool), the capture spins a temporary
one for just that window. Output formats: ``collapsed`` (flamegraph
text), ``cprofile`` (a pstats-style self/cumulative table estimated
from the same samples), ``json``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from .metrics import GLOBAL, enabled as obs_enabled

DEFAULT_HZ = 50.0
MAX_STACK_DEPTH = 48
MAX_STACKS = 8192  # distinct collapsed stacks kept; overflow aggregates

# -- stage taxonomy via frame matching --------------------------------------
# function name -> (path suffix, stage); innermost match wins. The
# suffixes pin common names ("commit", "acquire") to the module that
# gives them their stage meaning (docs/observability.md §Span stages).
_FRAME_TABLE: Dict[str, Tuple[str, str]] = {
    # sync stages
    "prepare_sync": ("node/core.py", "decode"),
    "_decode_chunk": ("node/core.py", "decode"),
    "_batch_prevalidate": ("node/core.py", "batch_verify"),
    "insert_event": ("hashgraph/hashgraph.py", "insert"),
    "insert_event_and_run_consensus": ("hashgraph/hashgraph.py", "insert"),
    "divide_rounds": ("hashgraph/hashgraph.py", "divide_rounds"),
    "decide_fame": ("hashgraph/hashgraph.py", "decide_fame"),
    "decide_round_received": ("hashgraph/hashgraph.py", "round_received"),
    "process_decided_rounds": ("hashgraph/hashgraph.py", "commit"),
    "commit": ("node/core.py", "proxy_deliver"),
    "add_self_event": ("node/core.py", "self_event"),
    "process_sig_pool": ("node/node.py", "process_sig_pool"),
    "_pull": ("node/node.py", "request_sync"),
    "_push": ("node/node.py", "eager_sync"),
    # accel stages (hashgraph/accel.py + ops/voting.py)
    "build_voting_window": ("ops/voting.py", "build"),
    "_snapshot": ("hashgraph/accel.py", "pack"),
    "_dispatch": ("hashgraph/accel.py", "dispatch"),
    "_dispatch_snap": ("hashgraph/accel.py", "dispatch"),
    "_compile_bucket": ("hashgraph/accel.py", "dispatch"),
    "_flush": ("hashgraph/accel.py", "kernel"),
    "apply_sweep_result": ("", "apply"),
    # lock wait: the instrumented core lock only — a thread inside
    # TimedLock.acquire is by definition waiting on the core lock
    "acquire": ("common/timed_lock.py", "lock_wait"),
}

# Innermost-frame (function, stdlib file) pairs that mean the thread is
# parked, not working. Matched by basename — stdlib paths vary.
_IDLE_FUNCS = frozenset(
    (
        "wait", "_wait_for_tstate_lock", "get", "put", "select", "poll",
        "accept", "recv", "recv_into", "readinto", "sleep", "read",
        "readline", "flush", "settimeout", "join", "epoll",
    )
)
_IDLE_FILES = frozenset(
    ("threading.py", "queue.py", "selectors.py", "socket.py", "ssl.py",
     "socketserver.py", "connection.py", "subprocess.py")
)


def frame_meta(fn: str, fname: str) -> Tuple[Optional[str], bool]:
    """(matched stage or None, marks-idle-when-innermost) for one
    frame — the single classification rule the sampler caches per code
    object. ``sleep`` covers Python sleep wrappers (common/clock.py),
    and this module's own frames mark idle because a thread parked in
    C-level ``time.sleep`` shows its Python caller as innermost."""
    path = fname.replace("\\", "/")
    stage = None
    hit = _FRAME_TABLE.get(fn)
    if hit is not None and (not hit[0] or path.endswith(hit[0])):
        stage = hit[1]
    idle = (
        (fn in _IDLE_FUNCS and os.path.basename(fname) in _IDLE_FILES)
        or fn == "sleep"
        or path.endswith("obs/profile.py")
    )
    return stage, idle


def stack_bucket(metas) -> str:
    """Stage bucket for one stack from per-frame ``(stage, idle)``
    pairs, innermost first: idle counts only at the innermost frame,
    then the first stage match walking outward, else ``other``. THE
    classification walk — classify() and the sampler hot path both run
    this, so the tested rule cannot diverge from the shipped one."""
    for depth, (stage, idle) in enumerate(metas):
        if depth == 0 and idle:
            return "idle"
        if stage is not None:
            return stage
    return "other"


def classify(frames: List[Tuple[str, str]]) -> str:
    """Stage bucket for one ``(function, filename)`` stack (innermost
    first) — the uncached reference path over the same rule."""
    return stack_bucket(frame_meta(fn, fname) for fn, fname in frames)


def _frame_label(fn: str, fname: str) -> str:
    base = os.path.basename(fname)
    if base.endswith(".py"):
        base = base[:-3]
    return f"{base}:{fn}"


class StackSampler:
    """The process-wide sampler. Aggregates are written by the sampler
    thread only and read by copy (GIL atomicity), so the hot path of
    every *profiled* thread pays nothing.

    Tick cost is kept low by caching per-code-object metadata (label,
    matched stage, idle-ness) the first time a frame is seen and
    aggregating stacks as tuples of interned labels — string rendering
    happens lazily at snapshot time, never on the sampling path."""

    def __init__(self, hz: float = DEFAULT_HZ):
        self.hz = max(1.0, min(float(hz), 1000.0))
        self.period_s = 1.0 / self.hz
        self.samples_total = 0  # one per thread per tick
        self.ticks = 0
        self.started_at: Optional[float] = None
        self.stage_counts: Dict[str, int] = {}
        # (stage, tuple-of-labels root→leaf) -> count
        self._stacks: Dict[Tuple[str, Tuple[str, ...]], int] = {}
        # code object -> (label, stage-or-None, is_idle_innermost)
        self._code_meta: Dict[object, Tuple[str, Optional[str], bool]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self.started_at = time.time()
        t = threading.Thread(
            target=self._loop, name="obs-profiler", daemon=True
        )
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=1.0)
        self._thread = None

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once(skip_ident=me)
            except Exception:
                # the profiler must never take the process down
                pass

    # -- sampling ------------------------------------------------------------

    def _meta(self, code) -> Tuple[str, Optional[str], bool]:
        """Cached per-code metadata: collapsed-stack label, the stage
        this frame matches (if any), and whether it marks the thread
        idle when innermost. One classify() cost per unique code object
        per process lifetime."""
        m = self._code_meta.get(code)
        if m is None:
            fn, fname = code.co_name, code.co_filename
            stage, idle = frame_meta(fn, fname)
            m = (sys.intern(_frame_label(fn, fname)), stage, idle)
            self._code_meta[code] = m
        return m

    def sample_once(self, skip_ident: Optional[int] = None) -> None:
        """One tick: every live thread's stack into the aggregates.
        Public for tests and for sim harnesses that want deterministic
        tick counts."""
        self.ticks += 1
        meta = self._meta
        for ident, frame in sys._current_frames().items():
            if ident == skip_ident:
                continue
            labels: List[str] = []
            metas: List[Tuple[Optional[str], bool]] = []
            f = frame
            depth = 0
            while f is not None and depth < MAX_STACK_DEPTH:
                label, frame_stage, frame_idle = meta(f.f_code)
                labels.append(label)
                metas.append((frame_stage, frame_idle))
                f = f.f_back
                depth += 1
            stage = stack_bucket(metas)
            self.samples_total += 1
            self.stage_counts[stage] = self.stage_counts.get(stage, 0) + 1
            labels.reverse()
            key = (stage, tuple(labels))
            if key in self._stacks:
                self._stacks[key] += 1
            elif len(self._stacks) < MAX_STACKS:
                self._stacks[key] = 1
            else:
                k = ("other", ("(stack-table-full)",))
                self._stacks[k] = self._stacks.get(k, 0) + 1

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        # list(...) first: the items copy is one C-level op the GIL
        # makes atomic, where a Python-level comprehension over the
        # live dict would race the sampler thread's inserts ("dict
        # changed size during iteration"). Collapsed keys are rendered
        # from the copy — never on the sampling path.
        items = list(self._stacks.items())
        stacks = {
            f"stage:{stage};" + ";".join(labels): count
            for (stage, labels), count in items
        }
        return {
            "hz": self.hz,
            "samples": self.samples_total,
            "ticks": self.ticks,
            "stages": dict(self.stage_counts),
            "stacks": stacks,
        }


def _diff_counts(after: Dict[str, int],
                 before: Dict[str, int]) -> Dict[str, int]:
    out = {}
    for k, v in after.items():
        d = v - before.get(k, 0)
        if d > 0:
            out[k] = d
    return out


def collapsed_text(stacks: Dict[str, int]) -> str:
    """Flamegraph collapsed-stack format, biggest first."""
    lines = [
        f"{key} {count}"
        for key, count in sorted(
            stacks.items(), key=lambda kv: (-kv[1], kv[0])
        )
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def cprofile_text(stacks: Dict[str, int], period_s: float,
                  limit: int = 40) -> str:
    """pstats-style table ESTIMATED from samples: self/cumulative
    sample counts converted to seconds at the sampling period. The
    header says so — these are statistical times, not cProfile's
    deterministic ones, but the columns read the same way."""
    self_c: Dict[str, int] = {}
    cum_c: Dict[str, int] = {}
    total = 0
    for key, count in stacks.items():
        frames = key.split(";")
        total += count
        if not frames:
            continue
        leaf = frames[-1]
        self_c[leaf] = self_c.get(leaf, 0) + count
        for fr in set(frames):
            cum_c[fr] = cum_c.get(fr, 0) + count
    hdr = (
        f"sampled profile: {total} samples at {1.0 / period_s:.0f} Hz "
        f"(period {1e3 * period_s:.1f} ms); times are samples x period\n"
        f"{'samples':>9} {'self_s':>8} {'self%':>6} {'cum_s':>8} "
        f"{'cum%':>6}  function\n"
    )
    rows = []
    for fr, n in sorted(self_c.items(), key=lambda kv: -kv[1])[:limit]:
        cn = cum_c.get(fr, n)
        rows.append(
            f"{n:>9} {n * period_s:>8.3f} "
            f"{(100.0 * n / total if total else 0):>6.1f} "
            f"{cn * period_s:>8.3f} "
            f"{(100.0 * cn / total if total else 0):>6.1f}  {fr}"
        )
    return hdr + "\n".join(rows) + ("\n" if rows else "")


# -- process-wide singleton --------------------------------------------------

_sampler: Optional[StackSampler] = None
_lock = threading.Lock()


def stage_counts() -> Dict[str, int]:
    """Live per-stage sample counts, empty when no sampler runs — the
    reader behind the profile_stage_samples{stage} instrument
    (registered by metrics.wire_global so the catalog contract holds
    whether or not the profiler ever started)."""
    s = _sampler
    return dict(s.stage_counts) if s is not None else {}


def resolve_hz(hz: Optional[float] = None) -> float:
    """Config value unless the env overrides (whole-cluster toggles
    without touching every node's flags): BABBLE_PROFILE_HZ."""
    env = os.environ.get("BABBLE_PROFILE_HZ")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return DEFAULT_HZ if hz is None else float(hz)


def ensure_started(hz: Optional[float] = None) -> Optional[StackSampler]:
    """Start (or return) the process sampler. None when profiling is
    off (BABBLE_OBS=0 kill switch, or resolved hz <= 0)."""
    global _sampler
    if not obs_enabled():
        return None
    hz = resolve_hz(hz)
    if hz <= 0:
        return None
    with _lock:
        if _sampler is None or not _sampler.running():
            _sampler = StackSampler(hz=hz)
            _sampler.start()
        return _sampler


def sampler() -> Optional[StackSampler]:
    return _sampler


def stop() -> None:
    """Test hook: stop and forget the process sampler."""
    global _sampler
    with _lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


def capture(seconds: float = 3.0,
            hz: Optional[float] = None) -> Dict[str, object]:
    """One profiling window: diff the running sampler's aggregates
    across ``seconds`` (or run a temporary sampler for just the window
    when none is running and the kill switch allows one).

    Returns ``{seconds, hz, samples, stages, stacks}`` — raw dicts;
    render with :func:`collapsed_text` / :func:`cprofile_text`."""
    seconds = max(0.05, min(float(seconds), 60.0))
    s = _sampler if _sampler is not None and _sampler.running() else None
    temp = None
    if s is None:
        if not obs_enabled():
            return {"error": "profiler disabled (BABBLE_OBS=0)"}
        temp = StackSampler(hz=resolve_hz(hz))
        temp.start()
        s = temp
    before = s.snapshot()
    time.sleep(seconds)
    after = s.snapshot()
    if temp is not None:
        temp.stop()
    return {
        "seconds": seconds,
        "hz": s.hz,
        "always_on": temp is None,
        "samples": after["samples"] - before["samples"],
        "stages": _diff_counts(after["stages"], before["stages"]),
        "stacks": _diff_counts(after["stacks"], before["stacks"]),
    }
