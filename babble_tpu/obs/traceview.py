"""traceview: merge several nodes' provenance exports into one
cross-node transaction timeline.

Usage (live cluster — point it at each node's service address):

    python -m babble_tpu.obs.traceview --nodes 127.0.0.1:8000,127.0.0.1:8001
    python -m babble_tpu.obs.traceview --nodes ... --txid <sha256 hex>
    python -m babble_tpu.obs.traceview --from-json dump.json [--json]

``--from-json`` takes a file of ``[{"node":…, "moniker":…, "records":
[…]}, …]`` — exactly what ``GET /traces`` returns per node — so sim
harness runs (or saved scrapes) merge identically to live clusters:
dump each node's ``node.get_traces()`` to one JSON list and point the
tool at the file.

The merge joins per-node records by txid and derives the cross-node
view: the origin (the node holding the ``admit`` stamp), hop order
(nodes ranked by their ``first_seen`` time — gossip is epidemic, so hop
N is "the Nth node the transaction reached", not a path through a fixed
topology), per-hop latency attribution (``wire`` from the carried trace
context's send stamp, ``queue`` transport-arrival → handler start,
``insert`` handler start → post-insert, ``consensus`` first-seen →
commit), and commit spread (first/last node commit). Timestamps are
each node's ``Config.clock.time()``; merging hosts with skewed clocks
skews the *cross-node* deltas (per-node attribution is immune).
"""

from __future__ import annotations

import json
import math
import sys
import urllib.error
import urllib.request
from typing import Dict, List, Optional


def fetch_node(endpoint: str, txid: Optional[str] = None,
               limit: int = 256, timeout: float = 5.0) -> Optional[dict]:
    """One node's provenance export over HTTP: ``/trace/<txid>`` (None
    on 404 — the node never saw the tx) or bulk ``/traces``."""
    path = f"/trace/{txid}" if txid else f"/traces?limit={limit}"
    try:
        with urllib.request.urlopen(
            f"http://{endpoint}{path}", timeout=timeout
        ) as r:
            body = json.loads(r.read().decode())
    except urllib.error.HTTPError as err:
        if err.code == 404:
            return None
        raise
    if txid:
        # normalize the single-record shape to the bulk export shape
        return {
            "node": body.get("node"),
            "moniker": body.get("moniker"),
            "records": [body],
        }
    return body


def merge_tx(txid: str, node_exports: List[dict]) -> Optional[dict]:
    """Join one transaction's records across nodes. ``node_exports`` is
    a list of ``/traces``-shaped dicts; returns None when no node holds
    the txid."""
    per_node = []
    for exp in node_exports:
        for rec in exp.get("records", ()):
            if rec.get("txid") == txid:
                per_node.append(
                    {
                        "node": exp.get("node"),
                        "moniker": exp.get("moniker"),
                        **rec,
                    }
                )
    if not per_node:
        return None

    origin = next((r for r in per_node if "admit" in r), None)
    hops = sorted(
        (r for r in per_node if "first_seen" in r),
        key=lambda r: r["first_seen"],
    )
    commits = [r for r in per_node if "commit" in r]
    timeline: List[list] = []
    if origin is not None:
        if "admit" in origin:
            timeline.append([origin["admit"], origin["node"], "admit"])
        if "drain" in origin:
            timeline.append([origin["drain"], origin["node"], "self_event"])
    merged_hops = []
    for i, r in enumerate(hops):
        timeline.append([r["first_seen"], r["node"], f"hop{i + 1}"])
        consensus_s = (
            round(r["commit"] - r["first_seen"], 6)
            if "commit" in r else None
        )
        merged_hops.append(
            {
                "hop": i + 1,
                "node": r["node"],
                "moniker": r.get("moniker"),
                "from": r.get("from"),
                "ctx": r.get("ctx"),
                "first_seen": r["first_seen"],
                "wire_s": r.get("wire_s"),
                "queue_s": r.get("queue_s"),
                "insert_s": r.get("insert_s"),
                "consensus_s": consensus_s,
            }
        )
    for r in commits:
        timeline.append([r["commit"], r["node"], "commit"])
    timeline.sort(key=lambda e: (e[0], str(e[1])))

    out: Dict[str, object] = {
        "txid": txid,
        "origin": None if origin is None else origin["node"],
        "admit": None if origin is None else origin.get("admit"),
        "drain": None if origin is None else origin.get("drain"),
        "hops": merged_hops,
        "nodes_seen": len(per_node),
        "committed_on": len(commits),
        "block": commits[0].get("block") if commits else None,
        "round_received": (
            commits[0].get("round_received") if commits else None
        ),
        "commit_first": (
            min(r["commit"] for r in commits) if commits else None
        ),
        "commit_last": (
            max(r["commit"] for r in commits) if commits else None
        ),
        "timeline": timeline,
    }
    if origin is not None and "admit" in origin and commits:
        out["e2e_s"] = round(out["commit_last"] - origin["admit"], 6)
    out["monotone"] = _monotone(out, per_node)
    return out


def _monotone(merged: dict, per_node: List[dict]) -> bool:
    """Sanity invariant asserted by ``make tracesmoke``: admit ≤ drain ≤
    every remote first-seen, and each node's first-seen ≤ its commit."""
    admit = merged.get("admit")
    drain = merged.get("drain")
    if admit is not None and drain is not None and drain < admit:
        return False
    floor = drain if drain is not None else admit
    for r in per_node:
        fs = r.get("first_seen")
        if fs is not None:
            if floor is not None and fs < floor:
                return False
            if "commit" in r and r["commit"] < fs:
                return False
    return True


def merge_all(node_exports: List[dict]) -> List[dict]:
    """Merge every txid appearing in any export (admit-time order where
    known, then first-seen)."""
    txids = []
    seen = set()
    for exp in node_exports:
        for rec in exp.get("records", ()):
            t = rec.get("txid")
            if t and t not in seen:
                seen.add(t)
                txids.append(t)
    merged = [merge_tx(t, node_exports) for t in txids]
    merged = [m for m in merged if m is not None]
    merged.sort(
        key=lambda m: (
            m["admit"] if m["admit"] is not None
            else (m["hops"][0]["first_seen"] if m["hops"] else 0.0)
        )
    )
    return merged


# -- attribution summary (bombard --trace) ---------------------------------


def _pct(values: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile: ceil(q*n)-1 (int(q*n) would bias small
    samples high — p50 of two values must be the lower one)."""
    vals = sorted(v for v in values if v is not None)
    if not vals:
        return None
    idx = max(0, math.ceil(q * len(vals)) - 1)
    return vals[min(len(vals) - 1, idx)]


def attribution_summary(merged: List[dict]) -> Dict[str, dict]:
    """p50/p99 per attribution stage over every hop of every merged tx,
    plus the end-to-end and origin-side splits."""
    stages: Dict[str, List[float]] = {
        "wire": [], "queue": [], "insert": [], "consensus": [],
        "mempool_wait": [], "e2e": [],
    }
    for m in merged:
        if m.get("admit") is not None and m.get("drain") is not None:
            stages["mempool_wait"].append(m["drain"] - m["admit"])
        if m.get("e2e_s") is not None:
            stages["e2e"].append(m["e2e_s"])
        for h in m["hops"]:
            for key, field in (
                ("wire", "wire_s"), ("queue", "queue_s"),
                ("insert", "insert_s"), ("consensus", "consensus_s"),
            ):
                if h.get(field) is not None:
                    stages[key].append(h[field])
    return {
        name: {
            "n": len(vals),
            "p50_ms": None if _pct(vals, 0.50) is None
            else round(1e3 * _pct(vals, 0.50), 3),
            "p99_ms": None if _pct(vals, 0.99) is None
            else round(1e3 * _pct(vals, 0.99), 3),
        }
        for name, vals in stages.items()
    }


# -- rendering --------------------------------------------------------------


def render(merged: dict) -> str:
    """Human timeline for one merged transaction."""
    lines = [
        f"tx {merged['txid'][:16]}…  "
        + (
            f"committed block {merged['block']} "
            f"round {merged['round_received']} "
            f"on {merged['committed_on']} node(s)"
            if merged["committed_on"]
            else "NOT committed"
        )
        + ("" if merged["monotone"] else "  [non-monotone stamps]")
    ]
    base = merged["timeline"][0][0] if merged["timeline"] else 0.0
    for t, node, stage in merged["timeline"]:
        lines.append(f"  +{1e3 * (t - base):9.3f} ms  {stage:<11} node {node}")
    for h in merged["hops"]:
        parts = [
            f"{k}={1e3 * h[f]:.3f}ms"
            for k, f in (
                ("wire", "wire_s"), ("queue", "queue_s"),
                ("insert", "insert_s"), ("consensus", "consensus_s"),
            )
            if h.get(f) is not None
        ]
        if parts:
            lines.append(
                f"    hop{h['hop']} (node {h['node']}"
                + (f" ← {h['from']}" if h.get("from") is not None else "")
                + "): " + " ".join(parts)
            )
    if merged.get("e2e_s") is not None:
        lines.append(f"  end-to-end: {1e3 * merged['e2e_s']:.3f} ms")
    return "\n".join(lines)


# -- CLI --------------------------------------------------------------------


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m babble_tpu.obs.traceview",
        description="merge per-node /traces exports into cross-node "
        "transaction timelines",
    )
    p.add_argument("--nodes", default="",
                   help="comma-separated service host:port list to scrape")
    p.add_argument("--from-json", dest="from_json", default="",
                   help="read a saved list of /traces exports instead")
    p.add_argument("--txid", default="", help="merge one transaction only")
    p.add_argument("--limit", type=int, default=256,
                   help="records per node for bulk scrapes")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit merged JSON instead of the timeline view")
    args = p.parse_args(argv)

    exports: List[dict] = []
    if args.from_json:
        with open(args.from_json, encoding="utf-8") as f:
            exports = json.load(f)
    elif args.nodes:
        for ep in args.nodes.split(","):
            ep = ep.strip()
            if not ep:
                continue
            try:
                exp = fetch_node(
                    ep, txid=args.txid or None, limit=args.limit
                )
            except Exception as err:  # noqa: BLE001 — report + continue
                print(f"{ep}: scrape failed ({err})", file=sys.stderr)
                continue
            if exp is not None:
                exports.append(exp)
    else:
        p.error("one of --nodes or --from-json is required")

    if args.txid:
        merged = merge_tx(args.txid, exports)
        if merged is None:
            print(f"txid {args.txid} not found on any node", file=sys.stderr)
            return 1
        merged_list = [merged]
    else:
        merged_list = merge_all(exports)

    if args.as_json:
        print(json.dumps(
            {
                "traces": merged_list,
                "attribution": attribution_summary(merged_list),
            },
            indent=1,
        ))
        return 0
    for m in merged_list:
        print(render(m))
        print()
    summary = attribution_summary(merged_list)
    print(f"merged {len(merged_list)} transaction(s); per-hop attribution:")
    for stage, s in summary.items():
        if s["n"]:
            print(
                f"  {stage:<12} n={s['n']:<5} p50={s['p50_ms']}ms "
                f"p99={s['p99_ms']}ms"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
