"""Stall flight recorder: dump everything when progress stops.

FoundationDB-style "when the invariant trips, record the state you wish
you had": a :class:`StallWatchdog` rides every running node and fires
when the node is *busy* (pending transactions, undetermined events, a
target round ahead of consensus) yet its progress signature — last
block index, last consensus round, consensus-event count — has not
moved for ``Config.watchdog_stall_s`` seconds. On a trip it writes one
replay-friendly JSON artifact:

- the stalled-stage diagnosis (``gossip`` / ``consensus`` / ``ingest``
  / ``commit``) from the node's live signals,
- the full typed stats snapshot (ingest counters, mempool, sentry
  ledger, selector health/backoff view, breaker state, commit-latency
  percentiles),
- the recent sync-span ring (the last ~64 gossip rounds with per-stage
  timings),
- the provenance tail (the last transactions the tracer followed),
- gossip-leg latency percentiles and queue depths.

One dump per stall *episode*: after a trip the watchdog re-arms only
when the progress signature moves again, and a per-node dump budget
(``max_dumps``) bounds disk even on a node that stalls forever. The
monitor thread is started by ``Node.run`` (production path only — the
sim harness drives nodes without threads and calls ``check()``
directly if it wants the recorder) and disabled entirely under
``BABBLE_OBS=0`` or ``watchdog_stall_s=0``.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import List, Optional

from ..config.config import (
    DEFAULT_WATCHDOG_INTERVAL_S,
    DEFAULT_WATCHDOG_STALL_S,
)


def default_flight_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "babble_tpu_flight")


class StallWatchdog:
    def __init__(self, node, stall_s: float = DEFAULT_WATCHDOG_STALL_S,
                 interval_s: float = DEFAULT_WATCHDOG_INTERVAL_S,
                 out_dir: str = "", max_dumps: int = 5):
        self.node = node
        self.clock = node.clock
        self.stall_s = stall_s
        self.interval_s = max(0.05, interval_s)
        self.out_dir = out_dir or default_flight_dir()
        self.max_dumps = max_dumps
        self.trips = 0
        self.dumps = 0
        self.artifacts: List[str] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._last_sig = None
        self._last_progress_t: Optional[float] = None
        self._tripped = False

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.stall_s <= 0 or self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(
            target=self._loop, name="stall-watchdog", daemon=True
        )
        t.start()
        self._thread = t

    def stop(self) -> None:
        self._stop.set()
        self._thread = None

    def _loop(self) -> None:
        # Event.wait (real time) rather than clock.sleep: the thread is
        # only ever started on wall-clocked production nodes, and wait()
        # makes shutdown immediate.
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the recorder must never
                # take the node down; a diagnostic that crashes is worse
                # than no diagnostic
                self.node.logger.debug(
                    "stall watchdog check failed", exc_info=True
                )

    # -- detection -----------------------------------------------------------

    def _progress_signature(self) -> tuple:
        n = self.node
        return (
            n.get_last_block_index(),
            n.get_last_consensus_round_index(),
            n.core.get_consensus_events_count(),
        )

    def check(self) -> Optional[str]:
        """One watchdog pass; returns the artifact path on a fresh trip.
        Callable directly (tests, sim harness) — the thread above is
        just this on a timer."""
        if self.stall_s <= 0:
            return None
        now = self.clock.monotonic()
        sig = self._progress_signature()
        if sig != self._last_sig:
            self._last_sig = sig
            self._last_progress_t = now
            self._tripped = False  # progress resumed: re-arm
            return None
        from ..node.state import State

        if self.node.get_state() != State.BABBLING or not self.node.core.busy():
            # Suspended / joining / idle: the node owes no progress, so
            # this time must not count toward the stall window — else a
            # node that sat quiet past stall_s would trip the instant it
            # resumed, before it had a single interval to make progress.
            self._last_progress_t = now
            self._tripped = False
            return None
        if self._tripped:
            return None
        stalled_for = now - (self._last_progress_t or now)
        if stalled_for < self.stall_s:
            return None
        self.trips += 1
        self._tripped = True
        return self._dump(stalled_for, now)

    def _stalled_stage(self, now: float) -> str:
        """Which pipeline stage froze first (coarse, from live signals):
        no successful gossip round inside the stall window → ``gossip``;
        gossip flows but events sit undetermined → ``consensus``; rounds
        decided but no block → ``commit``; otherwise the local ingest/
        self-event path (``ingest``)."""
        n = self.node
        lg = n.last_gossip_ok
        if lg is None or now - lg >= self.stall_s:
            return "gossip"
        if n.core.get_undetermined_events():
            return "consensus"
        if n.core.hg.pending_rounds.get_ordered_pending_rounds():
            return "commit"
        return "ingest"

    # -- the dump ------------------------------------------------------------

    def _dump(self, stalled_for: float, now: float) -> Optional[str]:
        if self.dumps >= self.max_dumps:
            return None
        n = self.node
        stage = self._stalled_stage(now)
        artifact = {
            "format": "babble-flight/1",
            "node": n.get_id(),
            "moniker": n.core.validator.moniker,
            "state": str(n.get_state()),
            "stalled_stage": stage,
            "stalled_for_s": round(stalled_for, 3),
            "tripped_at": round(self.clock.time(), 6),
            "thresholds": {
                "stall_s": self.stall_s,
                "interval_s": self.interval_s,
            },
            "progress_signature": {
                "last_block_index": self._last_sig[0],
                "last_consensus_round": self._last_sig[1],
                "consensus_events": self._last_sig[2],
            },
            "last_gossip_ok_age_s": (
                None if n.last_gossip_ok is None
                else round(now - n.last_gossip_ok, 3)
            ),
            "stats": n.get_stats_snapshot(),
            "recent_syncs": n.telemetry.tracer.recent(),
            "provenance_tail": n.telemetry.provenance.export(limit=32),
            "timers": n.timers.snapshot(),
            "queues": {
                "submit_queue": n.submit_q.qsize(),
                "mempool_pending": n.core.mempool.pending_count,
                "undetermined_events": len(
                    n.core.get_undetermined_events()
                ),
                "heads_pending": len(n.core.heads),
                "sig_pool": len(n.core.self_block_signatures),
            },
        }
        n.logger.warning(
            "stall watchdog tripped: no progress for %.1fs "
            "(stalled stage: %s)", stalled_for, stage,
        )
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            path = os.path.join(
                self.out_dir,
                f"flight_{artifact['moniker'] or artifact['node']}"
                f"_{self.dumps}_{int(self.clock.time() * 1e3)}.json",
            )
            with open(path, "w", encoding="utf-8") as f:
                json.dump(artifact, f, default=str, indent=1)
        except OSError:
            n.logger.warning("flight-recorder dump failed", exc_info=True)
            return None
        self.dumps += 1
        self.artifacts.append(path)
        return path
