"""Bench-history ledger: the repo's memory of its own performance.

Every ``bench.py`` entry point appends ONE schema-versioned record to
``BENCH_HISTORY.jsonl`` (one JSON object per line, append-only — the
format Git merges cleanly and ``jq`` streams). A record carries enough
context to make a number comparable later:

- ``git_rev``   — the commit the numbers were measured at;
- ``host``      — a **fingerprint** of the measurement substrate
  (cpu count + model, python, jax versions): the regression gate
  (``obs/perfgate.py``) only ever compares runs with the SAME
  fingerprint, because "got slower" on a different host is not a
  regression;
- ``run``       — the bench kind (``bench`` / ``smoke`` / ``obs`` /
  ``gossip`` / …): kinds are compared only against themselves;
- ``config``    — the knobs that shaped the run (node counts, windows);
- ``results``   — a flat list of ``{name, value, unit}`` metrics, the
  dotted names produced by flattening the bench's compact summary.

The backfill tool normalizes the pre-ledger ``BENCH_r*.json`` driver
artifacts (schema-less ``{n, cmd, rc, tail, parsed}`` captures, tails
often truncated mid-JSON) into the same schema, best-effort: a full
``parsed`` payload flattens exactly like a live run; a truncated tail
degrades to a whitelist regex scan and the record says so
(``degraded: true``).

Usage::

    python -m babble_tpu.obs.ledger --backfill [BENCH_r01.json ...]
    python -m babble_tpu.obs.ledger --show [--history BENCH_HISTORY.jsonl]

Env: ``BABBLE_BENCH_LEDGER`` overrides the ledger path; ``0`` disables
appending entirely (tests and one-off runs that must not write history).
"""

from __future__ import annotations

import hashlib
import json
import os
import platform
import re
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

SCHEMA = "babble-bench/1"
HISTORY_BASENAME = "BENCH_HISTORY.jsonl"
# Flattening caps: a record must stay a readable line, not a dump.
MAX_RESULTS = 160
MAX_DEPTH = 4

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def default_history_path() -> str:
    """Ledger location: env override, else ``BENCH_HISTORY.jsonl`` next
    to ``bench.py`` at the repo root (NOT the cwd — a bench launched
    from anywhere appends to the same history)."""
    env = os.environ.get("BABBLE_BENCH_LEDGER", "")
    if env and env != "0":
        return env
    return os.path.join(_REPO_ROOT, HISTORY_BASENAME)


def ledger_enabled() -> bool:
    return os.environ.get("BABBLE_BENCH_LEDGER", "") != "0"


# -- host fingerprint --------------------------------------------------------


def _cpu_model() -> str:
    try:
        with open("/proc/cpuinfo", encoding="utf-8") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    return line.split(":", 1)[1].strip()
    except OSError:
        pass
    return platform.processor() or platform.machine() or "unknown"


def _jax_version() -> Optional[str]:
    # importlib.metadata, not `import jax`: a ledger append must not pay
    # (or fail on) a full jax import just to record a version string.
    try:
        from importlib.metadata import version

        return version("jax")
    except Exception:
        return None


def host_info() -> Dict[str, object]:
    """The measurement substrate + its stable fingerprint. The
    fingerprint hashes exactly the fields that make perf numbers
    comparable; hostname is informational only (containers from one
    image are the same substrate under different names)."""
    cpu_count = os.cpu_count() or 0
    cpu_model = _cpu_model()
    py = platform.python_version()
    jaxv = _jax_version()
    basis = f"{cpu_count}|{cpu_model}|{py}|{jaxv}|{platform.system()}"
    return {
        "fingerprint": hashlib.sha256(basis.encode()).hexdigest()[:12],
        "cpu_count": cpu_count,
        "cpu_model": cpu_model,
        "python": py,
        "jax": jaxv,
        "platform": platform.system(),
    }


def git_rev() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10.0, cwd=_REPO_ROOT,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else None
    except Exception:
        return None


# -- metric flattening -------------------------------------------------------


def infer_unit(name: str) -> str:
    """Unit from the metric's (dotted) name, by the repo's own naming
    conventions — the summaries already encode units in suffixes."""
    leaf = name.rsplit(".", 1)[-1]
    if leaf.endswith("_ms") or leaf in ("p50", "p90", "p95", "p99"):
        return "ms"
    # NOT *_rate: shed_rate/burn_rate are fractions, not per-second
    # rates — mislabeling them "/s" would hand the gate a wrong
    # better-direction. Checked before "_s": txs_per_s is a rate.
    if "per_s" in leaf:
        return "/s"
    if leaf.endswith("_s"):
        return "s"
    if (
        leaf.endswith("ratio")
        or leaf.endswith("speedup")
        or leaf in ("vs_baseline", "obs_overhead")
        or leaf.startswith("speedup")
    ):
        return "x"
    return "count"


def flatten_results(fields: Dict[str, object]) -> List[Dict[str, object]]:
    """Numeric leaves of a (possibly nested) summary dict as
    ``{name, value, unit}`` rows, dotted path names, bounded."""
    rows: List[Dict[str, object]] = []

    def walk(prefix: str, obj, depth: int) -> None:
        if len(rows) >= MAX_RESULTS:
            return
        if isinstance(obj, bool):
            return  # flags are context, not metrics
        if isinstance(obj, (int, float)):
            rows.append(
                {"name": prefix, "value": obj, "unit": infer_unit(prefix)}
            )
            return
        if isinstance(obj, dict) and depth < MAX_DEPTH:
            for k, v in obj.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v, depth + 1)

    walk("", fields, 0)
    return rows


# -- records -----------------------------------------------------------------


def make_record(run: str, fields: Dict[str, object],
                config: Optional[Dict[str, object]] = None,
                source: str = "live",
                ts: Optional[float] = None,
                degraded: bool = False) -> Dict[str, object]:
    rec: Dict[str, object] = {
        "schema": SCHEMA,
        "ts": round(time.time() if ts is None else ts, 3),
        "run": run,
        "git_rev": git_rev(),
        "host": host_info(),
        "config": config or {},
        "results": flatten_results(fields),
        "source": source,
    }
    if degraded:
        rec["degraded"] = True
    return rec


def append(record: Dict[str, object],
           path: Optional[str] = None) -> Optional[str]:
    """Append one record; returns the path written, or None when the
    ledger is disabled (``BABBLE_BENCH_LEDGER=0``)."""
    if not ledger_enabled():
        return None
    path = path or default_history_path()
    line = json.dumps(record, separators=(",", ":"), default=str)
    with open(path, "a", encoding="utf-8") as f:
        f.write(line + "\n")
    return path


def read(path: Optional[str] = None) -> List[Dict[str, object]]:
    """Every parseable record, oldest first. Malformed lines are skipped
    (an append interrupted mid-line must not poison the whole history)."""
    path = path or default_history_path()
    out: List[Dict[str, object]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("schema") == SCHEMA:
                    out.append(rec)
    except OSError:
        return []
    return out


def results_map(record: Dict[str, object]) -> Dict[str, Tuple[float, str]]:
    out: Dict[str, Tuple[float, str]] = {}
    for row in record.get("results", ()):
        try:
            out[str(row["name"])] = (float(row["value"]), str(row.get("unit", "")))
        except (KeyError, TypeError, ValueError):
            continue
    return out


# -- backfill of the pre-ledger BENCH_r*.json artifacts ----------------------

# Whitelist for truncated tails: metric names whose FIRST occurrence in
# the (mid-JSON) text is the top-level bench meaning of that name.
_TAIL_WHITELIST = (
    "committed_txs_per_s_4node",
    "vs_baseline",
    "latency_p50_ms",
    "latency_p95_ms",
    "dag_pipeline_events_per_s",
    "dag_pipeline_ms_per_sweep",
    "native_sigs_per_s",
    "device_sigs_per_s",
    "device_vs_native",
)


def _last_json_line(text: str) -> Optional[dict]:
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def _scan_tail(tail: str) -> Dict[str, float]:
    found: Dict[str, float] = {}
    for name in _TAIL_WHITELIST:
        m = re.search(
            r'"' + re.escape(name) + r'"\s*:\s*(-?\d+(?:\.\d+)?)', tail
        )
        if m:
            found[name] = float(m.group(1))
    return found


def backfill_record(path: str) -> Dict[str, object]:
    """One pre-ledger driver artifact → one ledger record. The host
    block records the CURRENT container (the artifacts come from the
    same CI image lineage and carry no host data of their own); the
    ``source`` field names the artifact so provenance stays explicit."""
    with open(path, encoding="utf-8") as f:
        art = json.load(f)
    base = os.path.basename(path)
    ts = os.path.getmtime(path)
    parsed = art.get("parsed")
    tail = art.get("tail") or ""
    degraded = False
    if isinstance(parsed, dict) and "metric" in parsed:
        fields: Dict[str, object] = {
            str(parsed["metric"]): parsed.get("value"),
            "vs_baseline": parsed.get("vs_baseline"),
        }
        extra = parsed.get("extra")
        if isinstance(extra, dict):
            fields.update(extra)
    else:
        obj = _last_json_line(tail)
        if obj is not None and ("metric" in obj or "bench_summary" in obj):
            fields = dict(obj)
            if "metric" in fields:
                fields[str(fields.pop("metric"))] = fields.pop("value", None)
        else:
            fields = dict(_scan_tail(tail))
            degraded = True  # truncated capture: regex whitelist only
    rec = make_record(
        run="bench", fields=fields,
        config={"cmd": art.get("cmd"), "rc": art.get("rc")},
        source=f"backfill:{base}", ts=ts, degraded=degraded,
    )
    rec["round"] = art.get("n")
    return rec


def backfill(paths: List[str],
             history: Optional[str] = None) -> List[Dict[str, object]]:
    """Normalize artifacts into the ledger, oldest round first,
    skipping artifacts already backfilled (idempotent re-runs)."""
    history = history or default_history_path()
    existing = {
        r.get("source") for r in read(history)
        if str(r.get("source", "")).startswith("backfill:")
    }
    recs = []
    for p in paths:
        rec = backfill_record(p)
        if rec["source"] in existing:
            continue
        recs.append(rec)
    recs.sort(key=lambda r: (r.get("round") or 0, r["ts"]))
    for rec in recs:
        append(rec, history)
    return recs


# -- CLI ---------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m babble_tpu.obs.ledger",
        description="bench-history ledger: backfill and inspection",
    )
    p.add_argument("--history", default="", help="ledger path "
                   f"(default: {HISTORY_BASENAME} at the repo root)")
    p.add_argument("--backfill", nargs="*", metavar="ARTIFACT",
                   help="normalize pre-ledger BENCH_r*.json artifacts "
                   "into the ledger (no args: every BENCH_r*.json at "
                   "the repo root)")
    p.add_argument("--show", action="store_true",
                   help="print one summary line per record (the default "
                   "action when --backfill is not given)")
    args = p.parse_args(sys.argv[1:] if argv is None else argv)
    history = args.history or default_history_path()

    if args.backfill is not None:
        paths = args.backfill
        if not paths:
            import glob

            paths = sorted(glob.glob(os.path.join(_REPO_ROOT, "BENCH_r*.json")))
        if not paths:
            print("backfill: no artifacts found", file=sys.stderr)
            return 1
        recs = backfill(paths, history)
        print(
            f"backfilled {len(recs)} record(s) into {history} "
            f"({len(read(history))} total)"
        )
        return 0

    records = read(history)
    if not records:
        print(f"no records in {history}", file=sys.stderr)
        return 1
    for i, r in enumerate(records):
        n_res = len(r.get("results", ()))
        head = next(
            (
                f"{row['name']}={row['value']}{row['unit']}"
                for row in r.get("results", ())
                if row.get("name") == "committed_txs_per_s_4node"
            ),
            f"{n_res} metrics",
        )
        print(
            f"[{i}] {time.strftime('%Y-%m-%d %H:%M', time.localtime(r['ts']))} "
            f"run={r.get('run')} rev={r.get('git_rev')} "
            f"host={r.get('host', {}).get('fingerprint')} "
            f"src={r.get('source')} {head}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
