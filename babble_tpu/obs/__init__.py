"""Unified telemetry layer: metrics registry, span tracer, structured
logging, and the Prometheus exposition the service serves at /metrics.

The package is organized as:

- ``obs.metrics``  — Counter/Gauge/Histogram instruments + Registry +
  Prometheus text rendering. Hot-path increments are lock-free (GIL
  atomicity; a lost increment under a race is acceptable for stats,
  corruption is not possible). ``BABBLE_OBS=0`` is the kill switch: hot
  instruments become no-ops, zero-cost function-backed instruments keep
  working so ``get_stats`` and ``/metrics`` stay truthful.
- ``obs.trace``    — lightweight span tracer following one sync (and one
  transaction) through the pipeline; finished spans feed the
  ``sync_stage_seconds{stage=...}`` histograms and a bounded ring of
  recent traces served at ``/telemetry``.
- ``obs.telemetry``— NodeTelemetry: the per-node registry wiring every
  subsystem's counters into instruments, the legacy ``get_stats``
  compatibility snapshot, and the /metrics / /telemetry renderers.
- ``obs.catalog``  — the instrument catalog (name, type, labels,
  meaning): the single source of truth that registration, the docs
  table (docs/observability.md), and ``obs.lint`` all check against.
- ``obs.log``      — one logging entry point (level / JSON toggle /
  node-id correlation) replacing per-module ad-hoc setup.
- ``obs.lint``     — ``python -m babble_tpu.obs.lint``: fails when a
  cataloged instrument is missing from the docs table or vice versa.
- ``obs.ledger``   — the bench-history ledger (BENCH_HISTORY.jsonl):
  schema-versioned perf records appended by every bench run, plus the
  backfill of the pre-ledger BENCH_r* artifacts.
- ``obs.perfgate`` — ``python -m babble_tpu.obs.perfgate``: regression
  gate over the ledger (rolling same-host baseline, noise-aware bands,
  ``--inject-regression`` self-proof).
- ``obs.profile``  — always-on ~50 Hz thread-stack sampler: stage-
  attributed collapsed stacks at ``GET /profile`` and the
  ``profile_stage_samples{stage}`` instrument.
- ``obs.healthview`` — ``python -m babble_tpu.obs.healthview``: merge
  every node's /metrics + /stats + /suspects into per-node lag,
  queue-depth, quarantine, and commit-p50-SLO scoring.
"""

from .metrics import Registry, enabled, set_enabled  # noqa: F401
