"""Perf regression gate over the bench-history ledger.

``python -m babble_tpu.obs.perfgate`` compares the most recent ledger
record (the run the CI job just appended) against a **rolling baseline**
of earlier records with the same host fingerprint and the same run kind
— cross-host or cross-kind comparisons are never made, because "slower
on different hardware" is not a regression.

Noise handling (the single shared-core CI host swings individual runs
hard, see docs/observability.md §overhead):

- the baseline is the **median** of the last ``--window`` matching
  records (median-of-N, not last-run-vs-this-run);
- each metric's tolerance band is ``max(--tolerance, 3 * MAD/median)``
  — a metric whose own history is noisy earns a wider band;
- only metrics with an inferable direction are gated (``*_per_s`` and
  ``*speedup``/``*ratio`` are higher-better, ``*_ms``/``*_s`` are
  lower-better; counts are informational);
- the gate **hard-fails only on corroborated regressions**: at least
  two gated metrics out of band, or one metric beyond twice its band
  (``--strict`` fails on any single band violation).

Self-proof: ``--inject-regression`` clones the latest record, degrades
every gated metric by ``--inject-factor`` (default 35%) in its bad
direction, and runs the gate on the synthetic record — CI asserts the
nonzero exit, so a silently-toothless gate cannot ship (the same
prove-the-detector pattern as ``sim.sweep --inject-failure``).

Exit codes: 0 pass / no baseline yet; 1 corroborated regression;
2 usage or empty ledger.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Tuple

from . import ledger

DEFAULT_WINDOW = 5
DEFAULT_TOLERANCE = 0.15
DEFAULT_INJECT_FACTOR = 0.35
NOISE_MULT = 3.0  # tolerance widens to 3x the metric's own MAD ratio
# Metrics whose |median| sits below these floors gate as absolute
# deltas instead of ratios (a 0.2ms p50 doubling to 0.4ms is noise).
ABS_FLOOR = {"ms": 5.0, "s": 0.005, "/s": 1.0, "x": 0.05, "count": 1.0}


def direction(name: str, unit: str) -> Optional[str]:
    """'higher' / 'lower' when better is inferable, else None
    (ungated)."""
    leaf = name.rsplit(".", 1)[-1]
    if unit == "/s" or "per_s" in leaf:
        return "higher"
    if leaf.endswith(("speedup", "ratio")) or leaf == "vs_baseline":
        return "higher"
    if unit in ("ms", "s") or leaf.endswith(("_ms", "_s")):
        return "lower"
    return None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _mad(vals: List[float], med: float) -> float:
    return _median([abs(v - med) for v in vals]) if vals else 0.0


def baseline_for(records: List[dict], current: dict,
                 window: int) -> List[dict]:
    """The rolling same-substrate baseline: earlier records with the
    current record's host fingerprint and run kind, newest ``window``."""
    fp = current.get("host", {}).get("fingerprint")
    kind = current.get("run")
    matches = [
        r for r in records
        if r is not current
        and r.get("host", {}).get("fingerprint") == fp
        and r.get("run") == kind
    ]
    return matches[-window:]


def gate(current: dict, baseline: List[dict],
         tolerance: float = DEFAULT_TOLERANCE,
         strict: bool = False) -> dict:
    """Compare one record against its baseline window. Returns the
    verdict dict (``ok``, ``regressions``, ``improvements``,
    ``checked``); ``ok`` is False only on a corroborated regression."""
    cur = ledger.results_map(current)
    history: Dict[str, List[float]] = {}
    for rec in baseline:
        for name, (value, _unit) in ledger.results_map(rec).items():
            history.setdefault(name, []).append(value)

    regressions, improvements, checked = [], [], 0
    for name, (value, unit) in sorted(cur.items()):
        vals = history.get(name)
        if not vals:
            continue
        direc = direction(name, unit)
        if direc is None:
            continue
        med = _median(vals)
        floor = ABS_FLOOR.get(unit, 0.0)
        if abs(med) < floor and abs(value) < floor:
            continue  # both sides under the absolute noise floor
        rel_noise = _mad(vals, med) / abs(med) if med else 0.0
        band = max(tolerance, NOISE_MULT * rel_noise)
        delta = (value - med) / abs(med) if med else 0.0
        worse = -delta if direc == "higher" else delta
        checked += 1
        row = {
            "metric": name,
            "unit": unit,
            "current": value,
            "baseline_median": round(med, 4),
            "baseline_n": len(vals),
            "delta_pct": round(100.0 * delta, 1),
            "band_pct": round(100.0 * band, 1),
            "direction": direc,
        }
        if worse > band:
            row["severity"] = "hard" if worse > 2 * band else "soft"
            regressions.append(row)
        elif -worse > band:
            improvements.append(row)

    corroborated = (
        len(regressions) >= 2
        or any(r["severity"] == "hard" for r in regressions)
        or (strict and bool(regressions))
    )
    return {
        "ok": not corroborated,
        "checked": checked,
        "baseline_runs": len(baseline),
        "regressions": regressions,
        "improvements": improvements,
        "tolerance": tolerance,
        "strict": strict,
    }


def inject_regression(current: dict, factor: float,
                      baseline: Optional[List[dict]] = None,
                      tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """A synthetic regressed clone of ``current``: every gated metric
    degraded by ``factor`` in its bad direction (the gate self-proof).

    The degradation anchors on the metric's BASELINE MEDIAN when a
    baseline is given, not on the current value: after a genuine
    bigger-than-``factor`` improvement, degrading the current run alone
    still beats the old median and the proof would falsely report a
    toothless gate. And it degrades by at least 2.2x the metric's OWN
    noise band (the gate's hard-regression threshold is 2x): right
    after a perf jump the window is bimodal and the MAD-widened band
    legitimately exceeds any fixed factor — the proof's claim is "the
    gate fires on a beyond-band regression", so the injection must be
    beyond the band the gate will actually apply. Metrics with no
    history fall back to the current value (they are ungated anyway)."""
    history: Dict[str, List[float]] = {}
    for rec in baseline or ():
        for name, (value, _unit) in ledger.results_map(rec).items():
            history.setdefault(name, []).append(value)
    bad = json.loads(json.dumps(current))
    bad["source"] = f"inject-regression:{factor}"
    for row in bad.get("results", ()):
        try:
            name, unit, value = row["name"], row.get("unit", ""), float(row["value"])
        except (KeyError, TypeError, ValueError):
            continue
        direc = direction(str(name), str(unit))
        vals = history.get(str(name))
        if vals:
            anchor = _median(vals)
            noise = _mad(vals, anchor) / abs(anchor) if anchor else 0.0
            # the SAME band formula gate() will apply — including the
            # caller's --tolerance, or a widened band makes the proof
            # falsely report a toothless gate
            band = max(tolerance, NOISE_MULT * noise)
            degrade = max(factor, 2.2 * band)
        else:
            anchor, degrade = value, factor
        if direc == "higher":
            row["value"] = round(anchor * (1.0 - degrade), 6)
        elif direc == "lower":
            row["value"] = round(anchor * (1.0 + degrade), 6)
    return bad


def _render(verdict: dict, current: dict) -> str:
    lines = []
    fp = current.get("host", {}).get("fingerprint")
    lines.append(
        f"perfgate: run={current.get('run')} rev={current.get('git_rev')} "
        f"host={fp} vs {verdict['baseline_runs']} baseline run(s), "
        f"{verdict['checked']} gated metric(s)"
    )
    for row in verdict["regressions"]:
        lines.append(
            f"  REGRESSION [{row['severity']}] {row['metric']}: "
            f"{row['current']}{row['unit']} vs median "
            f"{row['baseline_median']}{row['unit']} "
            f"({row['delta_pct']:+.1f}%, band ±{row['band_pct']:.1f}%, "
            f"n={row['baseline_n']})"
        )
    for row in verdict["improvements"]:
        lines.append(
            f"  improvement {row['metric']}: {row['current']}{row['unit']} "
            f"vs median {row['baseline_median']}{row['unit']} "
            f"({row['delta_pct']:+.1f}%)"
        )
    if verdict["baseline_runs"] == 0:
        lines.append(
            "  no same-host same-kind baseline yet — pass (the ledger "
            "grows one run per bench; the gate arms itself)"
        )
    lines.append(
        "perfgate: "
        + ("OK" if verdict["ok"] else "FAIL (corroborated regression)")
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m babble_tpu.obs.perfgate",
        description="compare the latest bench run against its rolling "
        "same-host baseline; nonzero exit on corroborated regression",
    )
    p.add_argument("--history", default="",
                   help="ledger path (default: repo BENCH_HISTORY.jsonl)")
    p.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                   help="baseline depth (median of the last N matching "
                   "runs)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="minimum per-metric tolerance band (fraction)")
    p.add_argument("--strict", action="store_true",
                   help="fail on ANY band violation (default: require "
                   "corroboration)")
    p.add_argument("--inject-regression", action="store_true",
                   help="self-proof: gate a synthetically regressed "
                   "clone of the latest run — MUST exit nonzero")
    p.add_argument("--inject-factor", type=float,
                   default=DEFAULT_INJECT_FACTOR)
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the verdict as one JSON line")
    p.add_argument("--max-age-s", type=float, default=3600.0,
                   help="reject a stale latest record (guards against a "
                   "silently failed ledger append re-gating old history "
                   "as a pass; 0 disables)")
    args = p.parse_args(sys.argv[1:] if argv is None else argv)

    history = args.history or ledger.default_history_path()
    records = ledger.read(history)
    if not records:
        print(f"perfgate: no records in {history} — run a bench first",
              file=sys.stderr)
        return 2
    current = records[-1]
    # Freshness guard: bench._ledger_append swallows failures by design
    # (history must not kill a bench), so the gate — whose whole job is
    # teeth — must not quietly re-gate an OLD record as today's pass.
    import time as _time

    age = _time.time() - float(current.get("ts") or 0)
    if args.max_age_s > 0 and age > args.max_age_s:
        print(
            f"perfgate: latest record is {age / 3600:.1f}h old "
            f"(> {args.max_age_s / 3600:.1f}h) — the bench's ledger "
            "append likely failed; refusing to gate stale history "
            "(--max-age-s 0 to override)",
            file=sys.stderr,
        )
        return 2

    if args.inject_regression:
        # Baseline for the synthetic record includes the REAL latest run
        # (that is the history the regression would land on); a window
        # of one genuine run is enough for the proof. Built BEFORE the
        # injection so the clone can degrade from the baseline medians.
        probe = {"host": current.get("host", {}), "run": current.get("run")}
        baseline = baseline_for(records, probe, args.window)
        # the real latest run always corroborates its own clone's gate
        baseline = baseline or [current]
        bad = inject_regression(current, args.inject_factor, baseline,
                                tolerance=args.tolerance)
        verdict = gate(bad, baseline, args.tolerance, args.strict)
        current = bad
        _emit(verdict, current, args.as_json)
        # The injected run gates EXACTLY like a real one: regression →
        # exit 1. A toothless gate exits 0 here, and the make target's
        # inversion check (`if perfgate --inject-regression; then fail`)
        # turns that 0 into the build failure — the self-proof.
        if verdict["ok"]:
            print(
                "perfgate: INJECTED regression was NOT detected — the "
                "gate is toothless", file=sys.stderr,
            )
            return 0
        print(
            f"perfgate: injected regression correctly detected "
            f"({len(verdict['regressions'])} metric(s)) — exiting nonzero",
            file=sys.stderr,
        )
        return 1

    baseline = baseline_for(records, current, args.window)
    verdict = gate(current, baseline, args.tolerance, args.strict)
    _emit(verdict, current, args.as_json)
    return 0 if verdict["ok"] else 1


def _emit(verdict: dict, current: dict, as_json: bool) -> None:
    if as_json:
        print(json.dumps(
            {
                "perfgate": verdict,
                "run": current.get("run"),
                "git_rev": current.get("git_rev"),
                "source": current.get("source"),
            },
            separators=(",", ":"),
        ))
    else:
        print(_render(verdict, current))


if __name__ == "__main__":
    sys.exit(main())
