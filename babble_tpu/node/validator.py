"""Validator: wrapper around the node's signing key
(reference: src/node/validator.go:11-50)."""

from __future__ import annotations

from ..crypto.keys import PrivateKey


class Validator:
    def __init__(self, key: PrivateKey, moniker: str = ""):
        self.key = key
        self.moniker = moniker
        # Deriving the public key is a scalar multiplication — do it once.
        self._pub = key.public_key
        self._id = self._pub.id()

    def id(self) -> int:
        """FNV-1a 32-bit id of the public key
        (reference: validator.go:30-33, keys/public_key.go:36)."""
        return self._id

    def public_key_bytes(self) -> bytes:
        return self._pub.bytes()

    def public_key_hex(self) -> str:
        return self._pub.hex()
