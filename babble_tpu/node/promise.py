"""Join promise: async response plumbing for membership requests
(reference: src/node/promise.go:9-35)."""

from __future__ import annotations

import queue
from dataclasses import dataclass, field
from typing import List

from ..hashgraph.internal_transaction import InternalTransaction
from ..peers.peer import Peer


@dataclass
class JoinPromiseResponse:
    accepted: bool
    accepted_round: int
    peers: List[Peer] = field(default_factory=list)


class JoinPromise:
    def __init__(self, tx: InternalTransaction):
        self.tx = tx
        self._resp: "queue.Queue[JoinPromiseResponse]" = queue.Queue(1)

    def respond(self, accepted: bool, accepted_round: int, peers: List[Peer]) -> None:
        self._resp.put(JoinPromiseResponse(accepted, accepted_round, peers))

    def wait(self, timeout: float) -> JoinPromiseResponse:
        """Block until consensus decides the transaction; raises queue.Empty
        on timeout."""
        return self._resp.get(timeout=timeout)
