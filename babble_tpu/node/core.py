"""Core: the node's façade over the hashgraph.

Reference semantics: src/node/core.go — head/seq tracking (:143-177),
sync + heads-merge (:210-289), addSelfEvent (:292-333), commit callback
(:486-537), accepted-internal-transaction processing with the +6
effective-round rule (:562-650), eventDiff (:660-703), pools (:740-758).
"""

from __future__ import annotations

import logging
import queue
from typing import Callable, Dict, List, Optional

from ..hashgraph.block import Block
from ..hashgraph.errors import ForkError, is_normal_self_parent_error
from ..hashgraph.event import Event, WireEvent, sort_topological
from ..hashgraph.frame import Frame
from ..hashgraph.hashgraph import Hashgraph
from ..hashgraph.internal_transaction import (
    InternalTransaction,
    InternalTransactionReceipt,
    TransactionType,
)
from ..hashgraph.store import Store
from ..mempool import Mempool
from ..peers.peer_set import PeerSet
from .peer_selector import RandomPeerSelector
from .promise import JoinPromise
from .sentry import Sentry
from .validator import Validator

logger = logging.getLogger(__name__)

# All consistent hashgraphs will have decided the fame of round r witnesses
# by round r+5, so a new peer-set becomes effective at round r+6 (whitepaper
# lemmas 5.15 and 5.17; reference: core.go:566-569).
PEER_SET_EFFECTIVE_DELAY = 6


class PreparedSync:
    """Lock-free ingest work for one incoming sync: the longest decodable
    prefix of the wire events, hashed and batch-signature-verified OUTSIDE
    the core lock. ``Core.sync`` consumes it under the lock, which then
    only pays for the ordered insert + DivideRounds sweep.

    Contract: must be built (``Core.prepare_sync``) from the SAME wire
    event list later passed to ``Core.sync`` — ``decoded[i]`` corresponds
    to ``wire_events[i]``."""

    __slots__ = ("wire_events", "decoded")

    def __init__(self, wire_events: List[WireEvent]):
        self.wire_events = wire_events
        self.decoded: List[Event] = []


class Core:
    """reference: core.go:19-100."""

    def __init__(
        self,
        validator: Validator,
        peers: PeerSet,
        genesis_peers: PeerSet,
        store: Store,
        proxy_commit_callback: Callable[[Block], object],
        maintenance_mode: bool = False,
        accelerated_verify: bool = False,
        accelerator_mesh: int = 0,
        mempool: Optional[Mempool] = None,
        sentry: Optional[Sentry] = None,
        clock=None,
        selector_rng=None,
        selfevent_burst: int = 0,
    ):
        # Time source (common/clock.py): event timestamps, leave-loop
        # deadlines, selector backoff, and every telemetry duration below
        # read through this handle. Default: the process wall clock; the
        # sim engine injects virtual time (docs/simulation.md).
        from ..common.clock import WALL

        self.clock = clock if clock is not None else WALL
        # Gate the TPU batch-verify path behind a flag (the reference's
        # north-star `--accelerator` switch); jax is only imported when on.
        # Without the accelerator, incoming sync chunks still batch through
        # the native C++ verifier when it is available.
        self.accelerated_verify = accelerated_verify
        from babble_tpu.crypto import batch as _host_batch

        self._host_batch_verify = _host_batch.available()
        self.validator = validator
        self.genesis_peers = genesis_peers
        self.validators = genesis_peers
        self.peers = peers
        # Misbehavior ledger (node/sentry.py): classified ingest
        # rejections score peers toward time-boxed quarantine; the
        # selector skips quarantined ids via the hook below.
        self.sentry = sentry if sentry is not None else Sentry()
        self.sentry.set_peer_count(len(peers.peers))
        self.peer_selector = RandomPeerSelector(
            peers,
            validator.id(),
            clock=self.clock.monotonic,
            rng=selector_rng,
            quarantine_check=self.sentry.is_quarantined,
        )
        self.proxy_commit_callback = proxy_commit_callback
        self.maintenance_mode = maintenance_mode

        self.head: str = ""
        self.seq: int = -1

        self.accepted_round: int = -1
        self.removed_round: int = -1
        self.target_round: int = -1
        self.last_peer_change_round: int = -1

        # Other-peers' head events awaiting inclusion as self-events
        # (reference: core.go:66-73).
        self.heads: Dict[int, Optional[Event]] = {}

        # Client transactions live in the mempool (bounded, deduplicating,
        # own lock — docs/mempool.md); the internal-transaction pool keeps
        # its own small list path (membership itxs are rare and trusted).
        self.mempool = mempool if mempool is not None else Mempool()
        self.internal_transaction_pool: List[InternalTransaction] = []
        self.self_block_signatures = {}  # key -> BlockSignature
        self.promises: Dict[str, JoinPromise] = {}

        # Batched-ingest fast-path counters (surfaced via Node.get_stats
        # and bench.py): on the happy path every incoming sync costs
        # exactly ONE native batch-verify call, and fallback_singles
        # counts the per-event scalar re-checks that pinpoint offenders
        # after a batch reported failures.
        self.ingest_syncs = 0
        self.ingest_batch_verifies = 0
        self.ingest_batch_size_max = 0
        self.ingest_fallback_singles = 0

        # Coalesced self-event minting (docs/gossip.md §Adaptive
        # scheduling): when the mempool still holds a full event's worth
        # of transactions after the regular per-sync/monologue
        # self-event, mint up to ``selfevent_burst`` extra events in the
        # SAME lock hold — a hot mempool drains at burst x event_max_txs
        # per tick instead of one event cap per gossip round. 0 keeps
        # the reference's one-event-per-tick shape.
        self.selfevent_burst = max(0, int(selfevent_burst))
        self.selfevent_coalesced = 0

        # Commit listeners (docs/clients.md): called AFTER a block is
        # fully committed (state hash + receipts filled, own signature
        # attached) — the hook feeding the tx→block proof index and the
        # subscription hub. Listeners must be cheap/non-blocking; a
        # listener crash is contained so consensus can never stall on
        # the read tier.
        self.commit_listeners: List[Callable[[Block], None]] = []

        self.hg = Hashgraph(store, self.commit)
        self.hg.init(genesis_peers)
        # Fork evidence is scored against the *creator*, not the relaying
        # peer — resolve its id through the live repertoire.
        self.sentry.set_creator_resolver(
            lambda pub_hex: (
                p.id
                if (p := self.hg.store.repertoire_by_pub_key().get(pub_hex))
                is not None
                else None
            )
        )

        if accelerated_verify:
            # The same flag gates the consensus offload: fame and
            # round-received come off the device in batched sweeps
            # (reference hot loop: hashgraph.go:644-668). The mesh (for
            # witness-axis-sharded multi-chip sweeps) is attached later by
            # Node.init — AFTER the device probe, since building it
            # initializes the jax backend, which must never happen before
            # ensure_device() has ruled out a wedged link.
            from ..hashgraph.accel import TensorConsensus

            self.accelerator_mesh = accelerator_mesh
            # The owner identity keys the coprocessor's per-validator
            # accounting when several co-located validators multiplex
            # their sweep windows onto one shared mesh.
            self.hg.accel = TensorConsensus(
                clock=self.clock,
                owner=validator.moniker or validator.public_key_hex(),
            )

        # Telemetry (docs/observability.md): the per-node registry wiring
        # every subsystem's counters into instruments, created at the
        # core so standalone cores (benches, tests) measure identically
        # to full nodes. _stage_obs is None under BABBLE_OBS=0 — the
        # timing sites below null-check it and skip even the clock reads.
        from ..obs.telemetry import NodeTelemetry

        self.obs = NodeTelemetry(self)
        self._stage_obs = self.obs.stage_observer
        self.hg.stage_observer = self._stage_obs
        # The @staged decorator times hashgraph stages against this
        # clock, so simulated runs record virtual durations.
        self.hg.stage_clock = self.clock.perf_counter

    # -- head/seq -----------------------------------------------------------

    def set_head_and_seq(self) -> None:
        """reference: core.go:143-177."""
        head = ""
        seq = -1
        if self.validator.id() in self.hg.store.repertoire_by_id():
            try:
                last = self.hg.store.last_event_from(self.validator.public_key_hex())
            except Exception:
                last = ""
            if last:
                head = last
                seq = self.hg.store.get_event(last).index()
        self.head = head
        self.seq = seq

    def bootstrap(self) -> None:
        self.hg.bootstrap()

    def set_peers(self, ps: PeerSet) -> None:
        """reference: core.go:185-188. ``prior`` carries the surviving
        peers' health scores and backoff state across the rebuild, so a
        membership change doesn't amnesty every failing peer."""
        self.peers = ps
        self.sentry.set_peer_count(len(ps.peers))
        self.peer_selector = RandomPeerSelector(
            ps, self.validator.id(), prior=self.peer_selector
        )

    # -- busy ---------------------------------------------------------------

    def busy(self) -> bool:
        """Unfinished work gates the fast heartbeat
        (reference: core.go:196-202)."""
        return (
            self.hg.pending_loaded_events > 0
            or self.mempool.pending_count > 0
            or len(self.internal_transaction_pool) > 0
            or len(self.self_block_signatures) > 0
            or (self.hg.accel is not None and self.hg.accel.busy())
            or (
                self.hg.last_consensus_round is not None
                and self.hg.last_consensus_round < self.target_round
            )
        )

    # -- sync ---------------------------------------------------------------

    def prepare_sync(self, unknown_events: List[WireEvent]) -> PreparedSync:
        """Lock-free ingest stage: decode + hash the longest possible
        prefix of an incoming sync and verify all its signatures in ONE
        native batch call. Callers (node gossip/eager-sync handlers) run
        this BEFORE taking the core lock, so the lock only serializes the
        ordered insert + DivideRounds sweep.

        Thread-safety: the store is append-only for events (an index,
        once assigned, never re-resolves to a different hash), so the
        parent resolution in read_wire_info is snapshot-safe against
        concurrent inserts; the overlay covers parents that ride in the
        same sync. A decode stall (parent/creator only resolvable after
        inserting earlier events, e.g. a mid-batch membership change)
        cuts the prefix — Core.sync re-decodes the tail under the lock
        with the same chunked semantics as the reference's sequential
        decode+insert (core.go:210-289)."""
        prepared = PreparedSync(unknown_events)
        if not (self.accelerated_verify or self._host_batch_verify):
            # Sequential scalar path: decode and verify under the lock,
            # exactly the reference shape.
            return prepared
        decoded, _ = self._decode_chunk(unknown_events, 0)
        if decoded:
            self._batch_prevalidate(decoded)
        prepared.decoded = decoded
        return prepared

    def _decode_chunk(
        self, unknown_events: List[WireEvent], start: int
    ) -> tuple[List[Event], int]:
        """Decode the longest decodable run of ``unknown_events[start:]``,
        resolving same-sync parents through an overlay of the events
        decoded so far. Returns (decoded, next_pos); a decode stall cuts
        the run at next_pos. Shared by the lock-free prepare stage and
        sync's under-lock tail so their semantics can never diverge."""
        obs = self._stage_obs
        t0 = self.clock.perf_counter() if obs is not None else 0.0
        overlay: Dict[tuple, str] = {}
        decoded: List[Event] = []
        j = start
        n = len(unknown_events)
        while j < n:
            try:
                ev = self.hg.read_wire_info(unknown_events[j], overlay)
            except Exception:
                break
            # first decode at a (creator, index) slot wins — mirroring
            # insert semantics, where the first event to occupy a slot is
            # the one that lands and a conflicting twin is refused; a
            # hostile batch carrying both fork branches must not have the
            # SECOND branch hijack later parent resolution.
            overlay.setdefault((ev.creator(), ev.index()), ev.hex())
            decoded.append(ev)
            j += 1
        if obs is not None:
            obs("decode", self.clock.perf_counter() - t0)
        return decoded, j

    def _batch_prevalidate(self, decoded: List[Event]) -> None:
        """Verify a decoded chunk's signatures in one batch call, then
        pinpoint offenders: events the batch flagged are re-checked
        through the scalar verifier one by one, so a batch-layer artifact
        can never reject a valid event and a genuinely bad event is
        identified exactly (its verdict stays cached for insert to
        reject)."""
        obs = self._stage_obs
        t_verify = self.clock.perf_counter() if obs is not None else 0.0
        use_device_verify = self.accelerated_verify
        if use_device_verify:
            # Measured on the target: the device ladder kernel costs
            # ~590 ms per 64-signature tile through the accelerator
            # tunnel (dispatch/loop-bound) vs ~100 us/sig for the native
            # C++ verifier — the device NEVER wins at gossip batch sizes,
            # so the sync path stays on the host unless explicitly forced
            # (benchmarking / future hardware).
            import os

            from babble_tpu.ops.device import is_cpu_fallback, jax_usable

            # Opt-in AND a live accelerator: on the CPU/DEAD fallbacks
            # the ladder kernel would run on host XLA (or hang importing
            # jax), losing badly to the native verifier below.
            use_device_verify = (
                os.environ.get("BABBLE_DEVICE_VERIFY") == "1"
                and jax_usable()
                and not is_cpu_fallback()
            )
        if use_device_verify:
            from babble_tpu.ops.verify import prevalidate_events

            prevalidate_events(decoded)
        else:
            from babble_tpu.crypto.batch import prevalidate_events_host

            if not prevalidate_events_host(decoded):
                # Native library unavailable: scalar verify at insert.
                if obs is not None:
                    obs("batch_verify", self.clock.perf_counter() - t_verify)
                return
        self.ingest_batch_verifies += 1
        if len(decoded) > self.ingest_batch_size_max:
            self.ingest_batch_size_max = len(decoded)
        for ev in decoded:
            if ev.prevalidated() is False:
                ev.clear_prevalidation()
                ev.prevalidate(ev.verify())
                self.ingest_fallback_singles += 1
        if obs is not None:
            obs("batch_verify", self.clock.perf_counter() - t_verify)

    def sync(
        self,
        from_id: int,
        unknown_events: List[WireEvent],
        prepared: Optional[PreparedSync] = None,
        hop: Optional[dict] = None,
    ) -> None:
        """Insert wire events (topological order expected), track the other
        peer's head, and record a new self-event when busy
        (reference: core.go:210-289).

        ``prepared`` is the lock-free stage's output for these SAME wire
        events (see prepare_sync); without it the stage runs inline here,
        preserving the one-batch-verify-per-sync property for direct
        callers.

        ``hop`` is the carrying sync's causal-trace info
        (``{"from", "ctx", "recv"}`` — the node handlers build it from
        the RPC's trace context and arrival stamp); sampled transactions
        in newly inserted events get a first-seen provenance record with
        wire/queue/insert attribution (obs/provenance.py)."""
        self.ingest_syncs += 1
        if prepared is None:
            prepared = self.prepare_sync(unknown_events)
        elif prepared.wire_events is not unknown_events:
            # decoded[i] pairs positionally with wire_events[i]; a
            # prepared stage built from a different list would silently
            # mis-pair verified events with wire bookkeeping
            raise ValueError("prepared sync does not match wire events")
        prov = self.obs.provenance
        if prov is not None and prov.enabled and unknown_events:
            hop = dict(hop) if hop is not None else {}
            hop.setdefault("from", from_id)
            hop["start"] = self.clock.time()
        else:
            hop = None
        other_head: Optional[Event] = None
        n = len(unknown_events)
        # Equivocations are skip-and-collect, not abort: a fork-holding
        # honest peer's diff leads with its branch of the fork every
        # round, and aborting there would permanently wedge ingestion of
        # everything that peer exclusively holds. The first ForkError is
        # re-raised AFTER the batch (and heads/consensus bookkeeping)
        # completes, so the node's sentry still sees it.
        fork_errs: List[ForkError] = []

        pos = len(prepared.decoded)
        for we, ev in zip(unknown_events[:pos], prepared.decoded):
            other_head = self._ingest_one(
                we, ev, from_id, other_head, fork_errs, hop
            )

        while pos < n:
            # Tail after a decode stall: re-run decode+batch-verify in
            # chunks under the lock, resuming after the stalled inserts
            # land — identical semantics to the reference's sequential
            # decode+insert, just batched where the DAG allows.
            decoded: List[Event] = []
            j = pos
            if self.accelerated_verify or self._host_batch_verify:
                decoded, j = self._decode_chunk(unknown_events, pos)
                if decoded:
                    self._batch_prevalidate(decoded)
            if j == pos:
                # Sequential path (accelerator off, or chunk stalled at the
                # first event — let read_wire_info raise its real error).
                decoded = [self.hg.read_wire_info(unknown_events[pos])]
                j = pos + 1

            for we, ev in zip(unknown_events[pos:j], decoded):
                other_head = self._ingest_one(
                    we, ev, from_id, other_head, fork_errs, hop
                )
            pos = j

        # Do not overwrite a non-empty head with an empty one
        # (reference: core.go:246-252).
        existing = self.heads.get(from_id)
        if (
            from_id not in self.heads
            or existing is None
            or (other_head is not None and other_head.index() > existing.index())
        ):
            self.heads[from_id] = other_head

        # Only record a new self-event when there is something to say
        # (reference: core.go:264-270).
        if self.busy() or self.seq < 0:
            self.record_heads()
            self.drain_hot_mempool()

        # One batched voting sweep per sync covers every event inserted
        # above (device path; no-op on the oracle path).
        self.hg.flush_consensus()

        if fork_errs:
            raise fork_errs[0]

    def _ingest_one(
        self,
        we: WireEvent,
        ev: Event,
        from_id: int,
        other_head: Optional[Event],
        fork_errs: Optional[List[ForkError]] = None,
        hop: Optional[dict] = None,
    ) -> Optional[Event]:
        """Insert one decoded sync event and maintain the heads-merge
        bookkeeping; returns the updated other-peer head. A ForkError is
        collected into ``fork_errs`` (the insert is still refused) so
        the batch continues past it — see Core.sync."""
        try:
            self.insert_event_and_run_consensus(ev, set_wire_info=False)
        except ForkError as err:
            if fork_errs is None:
                raise
            fork_errs.append(err)
            return other_head
        except Exception as err:
            if is_normal_self_parent_error(err):
                # Benign concurrent-duplicate-insert race.
                return other_head
            raise

        if hop is not None and ev.body.transactions:
            # first local sight of this event's transactions: stamp the
            # sampled ones with per-hop attribution (duplicate inserts
            # never reach here — they raise above)
            self.obs.provenance.first_seen_batch(
                ev.body.transactions, hop
            )

        if we.body.creator_id == from_id:
            other_head = ev

        stale = self.heads.get(we.body.creator_id)
        if stale is not None and we.body.index > stale.index():
            del self.heads[we.body.creator_id]
        return other_head

    def record_heads(self) -> None:
        """reference: core.go:274-289."""
        for fid in list(self.heads.keys()):
            ev = self.heads[fid]
            self.add_self_event(ev.hex() if ev is not None else "")
            del self.heads[fid]

    def drain_hot_mempool(self) -> int:
        """Coalesced self-event minting under load: while a FULL
        event's worth of transactions is still pending after the
        regular self-event, mint up to ``selfevent_burst`` more (each
        chained on our own head, like a monologue event) so the backlog
        drains in one lock hold instead of one event cap per gossip
        tick. Deterministic — pure function of mempool/DAG state — so
        the sim engine replays it byte-identically. Returns the number
        of extra events minted."""
        minted = 0
        cap = max(1, self.mempool.event_max_txs)
        while (
            minted < self.selfevent_burst
            and self.mempool.pending_count >= cap
        ):
            before = self.mempool.pending_count
            try:
                self.add_self_event("")
            except Exception:
                logger.debug("coalesced self-event failed", exc_info=True)
                break
            if self.mempool.pending_count >= before:
                break  # no progress (too-early guard or requeue): stop
            minted += 1
        self.selfevent_coalesced += minted
        return minted

    def add_self_event(self, other_head: str) -> None:
        """Package the pools into a new head event
        (reference: core.go:292-333)."""
        if self.hg.store.last_round() < self.accepted_round:
            logger.debug(
                "too early to insert self-event (%d/%d)",
                self.hg.store.last_round(),
                self.accepted_round,
            )
            return

        obs = self._stage_obs
        t_event = self.clock.perf_counter() if obs is not None else 0.0
        sigs = list(self.self_block_signatures.values())
        n_itxs = len(self.internal_transaction_pool)

        # Batch drain under the mempool's caps: each self-event carries at
        # most event_max_txs / event_max_bytes of client transactions, so
        # gossip payloads stay bounded under sustained overload; leftovers
        # keep busy() true and ride the next event (FIFO fairness).
        txs = self.mempool.drain()
        if obs is not None:
            obs("mempool_drain", self.clock.perf_counter() - t_event)

        new_head = Event.new(
            txs,
            self.internal_transaction_pool[:n_itxs],
            sigs,
            [self.head, other_head],
            self.validator.public_key_bytes(),
            self.seq + 1,
            timestamp=int(self.clock.time()),
        )

        # Inserting can add items to the pools via the commit callback, so
        # only the packaged prefix is dropped (reference: core.go:325-330).
        # A failed insert puts the drained batch back at the FRONT of the
        # mempool — accepted transactions are never lost to a transient
        # event-creation failure.
        try:
            self.sign_and_insert_self_event(new_head)
        except Exception:
            self.mempool.requeue(txs)
            raise
        self.internal_transaction_pool = self.internal_transaction_pool[n_itxs:]
        for s in sigs:
            self.self_block_signatures.pop(s.key(), None)
        if obs is not None:
            # whole self-event packaging incl. its insert+DivideRounds
            # (the nested insert/divide_rounds stages record too)
            obs("self_event", self.clock.perf_counter() - t_event)

    def sign_and_insert_self_event(self, event: Event) -> None:
        """reference: core.go:337-343."""
        event.sign(self.validator.key)
        self.insert_event_and_run_consensus(event, set_wire_info=True)

    def insert_event_and_run_consensus(
        self, event: Event, set_wire_info: bool
    ) -> None:
        """reference: core.go:346-355."""
        self.hg.insert_event_and_run_consensus(event, set_wire_info)
        if event.creator() == self.validator.public_key_hex():
            self.head = event.hex()
            self.seq = event.index()

    def known_events(self) -> Dict[int, int]:
        return self.hg.store.known_events()

    # -- fast-forward -------------------------------------------------------

    def fast_forward(self, block: Block, frame: Frame) -> None:
        """Reset the hashgraph from a trusted Block+Frame
        (reference: core.go:367-402)."""
        peer_set = frame.peers

        self.hg.check_block(block, peer_set)

        if block.frame_hash() != frame.hash():
            raise ValueError("invalid frame hash")

        self.hg.reset(block, frame)
        self.set_head_and_seq()
        self.set_peers(peer_set)
        self.validators = peer_set

    def get_anchor_block_with_frame(self) -> tuple[Block, Frame]:
        return self.hg.get_anchor_block_with_frame()

    # -- leave --------------------------------------------------------------

    def leave(self, leave_timeout: float, lock=None) -> None:
        """Politely leave: submit a PEER_REMOVE itx and wait for consensus
        (reference: core.go:416-479). ``lock`` is the owning node's core
        lock, held only while mutating the pools — the consensus wait must
        happen outside it."""
        if self.maintenance_mode:
            return
        # A rejoining node can reach BABBLING (its join was accepted
        # remotely) while its OWN replay is still catching up through
        # history — at that instant self.validators may reflect an older
        # epoch that does not contain us (it may even have just replayed
        # our previous leave). Treating that stale view as "not a
        # validator" silently skips the leave and strands a ghost
        # validator in everyone's peer-set forever (found by the looped
        # rejoin hunt, tests/test_node_rejoin_loop.py). Wait for the
        # replay to reach our join before concluding we have nothing to
        # do — capped below leave_timeout so a node that genuinely never
        # joined doesn't stall its shutdown for the whole timeout.
        deadline = self.clock.monotonic() + min(leave_timeout, 5.0)
        while True:
            p = self.validators.by_id.get(self.validator.id())
            if p is not None or self.clock.monotonic() > deadline:
                break
            self.clock.sleep(0.05)
        if p is None or len(self.validators) <= 1:
            return

        itx = InternalTransaction.leave(p)
        itx.sign(self.validator.key)
        if lock is not None:
            with lock:
                promise = self.add_internal_transaction(itx)
        else:
            promise = self.add_internal_transaction(itx)

        try:
            resp = promise.wait(timeout=leave_timeout)
        except queue.Empty:
            raise TimeoutError("timeout waiting for leave request consensus")

        logger.debug("leave accepted at round %d", resp.accepted_round)

        # Wait until consensus reaches the removed round
        # (reference: core.go:458-478).
        if len(self.peers) >= 1:
            deadline = self.clock.monotonic() + leave_timeout
            while (
                self.hg.last_consensus_round is None
                or self.hg.last_consensus_round < self.removed_round
            ):
                if self.clock.monotonic() > deadline:
                    raise TimeoutError("timeout waiting to reach removed round")
                self.clock.sleep(0.05)

    # -- commit -------------------------------------------------------------

    def commit(self, block: Block) -> None:
        """The hashgraph's commit callback: push the block to the app, sign
        it, and process membership receipts (reference: core.go:485-536)."""
        obs = self._stage_obs
        if obs is None:
            commit_response = self.proxy_commit_callback(block)
        else:
            t0 = self.clock.perf_counter()
            try:
                commit_response = self.proxy_commit_callback(block)
            finally:
                obs("proxy_deliver", self.clock.perf_counter() - t0)

        # Feed the committed-hash LRU atomically with the commit (under
        # the mempool's own lock): from here on a client retry of any of
        # these transactions gets `already_committed`, and pending copies
        # (same tx submitted to several nodes, committed via another's
        # event) are dropped before they can double-commit.
        self.mempool.mark_committed(block.transactions())

        # Provenance: close the sampled transactions' records with the
        # commit stamp + block coordinates (every node stamps its own
        # commit; traceview merges the spread).
        prov = self.obs.provenance
        if prov is not None and prov.enabled and block.transactions():
            prov.commit_batch(
                block.transactions(), block.index(), block.round_received()
            )

        block.body.state_hash = commit_response.state_hash
        block.body.internal_transaction_receipts = commit_response.receipts

        # Sign the block if we belong to its validator-set
        # (reference: core.go:510-522).
        block_peer_set = self.hg.store.get_peer_set(block.round_received())
        if self.validator.id() in block_peer_set.by_id:
            sig = self.sign_block(block)
            self.self_block_signatures[sig.key()] = sig

        self.hg.set_anchor_block(block)

        self.process_accepted_internal_transactions(
            block.round_received(), commit_response.receipts
        )

        for listener in self.commit_listeners:
            try:
                listener(block)
            except Exception:  # noqa: BLE001 — the read tier never stalls consensus
                logger.debug("commit listener failed", exc_info=True)

    def sign_block(self, block: Block):
        """reference: core.go:539-556."""
        sig = block.sign(self.validator.key)
        block.set_signature(sig)
        self.hg.store.set_block(block)
        return sig

    def process_accepted_internal_transactions(
        self, round_received: int, receipts: List[InternalTransactionReceipt]
    ) -> None:
        """Apply accepted PEER_ADD/PEER_REMOVE at round_received + 6
        (reference: core.go:562-650)."""
        current_peers = self.peers
        validators = self.validators
        effective_round = round_received + PEER_SET_EFFECTIVE_DELAY

        changed = False
        for r in receipts:
            body = r.internal_transaction.body
            if not r.accepted:
                continue
            if body.type == TransactionType.PEER_ADD:
                validators = validators.with_new_peer(body.peer)
                current_peers = current_peers.with_new_peer(body.peer)
            elif body.type == TransactionType.PEER_REMOVE:
                validators = validators.with_removed_peer(body.peer)
                current_peers = current_peers.with_removed_peer(body.peer)
                if body.peer.id == self.validator.id():
                    self.removed_round = effective_round
            else:
                continue
            changed = True

        if changed:
            self.last_peer_change_round = effective_round
            self.hg.store.set_peer_set(effective_round, validators)
            self.validators = validators
            self.set_peers(current_peers)
            # Force everyone to reach the effective round so joiners can
            # participate (reference: core.go:639-643).
            if effective_round > self.target_round:
                self.target_round = effective_round

        for r in receipts:
            promise = self.promises.pop(r.internal_transaction.hash_string(), None)
            if promise is not None:
                if r.accepted:
                    promise.respond(True, effective_round, self.validators.peers)
                else:
                    promise.respond(False, 0, [])

    # -- diff ---------------------------------------------------------------

    def event_diff(self, other_known: Dict[int, int]) -> List[Event]:
        """Events we know that the other does not, topologically ordered
        (reference: core.go:660-703)."""
        unknown: List[Event] = []
        my_known = self.known_events()
        repertoire = self.hg.store.repertoire_by_id()
        for pid in my_known:
            ct = other_known.get(pid, -1)
            peer = repertoire.get(pid)
            if peer is None:
                continue
            for eh in self.hg.store.participant_events(peer.pub_key_hex, ct):
                unknown.append(self.hg.store.get_event(eh))
        return sort_topological(unknown)

    def to_wire(self, events: List[Event]) -> List[WireEvent]:
        return [e.to_wire() for e in events]

    # -- pools --------------------------------------------------------------

    def process_sig_pool(self) -> None:
        self.hg.process_sig_pool()

    @property
    def transaction_pool(self) -> List[bytes]:
        """FIFO snapshot of the mempool's pending transactions (read-only
        compatibility view of the reference's transactionPool slice)."""
        return self.mempool.pending_txs()

    def add_transactions(self, txs: List[bytes]) -> List[str]:
        """Admit transactions through the mempool; returns the verdicts
        (reference: core.go:740-745 appended unconditionally)."""
        return self.mempool.submit_many(txs)

    def add_internal_transaction(self, tx: InternalTransaction) -> JoinPromise:
        """reference: core.go:747-758."""
        promise = JoinPromise(tx)
        self.promises[tx.hash_string()] = promise
        self.internal_transaction_pool.append(tx)
        return promise

    # -- getters ------------------------------------------------------------

    def get_head(self) -> Event:
        return self.hg.store.get_event(self.head)

    def get_event(self, h: str) -> Event:
        return self.hg.store.get_event(h)

    def get_consensus_events_count(self) -> int:
        return self.hg.store.consensus_events_count()

    def get_undetermined_events(self) -> List[str]:
        return self.hg.undetermined_events

    def get_last_block_index(self) -> int:
        return self.hg.store.last_block_index()

    def get_last_consensus_round_index(self) -> Optional[int]:
        return self.hg.last_consensus_round

    def get_consensus_transactions_count(self) -> int:
        return self.hg.consensus_transactions
