"""Peer selection for gossip, with per-peer health scoring and backoff.

Reference semantics: src/node/peer_selector.go:11-103 — pick the next
gossip partner at random, excluding self and the last-contacted peer, and
track per-peer connected flags.

On top of the reference's uniform pick, the selector keeps a health score
per peer, fed by ``update_last``'s connected flag (the gossip loop calls
it after every round):

- every failure halves the score (floor ``score_floor``) and arms an
  exponential backoff with jitter — while it runs, the peer is skipped,
  so a dead peer stops eating gossip rounds within a few failures;
- when a failing peer's backoff expires it becomes due for a **probe**:
  the next ``next()`` returns it directly (rate-limited to one probe per
  ``probe_interval_s``), so no peer is ever starved and a healed peer is
  rediscovered promptly;
- successes multiply the score back up (full health after ~3 straight
  successes — graded so one lucky round through a flapping peer doesn't
  restore its full selection share);
- healthy peers are drawn with probability proportional to score, so a
  degraded-but-alive peer still gets a trickle of traffic instead of a
  hard cutoff.

If EVERY candidate is inside its backoff, the least-recently-blocked one
is returned anyway: gossip must never fully stop while any peer might
answer (liveness beats politeness under a full partition).

``clock``/``rng`` are injectable for deterministic tests. The selector
carries its OWN narrow lock (see RandomPeerSelector docstring below) and
health state survives peer-set changes via the ``prior`` argument
(core.set_peers passes the outgoing selector).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Protocol

from ..common.backoff import jittered_backoff
from ..peers.peer import Peer
from ..peers.peer_set import PeerSet


class PeerSelector(Protocol):
    def get_peers(self) -> PeerSet: ...

    def update_last(
        self, peer_id: int, connected: bool, penalize: bool = True
    ) -> bool: ...

    def next(self) -> Optional[Peer]: ...


@dataclass
class _Health:
    """Mutable per-peer health record (guarded by the selector lock)."""

    score: float = 1.0
    failures: int = 0  # consecutive failures
    blocked_until: float = 0.0  # backoff deadline (0 = not backed off)
    next_probe: float = 0.0  # earliest time a probe pick may fire
    probes: int = 0


class RandomPeerSelector:
    """reference: peer_selector.go:19-103, plus health scoring (above).

    Carries its OWN narrow lock: the selector is touched from gossip
    worker threads (next / update_last) that deliberately do NOT hold the
    node's core lock — selector state is independent of the hashgraph, so
    serializing it on the core lock only added contention to the insert
    pipeline."""

    def __init__(
        self,
        peer_set: PeerSet,
        self_id: int,
        prior: Optional["RandomPeerSelector"] = None,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        backoff_jitter: float = 0.25,
        score_decay: float = 0.5,
        score_recover: float = 3.0,
        score_floor: float = 0.05,
        probe_interval_s: float = 0.5,
        clock: Callable[[], float] = time.monotonic,
        rng: Optional[random.Random] = None,
        quarantine_check: Optional[Callable[[int], bool]] = None,
    ):
        self.peers = peer_set
        self.self_id = self_id
        self._lock = threading.Lock()
        self._selectable: Dict[int, Peer] = {
            p.id: p for p in peer_set.peers if p.id != self_id
        }
        self._connected: Dict[int, bool] = {pid: False for pid in self._selectable}
        self.last: Optional[int] = None
        if prior is not None:
            # peer-set change: keep tuning and the surviving peers' health
            backoff_base_s = prior.backoff_base_s
            backoff_cap_s = prior.backoff_cap_s
            backoff_jitter = prior.backoff_jitter
            score_decay = prior.score_decay
            score_recover = prior.score_recover
            score_floor = prior.score_floor
            probe_interval_s = prior.probe_interval_s
            clock = prior._clock
            rng = prior._rng
            if quarantine_check is None:
                quarantine_check = prior._quarantine_check
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.backoff_jitter = backoff_jitter
        self.score_decay = score_decay
        self.score_recover = score_recover
        self.score_floor = score_floor
        self.probe_interval_s = probe_interval_s
        self._clock = clock
        self._rng = rng if rng is not None else random.Random()
        # Sentry hook (node/sentry.py): while a peer is quarantined for
        # misbehavior it is excluded from gossip picks entirely — unlike
        # health backoff there is no probe trickle; the peer is
        # re-admitted only when the sentry's time-box expires.
        self._quarantine_check = quarantine_check
        self._health: Dict[int, _Health] = {}
        for pid in self._selectable:
            carried = prior._health.get(pid) if prior is not None else None
            self._health[pid] = carried if carried is not None else _Health()
        # counters surfaced through stats()
        self.backoff_skips = 0  # picks where ≥1 peer sat out a backoff
        self.probe_picks = 0  # picks that were forced probes
        self.starvation_overrides = 0  # all-backed-off liveness picks
        self.quarantine_skips = 0  # picks where ≥1 peer sat out a quarantine
        self.quarantine_overrides = 0  # all-quarantined liveness picks

    def get_peers(self) -> PeerSet:
        return self.peers

    # -- outcome feedback ----------------------------------------------

    def update_last(
        self, peer_id: int, connected: bool, penalize: bool = True
    ) -> bool:
        """Record the outcome of the last gossip; returns True on a new
        connection (reference: peer_selector.go:62-77). Feeds the health
        score and per-peer backoff. ``penalize=False`` records the
        connected flag without decaying health — for failures that were
        LOCAL (a handler bug, not the network), so a core defect can't
        back off every healthy peer in turn."""
        now = self._clock()
        with self._lock:
            self.last = peer_id
            if peer_id not in self._connected:
                return False
            h = self._health[peer_id]
            if connected:
                h.failures = 0
                h.blocked_until = 0.0
                h.next_probe = 0.0
                h.score = min(1.0, max(h.score, self.score_floor)
                              * self.score_recover)
            elif penalize:
                h.failures += 1
                h.score = max(self.score_floor, h.score * self.score_decay)
                h.blocked_until = now + jittered_backoff(
                    h.failures, self.backoff_base_s, self.backoff_cap_s,
                    self.backoff_jitter, self._rng,
                )
                # a probe becomes due once the backoff expires
                h.next_probe = h.blocked_until
            old = self._connected[peer_id]
            self._connected[peer_id] = connected
            return connected and not old

    # -- pick ------------------------------------------------------------

    def next(self) -> Optional[Peer]:
        """reference: peer_selector.go:80-103, health-weighted."""
        with self._lock:
            exclude = {self.last} if self.last is not None else set()
            return self._pick_locked(self._clock(), exclude)

    def next_many(self, k: int) -> List[Peer]:
        """Up to ``k`` DISTINCT gossip partners for one fan-out tick
        (adaptive scheduler, docs/gossip.md §Adaptive scheduling). Each
        pick runs the same health-weighted law as :meth:`next` with the
        already-chosen peers excluded; the list stops early when no
        further distinct candidate exists, so ``k`` larger than the
        peer set degrades gracefully."""
        picked: List[Peer] = []
        never: set = set()
        with self._lock:
            now = self._clock()
            avoid = {self.last} if self.last is not None else set()
            for _ in range(max(1, k)):
                # snapshot the skip/override counters: a pick the dup
                # check below discards must not inflate the operator
                # alarms (quarantine/starvation overrides) fanout-fold
                before = (
                    self.backoff_skips, self.probe_picks,
                    self.starvation_overrides, self.quarantine_skips,
                    self.quarantine_overrides,
                )
                p = self._pick_locked(now, avoid, never)
                if p is None or any(q.id == p.id for q in picked):
                    # exhausted, or a liveness override re-served a peer
                    # already chosen this tick — fan-out never doubles up
                    (self.backoff_skips, self.probe_picks,
                     self.starvation_overrides, self.quarantine_skips,
                     self.quarantine_overrides) = before
                    break
                picked.append(p)
                never = never | {p.id}
        return picked

    def _pick_locked(
        self, now: float, avoid: set, never: set = frozenset()
    ) -> Optional[Peer]:
        """One health-weighted pick; callers hold the selector lock.
        ``avoid`` peers (the reference's last-contacted exclusion) are
        skipped while alternatives exist but re-admitted when nothing
        else remains; ``never`` peers (fan-out's already-picked set) are
        only re-served on the final everyone-excluded fallback, which
        the caller's duplicate check turns into a stop — so a fan-out
        tick fills from every distinct candidate, including ``last``,
        before giving up."""
        ids = list(self._selectable.keys())
        if not ids:
            return None
        if self._quarantine_check is not None:
            # Quarantined peers are hard-excluded (no probe trickle)
            # while ANY non-quarantined peer exists — but with the
            # same liveness floor as the backoff path: an
            # all-quarantined view means framing (the sentry caps
            # honest quarantines at the BFT f bound) or gross
            # misconfiguration, and gossip must keep trying SOMEONE.
            open_ids = [i for i in ids if not self._quarantine_check(i)]
            if len(open_ids) < len(ids):
                self.quarantine_skips += 1
            if not open_ids:
                self.quarantine_overrides += 1
            else:
                ids = open_ids
        if len(ids) == 1:
            return self._selectable[ids[0]]
        pool = [i for i in ids if i not in never] or ids
        candidates = [i for i in pool if i not in avoid] or pool

        # due probes first: a failing peer whose backoff expired gets
        # deterministically re-tried (never starved, heals promptly).
        # Most-overdue first, so several failing peers share the probe
        # budget round-robin instead of the first-in-map monopolizing.
        due = [
            pid
            for pid in candidates
            if self._health[pid].failures > 0
            and self._health[pid].blocked_until <= now
            and 0.0 < self._health[pid].next_probe <= now
        ]
        if due:
            pid = min(due, key=lambda i: self._health[i].next_probe)
            h = self._health[pid]
            h.next_probe = now + self.probe_interval_s
            h.probes += 1
            self.probe_picks += 1
            return self._selectable[pid]

        open_ids = [
            i for i in candidates if self._health[i].blocked_until <= now
        ]
        if len(open_ids) < len(candidates):
            self.backoff_skips += 1
        if not open_ids:
            # every non-avoided candidate is backed off. Before
            # resurrecting a backed-off (likely dead) peer, re-admit
            # the avoided ones if THEY are healthy — re-gossiping a
            # known-good peer beats burning a round on a known-bad one.
            open_ids = [
                i for i in pool if self._health[i].blocked_until <= now
            ]
        if not open_ids:
            # truly everyone is backed off: pick the one whose backoff
            # expires first — gossip must keep trying SOMEONE
            self.starvation_overrides += 1
            return self._selectable[
                min(pool, key=lambda i: self._health[i].blocked_until)
            ]
        weights = [self._health[i].score for i in open_ids]
        total = sum(weights)
        if total <= 0.0:
            return self._selectable[self._rng.choice(open_ids)]
        roll = self._rng.random() * total
        acc = 0.0
        for pid, w in zip(open_ids, weights):
            acc += w
            if roll <= acc:
                return self._selectable[pid]
        return self._selectable[open_ids[-1]]

    # -- observability ---------------------------------------------------

    def stats(self) -> Dict[str, object]:
        with self._lock:
            unhealthy = sum(1 for h in self._health.values() if h.failures > 0)
            backed_off = sum(
                1
                for h in self._health.values()
                if h.blocked_until > self._clock()
            )
            return {
                "selector_unhealthy_peers": unhealthy,
                "selector_backed_off_peers": backed_off,
                "selector_backoff_skips": self.backoff_skips,
                "selector_probe_picks": self.probe_picks,
                "selector_starvation_overrides": self.starvation_overrides,
                "selector_quarantine_skips": self.quarantine_skips,
                "selector_quarantine_overrides": self.quarantine_overrides,
            }

    def health_of(self, peer_id: int) -> Optional[_Health]:
        """Test/debug hook: the live health record for one peer."""
        return self._health.get(peer_id)
