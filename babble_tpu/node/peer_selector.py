"""Peer selection for gossip.

Reference semantics: src/node/peer_selector.go:11-103 — pick the next
gossip partner at random, excluding self and the last-contacted peer, and
track per-peer connected flags.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Protocol

from ..peers.peer import Peer
from ..peers.peer_set import PeerSet


class PeerSelector(Protocol):
    def get_peers(self) -> PeerSet: ...

    def update_last(self, peer_id: int, connected: bool) -> bool: ...

    def next(self) -> Optional[Peer]: ...


class RandomPeerSelector:
    """reference: peer_selector.go:19-103.

    Carries its OWN narrow lock: the selector is touched from gossip
    worker threads (next / update_last) that deliberately do NOT hold the
    node's core lock — selector state is independent of the hashgraph, so
    serializing it on the core lock only added contention to the insert
    pipeline."""

    def __init__(self, peer_set: PeerSet, self_id: int):
        self.peers = peer_set
        self.self_id = self_id
        self._lock = threading.Lock()
        self._selectable: Dict[int, Peer] = {
            p.id: p for p in peer_set.peers if p.id != self_id
        }
        self._connected: Dict[int, bool] = {pid: False for pid in self._selectable}
        self.last: Optional[int] = None

    def get_peers(self) -> PeerSet:
        return self.peers

    def update_last(self, peer_id: int, connected: bool) -> bool:
        """Record the outcome of the last gossip; returns True on a new
        connection (reference: peer_selector.go:62-77)."""
        with self._lock:
            self.last = peer_id
            if peer_id in self._connected:
                old = self._connected[peer_id]
                self._connected[peer_id] = connected
                return connected and not old
            return False

    def next(self) -> Optional[Peer]:
        """reference: peer_selector.go:80-103."""
        with self._lock:
            ids = list(self._selectable.keys())
            if not ids:
                return None
            if len(ids) == 1:
                return self._selectable[ids[0]]
            candidates = [i for i in ids if i != self.last] or ids
            return self._selectable[random.choice(candidates)]
