"""SyncPipeline: bounded decode → batch-verify → insert staging for
inbound eager syncs.

The seed shape ran each inbound sync's whole life on one routine
thread: decode + batch-verify (lock-free since the batched-ingest fast
path) and then the insert tail under the core lock. Under 16-node load
that parks a pile of handler threads on the core lock, each holding its
decoded batch, convoying on the GIL (the `lock_wait_ms` counters from
PR 1 localize exactly this).

This pipeline splits the stages explicitly:

- **Stage 1 (caller thread, lock-free, concurrent):** decode + one
  native batch signature verification per sync (``Core.prepare_sync``)
  — many inbound syncs overlap here.
- **Stage 2 (one inserter thread, serialized):** the ordered insert +
  DivideRounds tail under the core lock — the only part that MUST be
  serial, drained by a single thread so handler threads never queue on
  the lock itself.

The hand-off queue is **bounded**: when inserts fall behind, submitters
block (briefly) and then run the insert inline — so the transport's
read loop ultimately slows down instead of the node buffering
unbounded decoded batches (backpressure). The ``inflight`` gauge (and
its high-water mark) is the `gossip_inflight_syncs` instrument.

The pipeline is wall-clock only: the deterministic sim engine drives
``_process_rpc`` single-threaded under virtual time, where a background
inserter thread would break replay determinism — Node only constructs
the pipeline when its clock is the process wall clock.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional


class SyncPipeline:
    def __init__(self, node, queue_cap: int = 64, submit_timeout: float = 5.0):
        self.node = node
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=max(1, queue_cap))
        self._submit_timeout = submit_timeout
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # -- instruments (obs/catalog.py: gossip_*) --
        self.inflight = 0            # syncs between submit and respond
        self.inflight_peak = 0       # high-water mark
        self.pipelined_syncs = 0     # syncs that went through the queue
        self.backpressure_stalls = 0  # submits that found the queue full

    # -- lifecycle -----------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            with self._lock:
                if self._thread is None or not self._thread.is_alive():
                    if self._stop.is_set():
                        return
                    self._thread = threading.Thread(
                        target=self._insert_loop, daemon=True,
                        name="sync-inserter",
                    )
                    self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._drain_stopped()

    def _drain_stopped(self) -> None:
        """Politely fail anything still queued so clients see an error
        instead of a silent timeout. Called by stop() and by any
        submit() that raced past the stop check — either way every
        queued RPC gets an answer and the inflight gauge balances."""
        while True:
            try:
                rpc, _cmd, _prepared, _hop = self._q.get_nowait()
            except queue.Empty:
                break
            self._dec_inflight()
            try:
                rpc.respond(None, "node shutting down")
            except Exception:
                pass

    # -- stages --------------------------------------------------------------

    def submit(self, rpc, cmd, hop) -> bool:
        """Stage 1 in the caller's thread, then enqueue the insert tail.
        Returns False when the pipeline is stopped (caller handles the
        sync inline, the pre-pipeline shape)."""
        if self._stop.is_set():
            return False
        self._ensure_thread()
        if self._thread is None:
            return False
        with self._lock:
            self.inflight += 1
            if self.inflight > self.inflight_peak:
                self.inflight_peak = self.inflight
        try:
            prepared = self.node.core.prepare_sync(cmd.events)
        except Exception as e:
            # answer here rather than returning False: the inline
            # fallback would re-run the whole decode + native batch
            # verify, doubling the CPU a hostile malformed batch costs.
            # _fail_eager_sync keeps the sentry attribution (peer-fault
            # rejections score the sender, crashes count rpc_errors).
            self._dec_inflight()
            try:
                self.node._fail_eager_sync(rpc, cmd, e)
            except Exception:
                pass
            return True
        if self._q.full():
            self.backpressure_stalls += 1
        try:
            self._q.put((rpc, cmd, prepared, hop),
                        timeout=self._submit_timeout)
        except queue.Full:
            # sustained pressure: do the insert on this thread — the
            # submitter (and through it the transport) pays the cost,
            # which is exactly the backpressure contract
            try:
                self.node._finish_eager_sync(rpc, cmd, prepared, hop)
            finally:
                self._dec_inflight()
            return True
        if self._stop.is_set():
            # raced with stop(): the inserter may already be gone and
            # stop()'s drain may have run before our put landed —
            # drain again so this RPC cannot hang unanswered
            self._drain_stopped()
        self.pipelined_syncs += 1
        return True

    def _insert_loop(self) -> None:
        while not self._stop.is_set():
            try:
                rpc, cmd, prepared, hop = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                self.node._finish_eager_sync(rpc, cmd, prepared, hop)
            except Exception:
                # _finish_eager_sync responds internally; a crash here
                # must not kill the inserter for every later sync
                pass
            finally:
                self._dec_inflight()

    def _dec_inflight(self) -> None:
        with self._lock:
            self.inflight -= 1

    def queue_depth(self) -> int:
        """Prepared syncs waiting in the bounded insert queue right now
        (the gossip_pipeline_queue_depth gauge — live backpressure,
        where the stall counters only show history)."""
        return self._q.qsize()

    def stats(self) -> dict:
        return {
            "gossip_inflight_syncs": self.inflight,
            "gossip_inflight_syncs_peak": self.inflight_peak,
            "gossip_pipelined_syncs": self.pipelined_syncs,
            "gossip_backpressure_stalls": self.backpressure_stalls,
            "gossip_pipeline_queue_depth": self.queue_depth(),
        }
