"""SyncPipeline: bounded decode → batch-verify → insert staging for
inbound eager syncs AND the gossip pull leg.

The seed shape ran each inbound sync's whole life on one routine
thread: decode + batch-verify (lock-free since the batched-ingest fast
path) and then the insert tail under the core lock. Under 16-node load
that parks a pile of handler threads on the core lock, each holding its
decoded batch, convoying on the GIL (the `lock_wait_ms` counters from
PR 1 localize exactly this).

This pipeline splits the stages explicitly:

- **Stage 1 (caller thread, lock-free, concurrent):** decode + one
  native batch signature verification per sync (``Core.prepare_sync``)
  — many inbound syncs overlap here.
- **Stage 2 (one inserter thread, serialized):** the ordered insert +
  DivideRounds tail under the core lock — the only part that MUST be
  serial, drained by a single thread so handler threads never queue on
  the lock itself.

Two kinds of work ride the same bounded queue (one FIFO, one inserter,
so per-peer arrival order is preserved across both):

- **eager syncs** (``submit``): a remote push with an RPC to answer —
  the response fires after the insert lands;
- **pulled syncs** (``submit_pull``): the events OUR gossip round
  pulled from a peer. Pre-pipeline, ``Node._pull`` ran the insert on
  the gossip thread under the core lock, so one slow insert stalled
  the next pull round-trip; staged, the gossip thread is free the
  moment stage 1 finishes and the pull leg's latency is the wire
  round-trip, not the insert.

The hand-off queue is **bounded**: when inserts fall behind, submitters
block (briefly) and then run the insert inline — so the transport's
read loop (or the pull gossip loop) ultimately slows down instead of
the node buffering unbounded decoded batches (backpressure). The
``inflight`` gauge (and its high-water mark) is the
`gossip_inflight_syncs` instrument. On top of the hard queue bound, the
adaptive scheduler (node/adaptive.py) publishes a **soft depth cap**:
under ingest congestion the pipeline treats a shallower queue as
"full", so backpressure reaches senders before the hard bound does.

The pipeline is wall-clock only: the deterministic sim engine drives
``_process_rpc`` single-threaded under virtual time, where a background
inserter thread would break replay determinism — Node only constructs
the pipeline when its clock is the process wall clock (which also keeps
the pull leg inline, and deterministic, under sim).
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from ..common.timed_lock import named_lock


class _PullSync:
    """Stand-in for the RPC command on a pulled batch: just the fields
    the insert tail needs. ``rpc is None`` marks a pull job in the
    queue — there is no remote caller to answer."""

    __slots__ = ("from_id", "events")

    def __init__(self, from_id: int, events: list):
        self.from_id = from_id
        self.events = events


class SyncPipeline:
    def __init__(self, node, queue_cap: int = 64, submit_timeout: float = 5.0):
        self.node = node
        self._q: "queue.Queue[tuple]" = queue.Queue(maxsize=max(1, queue_cap))
        self._submit_timeout = submit_timeout
        # Named for the BABBLE_LOCKCHECK order recorder (lockcheck.py).
        self._lock = named_lock("pipeline")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Signaled by the inserter after each drained item: soft-capped
        # submitters wait on it instead of polling (or queue-jumping).
        self._drained = threading.Condition()
        # -- instruments (obs/catalog.py: gossip_*) --
        self.inflight = 0            # syncs between submit and respond
        self.inflight_peak = 0       # high-water mark
        self.pipelined_syncs = 0     # syncs that went through the queue
        self.pull_pipelined = 0      # of which: gossip pull legs
        self.backpressure_stalls = 0  # submits that found the queue full
        # Soft depth cap (adaptive scheduler): submits treat the queue
        # as full at this depth; the hard Queue bound stays the ceiling.
        self.soft_depth = max(1, queue_cap)

    # -- lifecycle -----------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            with self._lock:
                if self._thread is None or not self._thread.is_alive():
                    if self._stop.is_set():
                        return
                    self._thread = threading.Thread(
                        target=self._insert_loop, daemon=True,
                        name="sync-inserter",
                    )
                    self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._drain_stopped()

    def set_soft_depth(self, depth: int) -> None:
        """Adaptive backpressure threshold (clamped to the hard bound)."""
        self.soft_depth = max(1, min(self._q.maxsize, int(depth)))

    def _drain_stopped(self) -> None:
        """Politely fail anything still queued so clients see an error
        instead of a silent timeout. Called by stop() and by any
        submit() that raced past the stop check — either way every
        queued RPC gets an answer and the inflight gauge balances.
        Pull jobs (rpc None) have no caller to answer; their events
        simply don't land, which a shutting-down node is allowed."""
        while True:
            try:
                rpc, _cmd, _prepared, _hop = self._q.get_nowait()
            except queue.Empty:
                break
            self._dec_inflight()
            if rpc is None:
                continue
            try:
                rpc.respond(None, "node shutting down")
            except Exception:
                pass

    # -- stages --------------------------------------------------------------

    def submit(self, rpc, cmd, hop) -> bool:
        """Stage 1 in the caller's thread, then enqueue the insert tail.
        Returns False when the pipeline is stopped (caller handles the
        sync inline, the pre-pipeline shape)."""
        if self._stop.is_set():
            return False
        self._ensure_thread()
        if self._thread is None:
            return False
        self._inc_inflight()
        try:
            prepared = self.node.core.prepare_sync(cmd.events)
        except Exception as e:
            # answer here rather than returning False: the inline
            # fallback would re-run the whole decode + native batch
            # verify, doubling the CPU a hostile malformed batch costs.
            # _fail_eager_sync keeps the sentry attribution (peer-fault
            # rejections score the sender, crashes count rpc_errors).
            self._dec_inflight()
            try:
                self.node._fail_eager_sync(rpc, cmd, e)
            except Exception:
                pass
            return True
        self._enqueue(rpc, cmd, prepared, hop)
        return True

    def submit_pull(self, from_id: int, events: list, hop) -> bool:
        """The pull leg's staging: decode + batch-verify in the calling
        gossip thread (stage 1), insert tail through the shared bounded
        queue. Returns False when the pipeline is stopped — the caller
        runs the pre-pipeline inline shape. A stage-1 failure PROPAGATES
        to the caller: `_gossip` must see it exactly like the inline
        pull path's (skip the push leg, score the serving peer through
        the sentry, record the contact as failed) — swallowing it here
        would keep pushing to, and health-boosting, a peer whose every
        batch fails verification."""
        if self._stop.is_set():
            return False
        self._ensure_thread()
        if self._thread is None:
            return False
        self._inc_inflight()
        cmd = _PullSync(from_id, events)
        try:
            prepared = self.node.core.prepare_sync(events)
        except Exception:
            self._dec_inflight()
            raise
        if self._enqueue(None, cmd, prepared, hop):
            # counted only when the insert tail actually left this
            # thread — a backpressure-degraded inline insert must not
            # read as "pipelined" (the acceptance metric's contract)
            self.pull_pipelined += 1
        return True

    def _enqueue(self, rpc, cmd, prepared, hop) -> bool:
        """Shared insert-tail hand-off: bounded put with the soft-depth
        early-full check; sustained pressure degrades to an inline
        insert on the submitter's thread (the backpressure contract).
        Returns True when the job was handed to the inserter, False
        when it degraded to an inline insert."""
        if self._q.qsize() >= self.soft_depth:
            # adaptive soft cap hit: BLOCK this submitter until the
            # inserter drains below the cap (or the timeout passes) —
            # early backpressure that still goes through the FIFO.
            # Running the insert inline here instead would jump the
            # queue and reorder a peer's batches against its earlier
            # ones still waiting (insert failures the sentry would then
            # score against an honest peer); ordering is the pipeline's
            # contract, so the only inline path left is the wedged-
            # inserter timeout fallback below, same as pre-soft-cap.
            self.backpressure_stalls += 1
            deadline = self.node.clock.monotonic() + self._submit_timeout
            with self._drained:
                while (
                    self._q.qsize() >= self.soft_depth
                    and not self._stop.is_set()
                    and self.node.clock.monotonic() < deadline
                ):
                    self._drained.wait(timeout=0.05)
            # the put below spends what is LEFT of the same budget — a
            # wedged inserter must degrade to the inline fallback after
            # one submit_timeout total, not two back to back
            budget = max(0.05, deadline - self.node.clock.monotonic())
        else:
            budget = self._submit_timeout
        try:
            self._q.put((rpc, cmd, prepared, hop), timeout=budget)
        except queue.Full:
            if rpc is None:
                # wedged-inserter fallback, pull flavor: DROP the batch
                # rather than insert it out of order ahead of the same
                # peer's queued earlier batches (the resulting unknown-
                # parent rejections would sentry-score an honest peer).
                # Pulls are idempotent — the next round re-fetches —
                # and the timeout above already was the backpressure.
                self._dec_inflight()
                return False
            self._finish_inline(rpc, cmd, prepared, hop)
            return False
        if self._stop.is_set():
            # raced with stop(): the inserter may already be gone and
            # stop()'s drain may have run before our put landed —
            # drain again so this RPC cannot hang unanswered
            self._drain_stopped()
        self.pipelined_syncs += 1
        return True

    def _finish_inline(self, rpc, cmd, prepared, hop) -> None:
        try:
            if rpc is None:
                self.node._finish_pulled_sync(
                    cmd.from_id, cmd.events, prepared, hop
                )
            else:
                self.node._finish_eager_sync(rpc, cmd, prepared, hop)
        finally:
            self._dec_inflight()

    def _insert_loop(self) -> None:
        while not self._stop.is_set():
            try:
                rpc, cmd, prepared, hop = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            try:
                # same dispatch as the inline-degrade path (one place)
                self._finish_inline(rpc, cmd, prepared, hop)
            except Exception:
                # the finishers answer/attribute internally; a crash
                # here must not kill the inserter for every later sync
                pass
            finally:
                with self._drained:
                    self._drained.notify_all()

    def _inc_inflight(self) -> None:
        with self._lock:
            self.inflight += 1
            if self.inflight > self.inflight_peak:
                self.inflight_peak = self.inflight

    def _dec_inflight(self) -> None:
        with self._lock:
            self.inflight -= 1

    def queue_depth(self) -> int:
        """Prepared syncs waiting in the bounded insert queue right now
        (the gossip_pipeline_queue_depth gauge — live backpressure,
        where the stall counters only show history)."""
        return self._q.qsize()

    def stats(self) -> dict:
        return {
            "gossip_inflight_syncs": self.inflight,
            "gossip_inflight_syncs_peak": self.inflight_peak,
            "gossip_pipelined_syncs": self.pipelined_syncs,
            "gossip_pull_pipelined_syncs": self.pull_pipelined,
            "gossip_backpressure_stalls": self.backpressure_stalls,
            "gossip_pipeline_queue_depth": self.queue_depth(),
            "gossip_pipeline_soft_depth": self.soft_depth,
        }

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Test/shutdown helper: block until nothing is in flight.
        Deliberately WALL time: the pipeline is auto-disabled under an
        injected sim clock, and its workers are real threads — a virtual
        deadline would never advance while polling them."""
        from ..common.clock import WALL

        deadline = WALL.monotonic() + timeout
        while WALL.monotonic() < deadline:
            with self._lock:
                if self.inflight == 0:
                    return True
            WALL.sleep(0.005)
        return False
